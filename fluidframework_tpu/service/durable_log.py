"""DurableLog: the ordered-log interface over the native C++ op log.

Drop-in for LocalLog in LocalOrderer/LocalServer (same OrderedLogBase
machinery), but every record is persisted through native/oplog.cpp, so a
process restart resumes the pipeline from disk — the single-node
durability story the reference gets from Kafka+Mongo (SURVEY §2.9
consolidation note).

Two on-disk lanes:

- **Columnar segment streams** (default for ``deltas/*`` topics): each
  sequenced boxcar persists as ONE packed column block
  (binwire.encode_seg_block — byte for byte the FT_COLS_OPS stamp
  section) appended through the native segment store
  (``<stream>.seg<k>`` files + 32-byte seq-span index entries). Recovery
  replay decodes blocks with vectorized ``np.frombuffer`` column reads,
  and seq-range backfill (:meth:`delta_blocks`) is a binary search over
  the mmap'd index plus raw byte-range copies served to binary clients
  verbatim — zero re-encode, zero per-op materialization.
- **Record topics** (rawops, checkpoints, versions, uploads — and any
  deltas directory written before the segment store existed): the
  original length-prefixed record files. Non-columnar encodings live in
  the ``log_compat`` shim; every trip through it on the deltas lane is
  counted under the ``storage.log.legacy_json`` deprecation counter.

Subscriber positions are in-memory (the lambdas own their checkpoints,
as in the reference).
"""

from __future__ import annotations

import os
import struct
from typing import Any, Optional

import numpy as np

from ..native.oplog import NativeOpLog
from ..utils.affinity import blocking
from ..obs.metrics import tier_counters
from ..protocol import binwire
from .local_log import OrderedLogBase
from .log_compat import (  # noqa: F401  (re-exported legacy codec names)
    _TAG_ESC,
    _TAG_MSG,
    _unwrap,
    _wrap,
    abox_header_bytes,
    abox_header_from,
    decode_json_value,
    encode_json_value,
)
from .segment_store import SegmentReader

# --------------------------------------------------- binary fast path
# The split deployment's hot records are (a) a raw ArrayBoxcar on the
# rawops topic and (b) the ticketed {"abatch": SequencedArrayBatch}
# record on the deltas topic. (b) rides the columnar segment store; (a)
# packs as kind-3 below with the SAME binwire cols section the segment
# block embeds, so ONE column encode per boxcar serves the rawops
# record, the deltas block, and the broadcast splice. Kinds 1/2 remain
# as the frozen decoders (and record-topic encoders) for pre-segment
# directories. 0xFF can never begin a JSON record.

_BIN_MARK = 0xFF
_BIN_RAW_ABOX = 1   # legacy raw boxcar (JSON header + column bytes)
_BIN_ABATCH = 2     # legacy sequenced batch (record-format deltas topics)
_BIN_RAW_COLS = 3   # raw boxcar: route header + binwire cols section

#: Native-handle budget per DurableLog handle (override per instance or
#: with FLUID_LOG_FD_CAP). A sharded core owns several per-partition
#: logs plus sockets, all inside one RLIMIT_NOFILE — at ~8 handles per
#: resident doc an uncapped 10k-doc rehydration would exhaust any
#: realistic limit, so cold handles LRU-cycle under this cap instead.
LOG_FD_CAP = int(os.environ.get("FLUID_LOG_FD_CAP", "2048"))

_RAW_COLS_HDR = struct.Struct("<d")  # boxcar timestamp


def _cols_of(box) -> Optional[bytes]:
    """The boxcar's binwire column section, encoded once and memoized on
    ``wire_cols`` (network-columnar boxcars arrive with it already set);
    None when the boxcar doesn't fit the columnar format."""
    cols = box.wire_cols
    if cols is None:
        try:
            cols = binwire.encode_cols(
                box.ds_id, box.channel_id, box.kind, box.a, box.b,
                box.cseq, box.rseq, box.text, box.text_off, box.props)
        except Exception:
            return None
        box.wire_cols = cols
    return cols


def _abox_bytes(box) -> bytes:
    cached = getattr(box, "_wire_cache", None)
    if cached is not None:
        return cached
    hdr = abox_header_bytes(box)
    text = box.text.encode()
    data = b"".join((
        len(hdr).to_bytes(4, "little"), hdr,
        np.ascontiguousarray(box.kind, np.int8).tobytes(),
        np.ascontiguousarray(box.a, np.int32).tobytes(),
        np.ascontiguousarray(box.b, np.int32).tobytes(),
        np.ascontiguousarray(box.cseq, np.int32).tobytes(),
        np.ascontiguousarray(box.rseq, np.int32).tobytes(),
        np.ascontiguousarray(box.text_off, np.int32).tobytes(),
        len(text).to_bytes(4, "little"), text,
    ))
    box._wire_cache = data
    return data


def _abox_from(data: bytes, off: int):
    from .array_batch import ArrayBoxcar

    hlen = int.from_bytes(data[off:off + 4], "little")
    off += 4
    tenant, doc, client, ds, ch, ts, n, props = abox_header_from(
        data[off:off + hlen])
    off += hlen
    kind = np.frombuffer(data, np.int8, n, off); off += n
    a = np.frombuffer(data, np.int32, n, off); off += 4 * n
    b = np.frombuffer(data, np.int32, n, off); off += 4 * n
    cseq = np.frombuffer(data, np.int32, n, off); off += 4 * n
    rseq = np.frombuffer(data, np.int32, n, off); off += 4 * n
    text_off = np.frombuffer(data, np.int32, n + 1, off); off += 4 * (n + 1)
    tlen = int.from_bytes(data[off:off + 4], "little")
    off += 4
    text = data[off:off + tlen].decode()
    return ArrayBoxcar(
        tenant_id=tenant, document_id=doc, client_id=client, ds_id=ds,
        channel_id=ch, kind=kind, a=a, b=b, cseq=cseq, rseq=rseq,
        text=text, text_off=text_off, props=props, timestamp=ts)


def _u16str(s: str) -> bytes:
    b = s.encode()
    return len(b).to_bytes(2, "little") + b


def _encode_binary(value: Any) -> bytes | None:
    from .array_batch import ArrayBoxcar, SequencedArrayBatch

    t = type(value)
    if t is ArrayBoxcar:
        cols = _cols_of(value)
        if cols is not None:
            return b"".join((
                bytes((_BIN_MARK, _BIN_RAW_COLS)),
                _u16str(value.tenant_id), _u16str(value.document_id),
                _u16str(value.client_id),
                _RAW_COLS_HDR.pack(value.timestamp),
                cols,
            ))
        return bytes((_BIN_MARK, _BIN_RAW_ABOX)) + _abox_bytes(value)
    if t is dict and value.keys() == {"tenant_id", "document_id",
                                      "abatch"}:
        batch = value.get("abatch")
        # the decoder reconstructs tenant_id/document_id FROM the boxcar,
        # so the binary path is only sound when the dict's fields equal
        # the boxcar's — any other record shape (renamed key, divergent
        # routing field) must round-trip through JSON verbatim
        if type(batch) is SequencedArrayBatch \
                and value["tenant_id"] == batch.boxcar.tenant_id \
                and value["document_id"] == batch.boxcar.document_id:
            return b"".join((
                bytes((_BIN_MARK, _BIN_ABATCH)),
                struct.pack("<qdI", batch.base_seq, batch.timestamp,
                            batch.n),
                np.ascontiguousarray(batch.msns, np.int64).tobytes(),
                _abox_bytes(batch.boxcar),
            ))
    return None


def _decode_binary(data: bytes) -> Any:
    from .array_batch import ArrayBoxcar, SequencedArrayBatch

    kind = data[1]
    if kind == _BIN_RAW_ABOX:
        return _abox_from(data, 2)
    if kind == _BIN_ABATCH:
        base_seq, ts, n = struct.unpack_from("<qdI", data, 2)
        off = 2 + struct.calcsize("<qdI")
        msns = np.frombuffer(data, np.int64, n, off)
        off += 8 * n
        box = _abox_from(data, off)
        return {"tenant_id": box.tenant_id,
                "document_id": box.document_id,
                "abatch": SequencedArrayBatch(
                    boxcar=box, base_seq=base_seq, msns=msns,
                    timestamp=ts)}
    if kind == _BIN_RAW_COLS:
        off = 2
        strs = []
        for _ in range(3):
            ln = int.from_bytes(data[off:off + 2], "little")
            off += 2
            strs.append(data[off:off + ln].decode())
            off += ln
        (ts,) = _RAW_COLS_HDR.unpack_from(data, off)
        off += _RAW_COLS_HDR.size
        sc, _ = binwire._read_cols(data, off)
        return ArrayBoxcar(
            tenant_id=strs[0], document_id=strs[1], client_id=strs[2],
            ds_id=sc.ds_id, channel_id=sc.channel_id, kind=sc.kind,
            a=sc.a, b=sc.b, cseq=sc.cseq, rseq=sc.rseq, text=sc.text,
            text_off=sc.text_off, props=sc.props, timestamp=ts,
            wire_cols=sc.cols)
    raise ValueError(f"unknown binary record kind {kind}")


def _encode_value(value: Any) -> bytes:
    data = _encode_binary(value)
    if data is not None:
        return data
    return encode_json_value(value)


def _decode_value(data: bytes) -> Any:
    if data[:1] == b"\xff":
        return _decode_binary(data)
    return decode_json_value(data)


def _sanitize(topic: str) -> str:
    """Bijective topic → file-name mapping (oplog topic names allow only
    [alnum._-]). '/' becomes '.'; a literal '.' in a tenant/doc id is
    escaped first so _desanitize can invert exactly — without the escape,
    a doc named 'notes.v2' would round-trip through list_topics as
    'notes/v2' and stage backchannel records would route to a
    nonexistent doc."""
    return topic.replace("_", "__").replace(".", "_d").replace("/", ".")


def _desanitize(name: str) -> str:
    out = []
    i, n = 0, len(name)
    while i < n:
        c = name[i]
        if c == ".":
            out.append("/")
        elif c == "_" and i + 1 < n:
            nxt = name[i + 1]
            if nxt == "_":
                out.append("_")
                i += 1
            elif nxt == "d":
                out.append(".")
                i += 1
            else:
                out.append(c)
        else:
            out.append(c)
        i += 1
    return "".join(out)


def _legacy_messages(value: Any) -> list:
    """Materialize the sequenced messages a legacy deltas record holds
    (the backfill door's compat shim for SEG_JSON blocks)."""
    if not isinstance(value, dict):
        return []
    abatch = value.get("abatch")
    if abatch is not None:
        return abatch.messages()
    boxcar = value.get("boxcar")
    if boxcar is not None:
        return list(boxcar)
    msg = value.get("message")
    return [msg] if msg is not None else []


_UNSET = object()


class DurableLog(OrderedLogBase):
    """Persistent ordered topics with subscriber fan-out.

    ``readonly=True`` opens a CONSUMER-PROCESS view over a directory
    another process writes (the Kafka consumer-group role): appends are
    refused by the native layer, and :meth:`poll` tails newly flushed
    producer records into this process's subscribers. A producer makes
    its appends visible with :meth:`flush` (page cache, cheap) and
    durable with :meth:`sync` (fsync, checkpoint boundaries).

    ``segmented=False`` forces every topic onto the record lane (the
    pre-segment behavior; the bench scalar A/B rides this knob).
    ``segment_bytes`` overrides the 4 MiB segment roll threshold."""

    def __init__(self, directory: str, readonly: bool = False,
                 segmented: bool = True,
                 segment_bytes: Optional[int] = None,
                 fd_cap: Optional[int] = None):
        super().__init__()
        self.directory = directory
        self.readonly = readonly
        self._log = NativeOpLog(directory, readonly=readonly)
        self._segmented = segmented
        if segment_bytes is not None:
            self._log.seg_config(segment_bytes)
        # ~8 native handles per resident doc would blow RLIMIT_NOFILE at
        # fleet scale (a 10k-doc mass rehydration is the concrete case);
        # the native layer LRU-cycles cold handles under this cap while
        # topic metadata stays resident. 0 disables.
        self._log.fd_cap(LOG_FD_CAP if fd_cap is None else fd_cap)
        self.counters = tier_counters("storage")
        # last-record decode cache per topic, PRIMED at append: the
        # drain delivers each record to every subscriber back to back
        # (3× on the deltas topic), and in-process those deliveries
        # share the live object exactly like LocalLog — consumers treat
        # log records as immutable. Cuts per-record decodes from
        # k-subscribers to zero on the hot path.
        self._read_cache: dict[str, tuple] = {}
        # topic lengths are consulted ~4×/record by the drain machinery;
        # caching removes a ctypes round trip per query (appends and
        # refreshes keep it exact — this handle is the only writer)
        self._len_cache: dict[str, int] = {}
        self._san_cache: dict[str, str] = {}
        self._seg_route: dict[str, Optional[str]] = {}
        self._seg_last: dict[str, int] = {}  # highest indexed seq span end
        # reader LRU: each SegmentReader pins 1 fd per mmap (CPython
        # dups the fd behind mmap.mmap), so resident readers are fd
        # budget exactly like native handles — cold ones close and
        # rebuild on demand (refresh revalidates from the index, no
        # record decodes)
        from collections import OrderedDict
        cap = LOG_FD_CAP if fd_cap is None else fd_cap
        self._reader_cap = max(32, cap // 4) if cap else 0
        self._readers: "OrderedDict[str, SegmentReader]" = OrderedDict()
        self._torn_count = 0

    # ------------------------------------------------------ topic routing

    def _san(self, topic: str) -> str:
        s = self._san_cache.get(topic)
        if s is None:
            s = self._san_cache[topic] = _sanitize(topic)
        return s

    def _seg_stream(self, topic: str) -> Optional[str]:
        """Sanitized segment-stream name for ``topic``, or None when the
        topic rides the record lane (cached)."""
        s = self._seg_route.get(topic, _UNSET)
        if s is not _UNSET:
            return s
        s = None
        if self._segmented and topic.startswith("deltas/"):
            san = self._san(topic)
            # a record-format topic already on disk (a directory written
            # before the segment store) stays record-format, for reads
            # AND subsequent writes — mixing lanes would split its order
            if not os.path.exists(os.path.join(self.directory,
                                               san + ".idx")):
                s = san
        self._seg_route[topic] = s
        return s

    def segment_reader(self, topic: str) -> Optional[SegmentReader]:
        """The mmap'd reader over ``topic``'s segment stream (None for
        record-lane topics)."""
        stream = self._seg_stream(topic)
        if stream is None:
            return None
        r = self._readers.get(stream)
        if r is None:
            flush = None if self.readonly else self._log.flush
            r = self._readers[stream] = SegmentReader(
                self.directory, stream, flush=flush)
            while self._reader_cap and len(self._readers) > self._reader_cap:
                _, cold = self._readers.popitem(last=False)
                cold.close()
        else:
            self._readers.move_to_end(stream)
        return r

    # ---------------------------------------------------------- tailing

    def _refresh_one(self, topic: str) -> int:
        stream = self._seg_stream(topic)
        if stream is not None:
            n = self._log.seg_refresh(stream)
            if n == 0 and os.path.exists(
                    os.path.join(self.directory, self._san(topic)
                                 + ".idx")):
                # the producer turned out to be record-format (it opened
                # a pre-segment directory): reroute before anyone reads
                self._seg_route[topic] = None
                n = self._log.refresh(self._san(topic))
        else:
            n = self._log.refresh(self._san(topic))
        self._len_cache[topic] = n
        return n

    def poll(self) -> bool:
        """Refresh every subscribed topic from disk; mark grown topics
        dirty. Returns True when drain() has new work."""
        if self.fault_plane is not None:
            # chaos seam, read side: a consumer process resuming from a
            # stale position (lost position file, conservative restart)
            # re-reads an already-consumed window — every subscriber
            # must tolerate redelivery
            if self.fault_plane("log.poll", directory=self.directory) \
                    == "rewind":
                for topic in self._order:
                    self.rewind_subscribers(topic, 1)
        grew = False
        for topic in self._order:
            n = self._refresh_one(topic)
            if any(pos[0] < n for _, pos in self._subs.get(topic, ())):
                self._dirty[topic] = None
                grew = True
        return grew

    def list_topics(self, prefix: str = "") -> list[str]:
        """Topics present on disk (desanitized), optionally filtered by
        prefix — how a consumer process discovers per-doc topics."""
        out = set()
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            if name.endswith(".segidx"):
                topic = _desanitize(name[:-7])
            elif name.endswith(".idx"):
                topic = _desanitize(name[:-4])
            else:
                continue
            if topic.startswith(prefix):
                out.add(topic)
        return sorted(out)

    def refresh_topic(self, topic: str) -> int:
        """Refresh ONE topic from disk; returns its record count."""
        return self._refresh_one(topic)

    @blocking("mmap page-cache flush (PR 6; PR 11 made it per-batch) — bounded but off the async fast path")
    def flush(self) -> None:
        self._log.flush()

    # ------------------------------------------------- storage primitives

    def _store(self, topic: str, value: Any) -> int:
        stream = self._seg_stream(topic)
        if stream is not None:
            block, first, last, btype = self._seg_encode(topic, value)
            offset = self._log.seg_append(stream, first, last, block,
                                          btype)
            self._seg_last[topic] = last
            self.counters.inc("storage.segment.appends")
        else:
            data = _encode_value(value)
            if data[0] != _BIN_MARK and topic.startswith("deltas/"):
                self.counters.inc("storage.log.legacy_json")
            offset = self._log.append(self._san(topic), data)
        self._len_cache[topic] = offset + 1
        self._read_cache[topic] = (offset, value)
        return offset

    def _seg_encode(self, topic: str, value: Any):
        """Encode one deltas record as a segment block: columnar when it
        is the canonical abatch shape, else the legacy shim (opaque
        record encoding behind the deprecation counter)."""
        from .array_batch import SequencedArrayBatch

        if type(value) is dict and value.keys() == {"tenant_id",
                                                    "document_id",
                                                    "abatch"}:
            batch = value["abatch"]
            if type(batch) is SequencedArrayBatch:
                box = batch.boxcar
                # tenant/doc reconstruct FROM the topic on decode, so the
                # columnar block is only sound when they all agree
                if topic == "deltas/%s/%s" % (box.tenant_id,
                                              box.document_id) \
                        and value["tenant_id"] == box.tenant_id \
                        and value["document_id"] == box.document_id \
                        and "/" not in box.tenant_id:
                    cols = _cols_of(box)
                    if cols is not None:
                        block = binwire.encode_seg_block(
                            cols, box.client_id, batch.base_seq,
                            batch.msns, batch.timestamp, box.timestamp)
                        return (block, batch.base_seq, batch.last_seq,
                                binwire.SEG_COLS)
        data = _encode_value(value)
        first, last = self._record_span(topic, value)
        self.counters.inc("storage.log.legacy_json")
        return data, first, last, binwire.SEG_JSON

    def _record_span(self, topic: str, value: Any) -> tuple[int, int]:
        """Seq span a legacy record covers, for its index entry; records
        with no derivable span get an empty span at the current high
        mark (kept in range queries' superset, filtered by the shim)."""
        try:
            if isinstance(value, dict):
                abatch = value.get("abatch")
                if abatch is not None:
                    return abatch.base_seq, abatch.last_seq
                boxcar = value.get("boxcar")
                if boxcar:
                    return (boxcar[0].sequence_number,
                            boxcar[-1].sequence_number)
                msg = value.get("message")
                if msg is not None:
                    return msg.sequence_number, msg.sequence_number
        except Exception:
            pass
        last = self._seg_last.get(topic, 0)
        return last, last

    def _seg_decode(self, topic: str, payload: bytes) -> Any:
        """SEG_COLS payload → the canonical abatch record (vectorized
        frombuffer column reads — the recovery-replay decode)."""
        from .array_batch import ArrayBoxcar, SequencedArrayBatch

        box_ts, cid, base_seq, ts, sc, msns = binwire.read_seg_block(
            payload)
        _, tenant, doc = topic.split("/", 2)
        box = ArrayBoxcar(
            tenant_id=tenant, document_id=doc, client_id=cid,
            ds_id=sc.ds_id, channel_id=sc.channel_id, kind=sc.kind,
            a=sc.a, b=sc.b, cseq=sc.cseq, rseq=sc.rseq, text=sc.text,
            text_off=sc.text_off, props=sc.props, timestamp=box_ts,
            wire_cols=sc.cols)
        return {"tenant_id": tenant, "document_id": doc,
                "abatch": SequencedArrayBatch(
                    boxcar=box, base_seq=base_seq, msns=msns,
                    timestamp=ts)}

    def _load(self, topic: str, offset: int) -> Any:
        cached = self._read_cache.get(topic)
        if cached is not None and cached[0] == offset:
            return cached[1]
        stream = self._seg_stream(topic)
        if stream is not None:
            reader = self.segment_reader(topic)
            if offset >= reader.count:
                reader.refresh()
            btype, _, _, payload = reader.block(offset)
            if btype == binwire.SEG_COLS:
                value = self._seg_decode(topic, payload)
                self.counters.inc("storage.segment.decodes")
            else:
                value = _decode_value(payload)
                self.counters.inc("storage.log.legacy_json")
        else:
            value = _decode_value(self._log.read(self._san(topic), offset))
        self._read_cache[topic] = (offset, value)
        return value

    def _stored_length(self, topic: str) -> int:
        n = self._len_cache.get(topic)
        if n is not None:
            return n
        stream = self._seg_stream(topic)
        if stream is not None:
            n = self._log.seg_count(stream)
        else:
            n = self._log.length(self._san(topic))
        self._len_cache[topic] = n
        return n

    def _torn_append(self, topic: str, value: Any) -> int:
        stream = self._seg_stream(topic)
        if stream is None or self.readonly:
            return super()._torn_append(topic, value)
        # segment streams have a PHYSICAL torn representation: leave a
        # ragged half-written tail on disk (alternating between a torn
        # block and a torn index entry), then run the same
        # detect-truncate-rewrite cycle crash recovery runs. Deltas
        # records are already ticketed, so unlike the rawops torn
        # semantics the record itself must survive — a permanently
        # missing seq would stall every consumer on an unfillable gap.
        block, first, last, btype = self._seg_encode(topic, value)
        self._log.seg_tear(stream, first, last, block, btype,
                           mode=self._torn_count % 2)
        self._torn_count += 1
        self.counters.inc("storage.segment.torn")
        offset = self._log.seg_append(stream, first, last, block, btype)
        self._seg_last[topic] = last
        self.counters.inc("storage.segment.appends")
        self._len_cache[topic] = offset + 1
        self._read_cache[topic] = (offset, value)
        return offset

    def first_offset_covering(self, topic: str, seq: int) -> int:
        """Lazy cold-boot tail entry: the lowest record offset whose
        block may hold any seq' ≥ ``seq`` — one binary search over the
        mmap'd seq-span index, zero record decodes. Record-lane topics
        have no index and return 0 (the subscriber's skip absorbs the
        prefix)."""
        reader = self.segment_reader(topic)
        if reader is None:
            return 0
        reader.refresh()
        return reader.first_covering(seq)

    # ------------------------------------------------------ backfill door

    def delta_blocks(self, topic: str, from_seq: int, to_seq: int):
        """Columnar backfill: ``(payloads, legacy_msgs)`` covering every
        record with from_seq < seq < to_seq, or None when the topic
        rides the record lane (caller falls back to scriptorium).

        ``payloads`` are SEG_COLS block payloads copied straight out of
        the segment mmaps — zero decode server-side; a boundary block
        may span past the requested range, and the CLIENT trims by seq
        after decoding (binwire.seg_block_wire_body /
        read_cols_deltas). Legacy blocks materialize through the compat
        shim and come back as in-range message objects."""
        stream = self._seg_stream(topic)
        if stream is None:
            return None
        reader = self.segment_reader(topic)
        reader.refresh()
        payloads: list[bytes] = []
        legacy: list = []
        for i in reader.range_blocks(from_seq, to_seq):
            btype, _, _, payload = reader.block(i)
            if btype == binwire.SEG_COLS:
                payloads.append(payload)
            else:
                self.counters.inc("storage.log.legacy_json")
                for m in _legacy_messages(_decode_value(payload)):
                    if from_seq < m.sequence_number < to_seq:
                        legacy.append(m)
        if payloads:
            self.counters.inc("storage.backfill.byterange", len(payloads))
        return payloads, legacy

    # ------------------------------------------------------------- admin

    @blocking("msync to stable storage — the slow durability barrier, checkpoint/teardown only")
    def sync(self) -> None:
        self._log.sync()

    def close(self) -> None:
        for r in self._readers.values():
            r.close()
        self._readers.clear()
        self._log.close()
