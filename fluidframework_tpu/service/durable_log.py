"""DurableLog: the ordered-log interface over the native C++ op log.

Drop-in for LocalLog in LocalOrderer/LocalServer (same OrderedLogBase
machinery), but every record is persisted through native/oplog.cpp, so a
process restart resumes the pipeline from disk — the single-node
durability story the reference gets from Kafka+Mongo (SURVEY §2.9
consolidation note).

Values must be protocol messages or JSON-serializable structures; they
are encoded via protocol/serialization with explicit tagging, and user
dicts that happen to collide with the tag keys are escaped, so framing is
unambiguous. Subscriber positions are in-memory (the lambdas own their
checkpoints, as in the reference).
"""

from __future__ import annotations

from typing import Any

import json

from ..native.oplog import NativeOpLog
from ..protocol.serialization import message_from_dict, message_to_dict
from .local_log import OrderedLogBase

_TAG_MSG = "_msg"  # a wrapped protocol message
_TAG_ESC = "_esc"  # an escaped user dict that contained a tag key


def _wrap(value: Any) -> Any:
    """Recursively tag protocol messages / escape colliding user dicts."""
    if isinstance(value, dict):
        out = {k: _wrap(v) for k, v in value.items()}
        if _TAG_MSG in out or _TAG_ESC in out:
            return {_TAG_ESC: out}
        return out
    if isinstance(value, (list, tuple)):
        return [_wrap(v) for v in value]
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return {_TAG_MSG: message_to_dict(value)}


def _unwrap(value: Any) -> Any:
    if isinstance(value, dict):
        if _TAG_MSG in value and len(value) == 1:
            return message_from_dict(value[_TAG_MSG])
        if _TAG_ESC in value and len(value) == 1:
            return {k: _unwrap(v) for k, v in value[_TAG_ESC].items()}
        return {k: _unwrap(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_unwrap(v) for v in value]
    return value


# --------------------------------------------------- binary fast path
# The split deployment's hot records are (a) a raw ArrayBoxcar on the
# rawops topic and (b) the ticketed {"abatch": SequencedArrayBatch}
# record on the deltas topic — and (b) embeds the very boxcar object (a)
# just carried. Packing those as struct+array bytes (instead of
# wrap-recursion + b64 + json) and memoizing the boxcar's encoding on
# the object makes the second append nearly free; everything else stays
# on the frozen JSON path. 0xFF can never begin a JSON record.

_BIN_MARK = 0xFF
_BIN_RAW_ABOX = 1
_BIN_ABATCH = 2


def _abox_bytes(box) -> bytes:
    import numpy as np

    cached = getattr(box, "_wire_cache", None)
    if cached is not None:
        return cached
    hdr = json.dumps(
        [box.tenant_id, box.document_id, box.client_id, box.ds_id,
         box.channel_id, box.timestamp, int(box.n), box.props],
        separators=(",", ":")).encode()
    text = box.text.encode()
    data = b"".join((
        len(hdr).to_bytes(4, "little"), hdr,
        np.ascontiguousarray(box.kind, np.int8).tobytes(),
        np.ascontiguousarray(box.a, np.int32).tobytes(),
        np.ascontiguousarray(box.b, np.int32).tobytes(),
        np.ascontiguousarray(box.cseq, np.int32).tobytes(),
        np.ascontiguousarray(box.rseq, np.int32).tobytes(),
        np.ascontiguousarray(box.text_off, np.int32).tobytes(),
        len(text).to_bytes(4, "little"), text,
    ))
    box._wire_cache = data
    return data


def _abox_from(data: bytes, off: int):
    import numpy as np

    from .array_batch import ArrayBoxcar

    hlen = int.from_bytes(data[off:off + 4], "little")
    off += 4
    tenant, doc, client, ds, ch, ts, n, props = json.loads(
        data[off:off + hlen].decode())
    off += hlen
    kind = np.frombuffer(data, np.int8, n, off); off += n
    a = np.frombuffer(data, np.int32, n, off); off += 4 * n
    b = np.frombuffer(data, np.int32, n, off); off += 4 * n
    cseq = np.frombuffer(data, np.int32, n, off); off += 4 * n
    rseq = np.frombuffer(data, np.int32, n, off); off += 4 * n
    text_off = np.frombuffer(data, np.int32, n + 1, off); off += 4 * (n + 1)
    tlen = int.from_bytes(data[off:off + 4], "little")
    off += 4
    text = data[off:off + tlen].decode()
    return ArrayBoxcar(
        tenant_id=tenant, document_id=doc, client_id=client, ds_id=ds,
        channel_id=ch, kind=kind, a=a, b=b, cseq=cseq, rseq=rseq,
        text=text, text_off=text_off, props=props, timestamp=ts)


def _encode_binary(value: Any) -> bytes | None:
    from .array_batch import ArrayBoxcar, SequencedArrayBatch

    t = type(value)
    if t is ArrayBoxcar:
        return bytes((_BIN_MARK, _BIN_RAW_ABOX)) + _abox_bytes(value)
    if t is dict and value.keys() == {"tenant_id", "document_id",
                                      "abatch"}:
        batch = value.get("abatch")
        # the decoder reconstructs tenant_id/document_id FROM the boxcar,
        # so the binary path is only sound when the dict's fields equal
        # the boxcar's — any other record shape (renamed key, divergent
        # routing field) must round-trip through JSON verbatim
        if type(batch) is SequencedArrayBatch \
                and value["tenant_id"] == batch.boxcar.tenant_id \
                and value["document_id"] == batch.boxcar.document_id:
            import struct

            import numpy as np

            return b"".join((
                bytes((_BIN_MARK, _BIN_ABATCH)),
                struct.pack("<qdI", batch.base_seq, batch.timestamp,
                            batch.n),
                np.ascontiguousarray(batch.msns, np.int64).tobytes(),
                _abox_bytes(batch.boxcar),
            ))
    return None


def _decode_binary(data: bytes) -> Any:
    import struct

    import numpy as np

    from .array_batch import SequencedArrayBatch

    kind = data[1]
    if kind == _BIN_RAW_ABOX:
        return _abox_from(data, 2)
    if kind == _BIN_ABATCH:
        base_seq, ts, n = struct.unpack_from("<qdI", data, 2)
        off = 2 + struct.calcsize("<qdI")
        msns = np.frombuffer(data, np.int64, n, off)
        off += 8 * n
        box = _abox_from(data, off)
        return {"tenant_id": box.tenant_id,
                "document_id": box.document_id,
                "abatch": SequencedArrayBatch(
                    boxcar=box, base_seq=base_seq, msns=msns,
                    timestamp=ts)}
    raise ValueError(f"unknown binary record kind {kind}")


def _encode_value(value: Any) -> bytes:
    data = _encode_binary(value)
    if data is not None:
        return data
    return json.dumps(_wrap(value), separators=(",", ":")).encode()


def _decode_value(data: bytes) -> Any:
    if data[:1] == b"\xff":
        return _decode_binary(data)
    return _unwrap(json.loads(data.decode()))


def _sanitize(topic: str) -> str:
    """Bijective topic → file-name mapping (oplog topic names allow only
    [alnum._-]). '/' becomes '.'; a literal '.' in a tenant/doc id is
    escaped first so _desanitize can invert exactly — without the escape,
    a doc named 'notes.v2' would round-trip through list_topics as
    'notes/v2' and stage backchannel records would route to a
    nonexistent doc."""
    return topic.replace("_", "__").replace(".", "_d").replace("/", ".")


def _desanitize(name: str) -> str:
    out = []
    i, n = 0, len(name)
    while i < n:
        c = name[i]
        if c == ".":
            out.append("/")
        elif c == "_" and i + 1 < n:
            nxt = name[i + 1]
            if nxt == "_":
                out.append("_")
                i += 1
            elif nxt == "d":
                out.append(".")
                i += 1
            else:
                out.append(c)
        else:
            out.append(c)
        i += 1
    return "".join(out)


class DurableLog(OrderedLogBase):
    """Persistent ordered topics with subscriber fan-out.

    ``readonly=True`` opens a CONSUMER-PROCESS view over a directory
    another process writes (the Kafka consumer-group role): appends are
    refused by the native layer, and :meth:`poll` tails newly flushed
    producer records into this process's subscribers. A producer makes
    its appends visible with :meth:`flush` (page cache, cheap) and
    durable with :meth:`sync` (fsync, checkpoint boundaries)."""

    def __init__(self, directory: str, readonly: bool = False):
        super().__init__()
        self.directory = directory
        self._log = NativeOpLog(directory, readonly=readonly)
        # last-record decode cache per topic, PRIMED at append: the
        # drain delivers each record to every subscriber back to back
        # (3× on the deltas topic), and in-process those deliveries
        # share the live object exactly like LocalLog — consumers treat
        # log records as immutable. Cuts per-record JSON decodes from
        # k-subscribers to zero on the hot path.
        self._read_cache: dict[str, tuple] = {}

    def poll(self) -> bool:
        """Refresh every subscribed topic from disk; mark grown topics
        dirty. Returns True when drain() has new work."""
        if self.fault_plane is not None:
            # chaos seam, read side: a consumer process resuming from a
            # stale position (lost position file, conservative restart)
            # re-reads an already-consumed window — every subscriber
            # must tolerate redelivery
            if self.fault_plane("log.poll", directory=self.directory) \
                    == "rewind":
                for topic in self._order:
                    self.rewind_subscribers(topic, 1)
        grew = False
        for topic in self._order:
            n = self._log.refresh(_sanitize(topic))
            if any(pos[0] < n for _, pos in self._subs.get(topic, ())):
                self._dirty[topic] = None
                grew = True
        return grew

    def list_topics(self, prefix: str = "") -> list[str]:
        """Topics present on disk (desanitized), optionally filtered by
        prefix — how a consumer process discovers per-doc topics."""
        import os

        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            if name.endswith(".idx"):
                topic = _desanitize(name[:-4])
                if topic.startswith(prefix):
                    out.append(topic)
        return sorted(out)

    def refresh_topic(self, topic: str) -> int:
        """Refresh ONE topic from disk; returns its record count."""
        return self._log.refresh(_sanitize(topic))

    def flush(self) -> None:
        self._log.flush()

    def _store(self, topic: str, value: Any) -> int:
        offset = self._log.append(_sanitize(topic), _encode_value(value))
        self._read_cache[topic] = (offset, value)
        return offset

    def _load(self, topic: str, offset: int) -> Any:
        cached = self._read_cache.get(topic)
        if cached is not None and cached[0] == offset:
            return cached[1]
        value = _decode_value(self._log.read(_sanitize(topic), offset))
        self._read_cache[topic] = (offset, value)
        return value

    def _stored_length(self, topic: str) -> int:
        return self._log.length(_sanitize(topic))

    def sync(self) -> None:
        self._log.sync()

    def close(self) -> None:
        self._log.close()
