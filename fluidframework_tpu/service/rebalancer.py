"""Self-driving placement: the load→decision→migration loop.

Ref: lambdas-driver/kafka-service/partitionManager.ts (the reference
scales by rebalancing Kafka partitions across consumer-group members);
here the same loop over our strictly richer ingredients — the SLO
engine *sees* latency burn, the migration engine *moves* a partition
with a ~3.4 ms blip, and this module decides WHEN and WHERE:

- **heat signal** — every admitted submit records per-partition ops and
  staged bytes into the windowed metrics registry
  (``placement.heat.*``, exact per-bucket sums — no reservoir
  sampling loss). :func:`read_local_heat` folds the last
  ``HEAT_WINDOW_S`` seconds into per-partition rates;
  :func:`collect_fleet_heat` fans the ``admin_core_heat`` RPC across
  the epoch table's membership so every core prices the whole fleet.
- **planner** — :func:`plan_rebalance`, a pure function: heat-aware
  greedy bin-packing (move the part that best halves the hottest→
  coldest gap) with three hysteresis gates so a borderline doc never
  flaps: per-partition **dwell** time, per-tick migration **budget**,
  and an **improvement threshold** (skewed-enough-to-bother, halved
  while the SLO engine is shedding — latency burn buys urgency).
  Deterministic under permuted input: every choice is a total-order
  ``min``/``max`` with explicit tie keys.
- **daemon** — :class:`Rebalancer`, an SLO-engine-shaped ticker thread
  per core. Each core plans only moves SOURCED from itself
  (``only_source``): decisions need no global lock because a migration
  is only actuated by the partition's owner, one at a time, through
  the full seal→fence→checkpoint→lease-transfer→adopt protocol.
- **elastic membership** — a joining core registers in the epoch
  table's ``cores`` section maximally cold and the planner drains load
  onto it within budget; ``admin placement drain CORE`` marks it
  draining and every partition is migrated away (dwell/threshold
  exempt — evacuation is not an optimization), then the core marks
  itself ``drained`` and can decommission.

Unreachable peers (dead ``admin_core_heat`` dial) are excluded from
the tick's membership view, so a crashed core is never chosen as a
migration target — the heat scrape doubles as a liveness probe.
"""

from __future__ import annotations

import inspect
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Iterable, Optional

from ..obs import get_journal, get_registry
from ..utils.affinity import blocking, ticker_thread
from .placement_plane import (
    CORE_ACTIVE,
    CORE_DRAINED,
    CORE_DRAINING,
    admin_rpc,
    placement_counters,
)

#: locked heat family (fluidlint LOCKED_FAMILIES): per-partition windowed
#: series, labeled ``part=<k>``
HEAT_OPS = "placement.heat.ops"
HEAT_BYTES = "placement.heat.bytes"

#: how far back a heat read looks; also the rate denominator
HEAT_WINDOW_S = 10.0


@dataclass(frozen=True)
class PartHeat:
    """Windowed per-partition load: ops/s plus staged bytes/s."""

    ops: float = 0.0
    bytes: float = 0.0

    @property
    def load(self) -> float:
        # one scalar for packing: an op costs ~1 KiB of staging in the
        # fleet benches, so bytes are discounted to op-equivalents
        return self.ops + self.bytes / 1024.0


_ZERO = PartHeat()


@dataclass(frozen=True)
class Move:
    k: int
    src: str
    dst: str
    dst_addr: str
    load: float


@dataclass(frozen=True)
class Plan:
    moves: tuple = ()
    suppressed_hysteresis: int = 0
    suppressed_budget: int = 0
    spread_before: float = 0.0
    spread_after: float = 0.0

    def to_dict(self) -> dict:
        return {
            "moves": [{"k": m.k, "src": m.src, "dst": m.dst,
                       "load": round(m.load, 3)} for m in self.moves],
            "suppressed_hysteresis": self.suppressed_hysteresis,
            "suppressed_budget": self.suppressed_budget,
            "spread_before": round(self.spread_before, 3),
            "spread_after": round(self.spread_after, 3),
        }


def plan_rebalance(heat: dict, owners: dict, cores: dict,
                   last_moved: dict, now: float, *,
                   dwell_s: float = 10.0, budget: int = 2,
                   improvement: float = 0.25, slo_hot: bool = False,
                   only_source: Optional[str] = None) -> Plan:
    """Pure planner: which partitions move where, this tick.

    ``heat`` is ``{k: PartHeat}``, ``owners`` is ``{k: owner}`` (the
    epoch table's parts), ``cores`` is the membership view ``{owner:
    {"addr", "state"}}`` ALREADY filtered to reachable members,
    ``last_moved`` is ``{k: monotonic_t}``. Deterministic: permuting
    dict insertion order cannot change the plan (every pick is a
    total-order min/max).

    Draining sources evacuate first and are exempt from dwell and the
    improvement threshold (but not the budget). Active sources move a
    partition only when the hottest→coldest gap exceeds
    ``improvement × mean`` (halved under ``slo_hot``), the candidate
    strictly narrows that gap, and its dwell clock has expired.

    Locality tiebreak (multi-host fleets): cores rows may carry a
    ``host`` group id; among equally-loaded targets the planner prefers
    one in the SOURCE's host group, so a cross-host hop (and its
    log-shipping handoff) is paid only when load demands it. Single-host
    fleets have no ``host`` keys — every target ties on locality and
    the historical pick order is unchanged.
    """
    hostmap = {o: row.get("host") for o, row in cores.items()}
    active = sorted(o for o, row in cores.items()
                    if row.get("state", CORE_ACTIVE) == CORE_ACTIVE)
    draining = sorted(o for o, row in cores.items()
                      if row.get("state") == CORE_DRAINING)
    loads = {o: 0.0 for o in cores}
    placement = {}
    for k, o in owners.items():
        if o in loads:
            placement[int(k)] = o
            loads[o] += heat.get(int(k), _ZERO).load
    thr = improvement * (0.5 if slo_hot else 1.0)

    def spread() -> float:
        vals = [loads[o] for o in active]
        return max(vals) - min(vals) if len(vals) >= 2 else 0.0

    def pick():
        """One best move given the working placement, or ``(None,
        n_dwell_blocked)`` when hysteresis is the only thing standing
        between the planner and a move."""
        if only_source is not None:
            srcs = [only_source] if only_source in cores else []
        else:
            srcs = draining + sorted(active,
                                     key=lambda o: (-loads[o], o))
        for src in srcs:
            state = cores[src].get("state", CORE_ACTIVE)
            parts = sorted(k for k, o in placement.items() if o == src)
            targets = [o for o in active if o != src]
            if not targets:
                continue
            dst = min(targets, key=lambda o: (
                loads[o],
                0 if hostmap.get(o) == hostmap.get(src) else 1,
                o))
            if state in (CORE_DRAINING, CORE_DRAINED):
                if not parts:
                    continue
                # evacuation: hottest part first (ties → lowest k), no
                # dwell/threshold gate — the operator already decided
                k = max(parts, key=lambda k: (heat.get(k, _ZERO).load,
                                              -k))
                return (Move(k, src, dst, cores[dst]["addr"],
                             heat.get(k, _ZERO).load), 0)
            if state != CORE_ACTIVE:
                continue
            diff = loads[src] - loads[dst]
            mean = sum(loads[o] for o in active) / len(active)
            if diff <= 0 or diff <= thr * mean:
                continue
            eligible, blocked = [], 0
            for k in parts:
                ld = heat.get(k, _ZERO).load
                if ld <= 0.0:
                    continue
                if now - last_moved.get(k, float("-inf")) < dwell_s:
                    blocked += 1
                    continue
                nd = abs(diff - 2.0 * ld)
                if nd < diff:  # strictly narrows the gap, never flips it
                    eligible.append((nd, k, ld))
            if not eligible:
                if blocked:
                    return (None, blocked)
                continue
            nd, k, ld = min(eligible)
            return (Move(k, src, dst, cores[dst]["addr"], ld), 0)
        return (None, 0)

    spread_before = spread()
    moves: list = []
    suppressed_hysteresis = 0
    suppressed_budget = 0
    while len(moves) < max(0, budget):
        mv, blocked = pick()
        suppressed_hysteresis += blocked
        if mv is None:
            break
        moves.append(mv)
        placement[mv.k] = mv.dst
        loads[mv.src] -= mv.load
        loads[mv.dst] += mv.load
    if len(moves) == budget and budget > 0:
        # one probe past the budget: a move the planner WOULD make but
        # for the budget gate is the flap-control signal operators watch
        mv, _ = pick()
        if mv is not None:
            suppressed_budget += 1
    return Plan(moves=tuple(moves),
                suppressed_hysteresis=suppressed_hysteresis,
                suppressed_budget=suppressed_budget,
                spread_before=spread_before, spread_after=spread())


# ------------------------------------------------------------------ heat

def read_local_heat(parts: Iterable[int], now: Optional[float] = None,
                    registry=None) -> dict:
    """Fold the registry's windowed ``placement.heat.*`` series into
    ``{k: PartHeat}`` rates for this process's partitions. Cold owned
    partitions appear with zero heat — a draining core must evacuate
    idle partitions too, so absence is not an option."""
    reg = registry if registry is not None else get_registry()
    ops = reg.window_sums_by(HEAT_OPS, "part", now=now,
                             window_s=HEAT_WINDOW_S)
    byts = reg.window_sums_by(HEAT_BYTES, "part", now=now,
                              window_s=HEAT_WINDOW_S)
    out = {}
    for k in parts:
        out[int(k)] = PartHeat(
            ops=ops.get(str(k), 0.0) / HEAT_WINDOW_S,
            bytes=byts.get(str(k), 0.0) / HEAT_WINDOW_S)
    return out


@blocking("fleet-wide heat fan-out: concurrent per-peer dials joined "
          "on ONE shared deadline — runs on the rebalancer ticker")
def collect_fleet_heat(table_rec: dict, self_owner: str,
                       self_heat: dict, secret: Optional[str] = None,
                       timeout: float = 5.0) -> tuple:
    """Fan ``admin_core_heat`` across the membership and merge with the
    local read. Returns ``(heat, reachable)``; a peer whose dial fails
    is left OUT of ``reachable``, so the planner never targets a core
    that cannot answer a one-frame RPC.

    Dials run CONCURRENTLY (one daemon thread each) against a shared
    deadline: one wedged peer costs the scrape ``timeout`` seconds
    total, not ``timeout × peers`` — with 16 cores a single dead host
    group used to stall the tick for over a minute. A dial still
    in flight at the deadline is counted
    (``placement.heat.scrape_timeouts``) and its owner treated exactly
    like a refused dial: out of ``reachable``, never a target."""
    heat = dict(self_heat)
    reachable = {self_owner}
    dials = []
    for owner, row in sorted(table_rec.get("cores", {}).items()):
        if owner == self_owner:
            continue
        if row.get("state") == CORE_DRAINED:
            reachable.add(owner)  # owns nothing; no dial needed
            continue
        dials.append((owner, row))
    if not dials:
        return heat, reachable
    replies: dict = {}

    def dial(owner: str, row: dict) -> None:
        host_s, _, port_s = row.get("addr", "").rpartition(":")
        frame = {"t": "admin_core_heat"}
        if secret:
            frame["secret"] = secret
        try:
            replies[owner] = admin_rpc(host_s or "127.0.0.1",
                                       int(port_s), frame,
                                       timeout=timeout)
        except (OSError, ValueError, RuntimeError):
            pass  # unreachable: absent from replies

    threads = [threading.Thread(target=dial, args=d, daemon=True,
                                name=f"heat-scrape-{d[0]}")
               for d in dials]
    for t in threads:
        t.start()
    deadline = time.monotonic() + timeout
    for t in threads:
        t.join(max(0.0, deadline - time.monotonic()))
    timeouts = 0
    for (owner, _row), t in zip(dials, threads):
        if t.is_alive():
            timeouts += 1  # abandoned: the daemon thread dies unheard
            continue
        reply = replies.get(owner)
        if reply is None:
            continue
        reachable.add(owner)
        for ks, h in reply.get("parts", {}).items():
            heat[int(ks)] = PartHeat(ops=float(h.get("ops", 0.0)),
                                     bytes=float(h.get("bytes", 0.0)))
    if timeouts:
        placement_counters().inc("placement.heat.scrape_timeouts",
                                 timeouts)
    return heat, reachable


@blocking("per-peer admin_rpc dial with a multi-second timeout — fleet fan-out must run on a ticker or an executor, never the loop")
def peer_tier_snapshots(table_rec: dict, self_owner: str, tier: str,
                        secret: Optional[str] = None,
                        timeout: float = 5.0) -> list:
    """Fetch ``tier_snapshot(tier)`` from every reachable peer core
    (``admin_tier_snapshot``) — the fleet-total half of
    ``obs.sum_counter_snapshots``. Unreachable peers are skipped, not
    fatal: a fleet sum is an observability read, not a correctness
    input."""
    snaps = []
    for owner, row in sorted(table_rec.get("cores", {}).items()):
        if owner == self_owner:
            continue
        host_s, _, port_s = row.get("addr", "").rpartition(":")
        frame = {"t": "admin_tier_snapshot", "tier": tier}
        if secret:
            frame["secret"] = secret
        try:
            reply = admin_rpc(host_s or "127.0.0.1", int(port_s),
                              frame, timeout=timeout)
        except (OSError, ValueError, RuntimeError):
            continue
        snaps.append(reply.get("counters", {}))
    return snaps


# ---------------------------------------------------------------- daemon

class Rebalancer:
    """Per-core rebalancing daemon (SLO-engine-shaped ticker thread).

    Each tick: refresh the dwell clock from epoch-table bumps, gather
    fleet heat, plan moves sourced from THIS core only, actuate them
    one at a time through ``MigrationEngine.migrate``, and — when this
    core is draining and owns nothing — mark it ``drained``.

    ``heat_reader(owners, cores, now) -> (heat, reachable)`` and
    ``actuate(k, target_addr)`` are injectable seams: the front end
    routes actuation through a loopback ``admin_migrate_part`` RPC so
    the migration runs on the event loop (the single-threaded
    no-two-writers guarantee), while chaos/tests drive in-proc engines
    and frozen clocks. :meth:`tick` takes an explicit ``now`` for
    deterministic hysteresis tests.
    """

    def __init__(self, host, engine, *, slo_engine=None,
                 tick_s: float = 0.5, dwell_s: float = 10.0,
                 budget: int = 2, improvement: float = 0.25,
                 cooldown_s: Optional[float] = None,
                 heat_reader: Optional[Callable] = None,
                 actuate: Optional[Callable] = None,
                 secret: Optional[str] = None, registry=None,
                 counters=None, journal=None):
        self.host = host
        self.engine = engine
        self.slo_engine = slo_engine
        self.journal = journal if journal is not None else get_journal()
        # injected actuate seams predate the journal-cause thread; only
        # pass cause= to ones that declare it (or **kwargs)
        self._actuate_cause_ok = False
        if actuate is not None:
            try:
                params = inspect.signature(actuate).parameters
                self._actuate_cause_ok = (
                    "cause" in params
                    or any(p.kind is p.VAR_KEYWORD
                           for p in params.values()))
            except (TypeError, ValueError):
                pass
        self.tick_s = float(tick_s)
        self.dwell_s = float(dwell_s)
        self.cooldown_s = (float(cooldown_s) if cooldown_s is not None
                           else self.dwell_s)
        self.budget = int(budget)
        self.improvement = float(improvement)
        self._heat_reader = heat_reader
        self._actuate_fn = actuate
        self._secret = secret
        self._registry = registry
        self.counters = (counters if counters is not None
                         else placement_counters())
        self.last_moved: dict = {}
        self._last_issued: Optional[float] = None
        self._part_epochs: dict = {}
        self.history: deque = deque(maxlen=256)
        self.last_plan: Optional[Plan] = None
        self.last_error: Optional[str] = None
        self._drained_marked = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- loop

    def start(self) -> "Rebalancer":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="rebalancer", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    @ticker_thread("rebalancer")
    def _run(self) -> None:
        while not self._stop.wait(self.tick_s):
            try:
                self.tick()
            except Exception as e:  # keep ticking; surface via status()
                self.last_error = f"{type(e).__name__}: {e}"

    # ------------------------------------------------------------- tick

    def tick(self, now: Optional[float] = None) -> Plan:
        now = time.monotonic() if now is None else now
        c = self.counters
        c.inc("placement.rebalance.ticks")
        table = self.host.table
        rec = table.read()
        owners = {int(k): p["owner"]
                  for k, p in rec.get("parts", {}).items()}
        # dwell clock: an epoch bump on k means SOMEONE moved/claimed it
        # (covers moves issued by peer cores — the table is the shared
        # clock, no cross-core gossip needed). First sighting is a
        # baseline, not a move.
        for ks, p in rec.get("parts", {}).items():
            k, e = int(ks), p["epoch"]
            prev = self._part_epochs.get(k)
            if prev is not None and e != prev:
                self.last_moved[k] = now
            self._part_epochs[k] = e
        # source cool-down: the windowed heat signal LAGS a migration —
        # the target's window starts empty, so for up to a window this
        # core still looks like the hot one. Re-planning inside that
        # lag mass-drains the source and then ping-pongs the whole set
        # back. After issuing a move, hold off further balance planning
        # until the signal has had a cool-down to re-converge. Draining
        # is exempt: evacuation ignores heat comparisons entirely.
        if (self._last_issued is not None and self.cooldown_s > 0
                and now - self._last_issued < self.cooldown_s
                and not getattr(self.host, "draining", False)):
            # keep last_plan: the admin CLI should show the real plan,
            # not the cool-down's deliberate no-op
            return Plan(moves=(), suppressed_hysteresis=0,
                        suppressed_budget=0,
                        spread_before=0.0, spread_after=0.0)
        if self._heat_reader is not None:
            heat, reachable = self._heat_reader(
                owners, rec.get("cores", {}), now)
        else:
            self_heat = read_local_heat(
                list(self.host.servers), now=now,
                registry=self._registry)
            heat, reachable = collect_fleet_heat(
                rec, self.host.owner_id, self_heat,
                secret=self._secret)
        cores = {o: row for o, row in rec.get("cores", {}).items()
                 if o in reachable}
        slo_hot = bool(self.slo_engine is not None
                       and self.slo_engine.shed_signal)
        plan = plan_rebalance(
            heat, owners, cores, self.last_moved, now,
            dwell_s=self.dwell_s, budget=self.budget,
            improvement=self.improvement, slo_hot=slo_hot,
            only_source=self.host.owner_id)
        self.last_plan = plan
        jr = self.journal
        plan_id = None
        if plan.moves or plan.suppressed_hysteresis \
                or plan.suppressed_budget:
            # decision-time heat snapshot: the journal answers "what did
            # the planner SEE", which the live metrics can't once the
            # window rolls (bounded: hottest 16 partitions)
            hot = sorted(heat.items(), key=lambda kv: -kv[1].load)[:16]
            snapshot = {str(k): round(h.load, 2) for k, h in hot}
            if plan.moves:
                plan_id = jr.emit(
                    "rebalance.plan",
                    moves=[{"k": m.k, "src": m.src, "dst": m.dst,
                            "load": round(m.load, 3)}
                           for m in plan.moves],
                    spread_before=round(plan.spread_before, 3),
                    spread_after=round(plan.spread_after, 3),
                    slo_hot=slo_hot, heat=snapshot)
            if plan.suppressed_hysteresis or plan.suppressed_budget:
                reasons = []
                if plan.suppressed_hysteresis:
                    reasons.append("hysteresis")
                if plan.suppressed_budget:
                    reasons.append("budget")
                jr.emit("rebalance.suppressed",
                        reason="+".join(reasons),
                        hysteresis=plan.suppressed_hysteresis,
                        budget=plan.suppressed_budget,
                        slo_hot=slo_hot, heat=snapshot)
        if plan.moves:
            c.inc("placement.rebalance.plans")
        if plan.suppressed_hysteresis:
            c.inc("placement.rebalance.suppressed_hysteresis",
                  plan.suppressed_hysteresis)
        if plan.suppressed_budget:
            c.inc("placement.rebalance.suppressed_budget",
                  plan.suppressed_budget)
        for mv in plan.moves:
            act_id = jr.emit("rebalance.actuate", cause=plan_id,
                             part=mv.k, src=mv.src, dst=mv.dst,
                             load=round(mv.load, 3))
            try:
                if self._actuate_fn is not None:
                    if self._actuate_cause_ok:
                        self._actuate_fn(mv.k, mv.dst_addr, cause=act_id)
                    else:
                        self._actuate_fn(mv.k, mv.dst_addr)
                else:
                    self.engine.migrate(mv.k, mv.dst_addr, cause=act_id)
            except Exception as e:
                self.last_error = f"{type(e).__name__}: {e}"
                break
            self.last_moved[mv.k] = now
            self._last_issued = now
            self.history.append((now, mv.k, mv.src, mv.dst))
            c.inc("placement.rebalance.migrations_issued")
        if (getattr(self.host, "draining", False)
                and not self.host.servers and not self._drained_marked):
            if table.core_state(self.host.owner_id) == CORE_DRAINING:
                table.set_core_state(self.host.owner_id, CORE_DRAINED)
            self._drained_marked = True
        return plan

    # ----------------------------------------------------------- status

    def flap_count(self) -> int:
        """Re-migrations of the same partition inside its dwell window —
        the bench's flap-free acceptance gate reads this."""
        last: dict = {}
        flaps = 0
        for (t, k, _src, _dst) in self.history:
            if k in last and t - last[k] < self.dwell_s:
                flaps += 1
            last[k] = t
        return flaps

    def status(self) -> dict:
        return {
            "armed": True,
            "owner": self.host.owner_id,
            "draining": bool(getattr(self.host, "draining", False)),
            "drained": self._drained_marked,
            "tick_s": self.tick_s,
            "dwell_s": self.dwell_s,
            "cooldown_s": self.cooldown_s,
            "budget": self.budget,
            "improvement": self.improvement,
            "flaps": self.flap_count(),
            "last_error": self.last_error,
            "last_plan": (self.last_plan.to_dict()
                          if self.last_plan is not None else None),
            "history": [{"t": round(t, 3), "k": k, "src": s, "dst": d}
                        for (t, k, s, d) in list(self.history)[-16:]],
        }
