"""Service-generated summaries from TPU device state.

Ref: scribe's writeServiceSummary (scribe/summaryWriter.ts:226) — the
reference's server can persist a service summary without any client
summarizer, but must REPLAY the op log in JS to get content. Here the
TpuDocumentApplier already holds every doc's converged merge-tree on
device, so a service summary is a decode + upload: the scribe-replay
batch pass of BASELINE config 5, productized.

Scope (by design): the device models merge-tree channels. Documents
whose data stores hold ONLY device-modeled channels get full service
summaries; anything else must keep client summaries — the summarizer
refuses rather than writing a summary that would boot clients into
truncated state.
"""

from __future__ import annotations

from typing import Optional

from ..driver.local import LocalStorage
from .core import summary_versions_collection

DS_ID = "default"
TEXT_CHANNEL = "text"


class ServiceSummarizer:
    """Writes acked summaries straight from the applier's device state."""

    def __init__(self, server, applier, ds_id: str = DS_ID,
                 channel_id: str = TEXT_CHANNEL):
        self.server = server
        self.applier = applier
        self.ds_id = ds_id
        self.channel_id = channel_id
        self.summaries_written = 0

    def summarize_doc(self, tenant_id: str, document_id: str) -> str:
        """Decode the doc from the device, compose a bootable container
        summary with scribe's protocol replica, upload, and ack it
        (scribe itself is the validator — a service summary commits
        directly, the writeServiceSummary contract)."""
        orderer = self.server._get_orderer(tenant_id, document_id)
        scribe = orderer.scribe
        replica = self.applier.get_tree(tenant_id, document_id)
        summary = {
            "protocol": scribe.protocol.snapshot(),
            "runtime": {
                "dataStores": {
                    self.ds_id: {
                        "pkg": "default",
                        "snapshot": {
                            "channels": {
                                self.channel_id: {
                                    "type": "shared-string",
                                    "snapshot": {
                                        "mergetree": replica.snapshot(),
                                        "intervals": {},
                                    },
                                },
                            }
                        },
                    }
                }
            },
            "sequence_number": scribe.protocol.sequence_number,
        }
        storage = LocalStorage(self.server, tenant_id, document_id)
        version_id = storage.upload_summary(
            summary, parent=scribe.last_summary_head)
        # the service is its own validator: flip the ref directly
        col = summary_versions_collection(tenant_id, document_id)
        version = self.server.db.find_one(col, version_id)
        self.server.db.upsert(col, version_id, dict(version, acked=True))
        scribe.last_summary_head = version_id
        self.summaries_written += 1
        return version_id

    def summarize_all(self, tenant_id: str, documents: list[str],
                      min_seq: Optional[int] = None) -> int:
        """The batch pass (BASELINE config 5): one device fence, then a
        decode+upload per doc. Returns the number summarized."""
        self.applier.finalize()  # one fence for the whole batch
        n = 0
        for doc in documents:
            orderer = self.server._get_orderer(tenant_id, doc)
            if min_seq is not None and \
                    orderer.deli.sequence_number < min_seq:
                continue
            self.summarize_doc(tenant_id, doc)
            n += 1
        return n
