"""Service-generated summaries from TPU device state.

Ref: scribe's writeServiceSummary (scribe/summaryWriter.ts:226) — the
reference's server can persist a service summary without any client
summarizer, but must REPLAY the op log in JS to get content. Here the
TpuDocumentApplier already holds every doc's converged merge-tree on
device, so a service summary is a decode + upload: the scribe-replay
batch pass of BASELINE config 5, productized.

Scope (by design): the device models merge-tree channels. Documents
whose data stores hold ONLY device-modeled channels get full service
summaries; anything else must keep client summaries — the summarizer
refuses rather than writing a summary that would boot clients into
truncated state.
"""

from __future__ import annotations

from typing import Optional


DS_ID = "default"
TEXT_CHANNEL = "text"


class ServiceSummarizer:
    """Writes acked summaries straight from the applier's device state."""

    def __init__(self, server, applier, ds_id: str = DS_ID,
                 channel_id: str = TEXT_CHANNEL):
        self.server = server
        self.applier = applier
        self.ds_id = ds_id
        self.channel_id = channel_id
        self.summaries_written = 0
        self.refusals: list[tuple[str, str, str]] = []

    def summarize_doc(self, tenant_id: str, document_id: str) -> str:
        """Decode the doc from the device, compose a bootable container
        summary with scribe's protocol replica, upload, and ack it
        (scribe itself is the validator — a service summary commits
        directly, the writeServiceSummary contract)."""
        orderer = self.server._get_orderer(tenant_id, document_id)
        scribe = orderer.scribe
        pkg = self._check_summarizable(tenant_id, document_id, orderer)
        replica = self.applier.get_tree(tenant_id, document_id)
        summary = {
            "protocol": scribe.protocol.snapshot(),
            "runtime": {
                "dataStores": {
                    self.ds_id: {
                        "pkg": pkg,
                        "snapshot": {
                            "channels": {
                                self.channel_id: {
                                    "type": "shared-string",
                                    "snapshot": {
                                        "mergetree": replica.snapshot(),
                                        "intervals": {},
                                    },
                                },
                            }
                        },
                    }
                }
            },
            "sequence_number": scribe.protocol.sequence_number,
        }
        storage = self.server.storage(tenant_id, document_id)
        version_id = storage.upload_summary(
            summary, parent=scribe.last_summary_head)
        # the service is its own validator, but must still commit through
        # the scribe's ref-update path so the version reaches the durable
        # versions topic (survives process death) and retention advances
        scribe.commit_version(version_id, scribe.protocol.sequence_number)
        # the gate pass proved full coverage — anchor the slot so the doc
        # stays summarizable after this commit's own retention truncation
        self.applier.mark_anchored(tenant_id, document_id)
        self.summaries_written += 1
        return version_id

    def _check_summarizable(self, tenant_id: str, document_id: str,
                            orderer) -> str:
        """The refusal gate (module docstring contract). Committing a
        service summary advances retention past scribe's seq, so anything
        the summary does not contain must provably not exist:

        - the applier must not LAG the stream (its state is the content);
        - the doc must hold ONLY the device-modeled data store/channel —
          foreign chanops truncated from the log while absent from the
          summary would be lost permanently;
        - the applier's coverage must be PROVEN complete: either anchored
          (checkpoint restore / authoritative replay / an earlier gate
          pass) or, with the log untruncated, ingested from the doc's
          first channel op. A max-seq check alone would admit an applier
          fed only the post-truncation tail and drop the prefix.
        - when retention already truncated a prefix, the PRIOR acked
          summary must not carry foreign content the stream no longer
          shows.

        Returns the data store's pkg (from its attach op, or the prior
        summary) so the new summary boots the same code."""
        from ..protocol.messages import MessageType

        base = orderer.scriptorium.retained_base(tenant_id, document_id)
        applied = self.applier.applied_seq(tenant_id, document_id)
        anchored = self.applier.is_anchored(tenant_id, document_id)
        if base > 0 and not anchored:
            raise RuntimeError(
                f"applier coverage for {tenant_id}/{document_id} is not "
                f"anchored and the log is truncated below seq {base}: "
                "the prefix is not provably in the device state")
        pkg = "default"
        first_channel_seq = 0
        last_channel_seq = 0
        # restart-window check: a checkpoint-restored anchor is only valid
        # if NO channel op was sequenced between the checkpoint and the
        # point the feed resumed — such ops are in the log but not in the
        # restored device state
        gap = self.applier.restore_gap(tenant_id, document_id)
        gap_lo, gap_hi = (gap if gap is not None else (None, None))
        if gap_lo is not None and base > gap_lo:
            # the log was truncated beyond the checkpoint point (a client
            # summary committed during/after the downtime): the restart
            # window is no longer inspectable, so coverage is unprovable
            raise RuntimeError(
                f"doc {tenant_id}/{document_id}: retention base {base} "
                f"passed the applier's checkpoint seq {gap_lo} while its "
                "restart window is unverified — keep client summaries")
        for m in orderer.scriptorium.get_deltas(
                tenant_id, document_id, base, 10**9):
            if m.type != MessageType.OPERATION:
                continue
            env = m.contents
            if not isinstance(env, dict):
                continue
            kind = env.get("kind")
            if kind == "attach":
                if env.get("id") != self.ds_id:
                    raise RuntimeError(
                        f"doc {tenant_id}/{document_id} has a data store "
                        f"{env.get('id')!r} the device does not model — "
                        "keep client summaries for this doc")
                pkg = env.get("pkg", pkg)
                foreign = set((env.get("snapshot") or {})
                              .get("channels") or {}) - {self.channel_id}
                if foreign:
                    raise RuntimeError(
                        f"doc {tenant_id}/{document_id} attached with "
                        f"non-modeled channels {sorted(foreign)}")
            elif kind == "chanop":
                inner = env.get("contents") or {}
                if env.get("address") != self.ds_id or \
                        inner.get("address") != self.channel_id:
                    raise RuntimeError(
                        f"doc {tenant_id}/{document_id} has ops for "
                        f"{env.get('address')}/{inner.get('address')} the "
                        "device does not model — keep client summaries")
                if "attach" not in inner:
                    last_channel_seq = m.sequence_number
                    if not first_channel_seq:
                        first_channel_seq = m.sequence_number
                    if gap_lo is not None and m.sequence_number > gap_lo \
                            and (gap_hi is None
                                 or m.sequence_number < gap_hi):
                        raise RuntimeError(
                            f"doc {tenant_id}/{document_id} has channel op "
                            f"seq {m.sequence_number} sequenced in the "
                            f"applier's restart window (checkpoint at "
                            f"{gap_lo}, feed resumed at {gap_hi}): the "
                            "restored state does not contain it")
        if applied < last_channel_seq:
            raise RuntimeError(
                f"applier lags the stream for {tenant_id}/{document_id}: "
                f"applied seq {applied} < last channel op "
                f"{last_channel_seq}; feed the applier before summarizing")
        if not anchored and first_channel_seq and \
                self.applier.first_seq(tenant_id, document_id) \
                > first_channel_seq:
            raise RuntimeError(
                f"applier for {tenant_id}/{document_id} started ingesting "
                f"at seq {self.applier.first_seq(tenant_id, document_id)} "
                f"but the doc's channel history starts at "
                f"{first_channel_seq}: coverage is incomplete")
        if base > 0:
            # content below the base is only reachable through the prior
            # acked summary — it must not hold anything we would drop
            prior = self.server.storage(tenant_id,
                                 document_id).get_snapshot_tree()
            stores = ((prior or {}).get("runtime") or {}) \
                .get("dataStores") or {}
            foreign_ds = set(stores) - {self.ds_id}
            ours = (stores.get(self.ds_id) or {})
            foreign_ch = set((ours.get("snapshot") or {})
                             .get("channels") or {}) - {self.channel_id}
            if foreign_ds or foreign_ch:
                raise RuntimeError(
                    f"prior summary of {tenant_id}/{document_id} holds "
                    f"non-modeled content (stores {sorted(foreign_ds)}, "
                    f"channels {sorted(foreign_ch)}) — keep client "
                    "summaries for this doc")
            pkg = ours.get("pkg", pkg)
        return pkg

    def summarize_all(self, tenant_id: str, documents: list[str],
                      min_seq: Optional[int] = None) -> int:
        """The batch pass (BASELINE config 5): one device fence, then a
        decode+upload per doc. Returns the number summarized; docs the
        refusal gate rejects are SKIPPED (recorded in ``self.refusals``),
        not allowed to abort the rest of the fleet — they simply keep
        client summaries."""
        self.applier.finalize()  # one fence for the whole batch
        self.refusals: list[tuple[str, str, str]] = []
        n = 0
        for doc in documents:
            orderer = self.server._get_orderer(tenant_id, doc)
            if min_seq is not None and \
                    orderer.deli.sequence_number < min_seq:
                continue
            try:
                self.summarize_doc(tenant_id, doc)
            except RuntimeError as e:
                self.refusals.append((tenant_id, doc, str(e)))
                continue
            n += 1
        return n
