"""Service-generated summaries from TPU device state.

Ref: scribe's writeServiceSummary (scribe/summaryWriter.ts:226) — the
reference's server can persist a service summary without any client
summarizer, but must REPLAY the op log in JS to get content. Here the
TpuDocumentApplier already holds every doc's converged merge-tree on
device, so a service summary is a decode + upload: the scribe-replay
batch pass of BASELINE config 5, productized.

Two layers on top of the one-shot decode+upload:

- **Columnar content-addressed storage**: the merge-tree snapshot is
  encoded as packed snapcols chunks (protocol/snapcols.py), each chunk
  a content-addressed blob. Unchanged chunks hash identically across
  summary generations and are NOT re-uploaded
  (``storage.snapshot.chunks_reused``); an incremental summary ships
  only the changed tail. The version's root blob is a small "snapcols"
  record naming the chunk hashes plus the protocol state.
- **Threshold-driven loop**: with ``ops_per_summary`` set, ``run_pass``
  summarizes every doc whose stream advanced ≥ N ops since its last
  summary — the serving side of the snapshot fast-boot plane (a late
  joiner's backfill is then O(snapshot + Δ), never O(whole log)).

Scope (by design): the device models merge-tree channels. Documents
whose data stores hold ONLY device-modeled channels get full service
summaries; anything else must keep client summaries — the summarizer
refuses rather than writing a summary that would boot clients into
truncated state.
"""

from __future__ import annotations

import hashlib
from typing import Optional

from ..obs import tier_counters
from ..protocol import snapcols

DS_ID = "default"
TEXT_CHANNEL = "text"

#: root-record marker distinguishing columnar summaries from legacy
#: monolithic dicts and client summary trees
SNAPCOLS_KIND = "snapcols"


def snapcols_root(snap: dict, chunk_ids: list, protocol: dict,
                  sequence_number: int, pkg: str, ds_id: str,
                  channel_id: str) -> dict:
    """The version root record: everything a boot needs EXCEPT the chunk
    bytes themselves (which are content-addressed siblings)."""
    return {
        "t": SNAPCOLS_KIND,
        "v": snapcols.SNAPCOLS_VER,
        "chunks": list(chunk_ids),
        "tree_seq": snap["seq"],
        "min_seq": snap["minSeq"],
        "protocol": protocol,
        "sequence_number": sequence_number,
        "pkg": pkg,
        "ds": ds_id,
        "channel": channel_id,
    }


class HostReplicaSource:
    """Applier-duck-typed content source for deployments without a
    device applier (the socket front end's summarize loop): persistent
    host-side merge-tree replicas fed incrementally from the sequenced
    log — the reference's scribe-replay, kept warm so each summary pays
    only the delta since the last one.

    Coverage story: replicas ingest from seq 0 while the log is whole,
    so the summarizer gate's from-genesis check passes; after the first
    committed summary anchors the doc, retention may trim and the
    replica keeps advancing incrementally (its state already covers the
    trimmed prefix)."""

    def __init__(self, server, ds_id: str = DS_ID,
                 channel_id: str = TEXT_CHANNEL):
        self.server = server
        self.ds_id = ds_id
        self.channel_id = channel_id
        self._replicas: dict = {}
        self._applied: dict = {}
        self._first: dict = {}
        self._anchored: set = set()

    def _ingest(self, tenant_id: str, document_id: str):
        from ..mergetree.client import MergeTreeClient
        from .tpu_applier import channel_stream

        key = (tenant_id, document_id)
        replica = self._replicas.get(key)
        if replica is None:
            replica = self._replicas[key] = MergeTreeClient(
                f"svc-summarizer/{tenant_id}/{document_id}")
        for m in channel_stream(self.server, tenant_id, document_id,
                                self.ds_id, self.channel_id,
                                from_seq=self._applied.get(key, 0)):
            if m.sequence_number <= self._applied.get(key, 0):
                continue
            replica.apply_msg(m, local=False)
            self._applied[key] = m.sequence_number
            self._first.setdefault(key, m.sequence_number)
        return replica

    # ---- the applier surface the summarizer consumes ----
    def get_tree(self, tenant_id: str, document_id: str):
        return self._ingest(tenant_id, document_id)

    def applied_seq(self, tenant_id: str, document_id: str) -> int:
        self._ingest(tenant_id, document_id)
        return self._applied.get((tenant_id, document_id), 0)

    def first_seq(self, tenant_id: str, document_id: str) -> int:
        return self._first.get((tenant_id, document_id), 0)

    def is_anchored(self, tenant_id: str, document_id: str) -> bool:
        return (tenant_id, document_id) in self._anchored

    def mark_anchored(self, tenant_id: str, document_id: str) -> None:
        self._anchored.add((tenant_id, document_id))

    def restore_gap(self, tenant_id: str, document_id: str):
        return None  # host replicas never restore from a checkpoint

    def finalize(self) -> None:
        pass  # no device fence


class ServiceSummarizer:
    """Writes acked summaries straight from the applier's device state."""

    #: chaos seam (fluidframework_tpu/chaos): a crash directive at
    #: ``snapshot.upload`` kills the summarizer after the chunk upload
    #: but before the scribe commit — the mid-upload crash window
    fault_plane = None

    def __init__(self, server, applier, ds_id: str = DS_ID,
                 channel_id: str = TEXT_CHANNEL,
                 ops_per_summary: Optional[int] = None,
                 segs_per_chunk: int = snapcols.SEGS_PER_CHUNK,
                 text_split: int = snapcols.TEXT_SPLIT_CHARS):
        self.server = server
        self.applier = applier
        self.ds_id = ds_id
        self.channel_id = channel_id
        self.ops_per_summary = ops_per_summary
        self.segs_per_chunk = segs_per_chunk
        self.text_split = text_split
        self.summaries_written = 0
        self.refusals: list[tuple[str, str, str]] = []
        self.counters = tier_counters("service")
        # (tenant, doc) → chunk-hash set of the last written generation
        # (seeded from the prior acked snapcols version on first touch,
        # so dedupe survives summarizer restarts)
        self._last_chunks: dict = {}
        # (tenant, doc) → stream seq at the last summary attempt — the
        # threshold loop's trigger state
        self._last_attempt_seq: dict = {}

    def summarize_doc(self, tenant_id: str, document_id: str) -> str:
        """Decode the doc from the device, compose a bootable container
        summary with scribe's protocol replica, upload, and ack it
        (scribe itself is the validator — a service summary commits
        directly, the writeServiceSummary contract)."""
        orderer = self.server._get_orderer(tenant_id, document_id)
        scribe = orderer.scribe
        pkg = self._check_summarizable(tenant_id, document_id, orderer)
        replica = self.applier.get_tree(tenant_id, document_id)
        storage = self.server.storage(tenant_id, document_id)
        snap = replica.snapshot()
        chunks = snapcols.encode_snapshot_chunks(
            snap, self.segs_per_chunk, self.text_split)
        prior = self._prior_chunks(tenant_id, document_id, storage)
        chunk_ids = []
        for chunk in chunks:
            chunk_id = hashlib.sha256(chunk).hexdigest()
            if chunk_id in prior:
                # content-addressed dedupe across generations: the blob
                # is already durable, only the root record names it again
                self.counters.inc("storage.snapshot.chunks_reused")
            else:
                chunk_id = storage.write_blob(chunk)
                self.counters.inc("storage.snapshot.chunks_written")
            chunk_ids.append(chunk_id)
        summary = snapcols_root(
            snap, chunk_ids, scribe.protocol.snapshot(),
            scribe.protocol.sequence_number, pkg, self.ds_id,
            self.channel_id)
        version_id = storage.upload_summary(
            summary, parent=scribe.last_summary_head)
        plane = self.fault_plane
        if plane is not None:
            # crash window: chunks + version record uploaded, commit not
            # yet run — the version must stay invisible to boots
            plane("snapshot.upload", tenant=tenant_id, doc=document_id)
        # the service is its own validator, but must still commit through
        # the scribe's ref-update path so the version reaches the durable
        # versions topic (survives process death) and retention advances
        scribe.commit_version(version_id, scribe.protocol.sequence_number)
        # history plane hook: the committed generation becomes a commit
        # node in the doc's ref graph (refs/main advances; forks and
        # time-travel resolve against these)
        history = getattr(self.server, "history", None)
        if history is not None:
            history.record_commit(
                tenant_id, document_id, version_id,
                scribe.protocol.sequence_number, chunk_ids)
        # the gate pass proved full coverage — anchor the slot so the doc
        # stays summarizable after this commit's own retention truncation
        self.applier.mark_anchored(tenant_id, document_id)
        self.summaries_written += 1
        self._last_chunks[(tenant_id, document_id)] = set(chunk_ids)
        self._last_attempt_seq[(tenant_id, document_id)] = \
            scribe.protocol.sequence_number
        return version_id

    def _prior_chunks(self, tenant_id: str, document_id: str,
                      storage) -> set:
        """Chunk hashes of the previous summary generation (for dedupe):
        the in-memory set, or — first touch after a restart — the latest
        acked snapcols version's chunk list."""
        key = (tenant_id, document_id)
        cached = self._last_chunks.get(key)
        if cached is not None:
            return cached
        prior: set = set()
        try:
            import json

            versions = storage.get_versions(1)
            if versions:
                root = json.loads(
                    storage.read_blob(versions[0]["tree_id"]).decode())
                if root.get("t") == SNAPCOLS_KIND:
                    prior = set(root.get("chunks", ()))
        except (KeyError, ValueError):
            prior = set()
        self._last_chunks[key] = prior
        return prior

    # ------------------------------------------------ threshold loop

    def maybe_summarize(self, tenant_id: str,
                        document_id: str) -> Optional[str]:
        """Summarize iff the stream advanced ≥ ops_per_summary since the
        last attempt. Refusals also re-arm the threshold (retrying a
        permanent refusal every op would re-scan the log each time)."""
        if self.ops_per_summary is None:
            return None
        orderer = self.server._get_orderer(tenant_id, document_id)
        seq = orderer.deli.sequence_number
        key = (tenant_id, document_id)
        if seq - self._last_attempt_seq.get(key, 0) < self.ops_per_summary:
            return None
        try:
            return self.summarize_doc(tenant_id, document_id)
        except RuntimeError as e:
            self.refusals.append((tenant_id, document_id, str(e)))
            self._last_attempt_seq[key] = seq
            return None

    def run_pass(self, tenant_id: str, documents: list[str]) -> int:
        """One threshold-loop tick over the given docs (the service
        host calls this periodically): a single device fence, then a
        maybe_summarize per doc over threshold."""
        self.applier.finalize()
        n = 0
        for doc in documents:
            if self.maybe_summarize(tenant_id, doc) is not None:
                n += 1
        return n

    def _check_summarizable(self, tenant_id: str, document_id: str,
                            orderer) -> str:
        """The refusal gate (module docstring contract). Committing a
        service summary advances retention past scribe's seq, so anything
        the summary does not contain must provably not exist:

        - the applier must not LAG the stream (its state is the content);
        - the doc must hold ONLY the device-modeled data store/channel —
          foreign chanops truncated from the log while absent from the
          summary would be lost permanently;
        - the applier's coverage must be PROVEN complete: either anchored
          (checkpoint restore / authoritative replay / an earlier gate
          pass) or, with the log untruncated, ingested from the doc's
          first channel op. A max-seq check alone would admit an applier
          fed only the post-truncation tail and drop the prefix.
        - when retention already truncated a prefix, the PRIOR acked
          summary must not carry foreign content the stream no longer
          shows.

        Returns the data store's pkg (from its attach op, or the prior
        summary) so the new summary boots the same code."""
        from ..protocol.messages import MessageType

        base = orderer.scriptorium.retained_base(tenant_id, document_id)
        applied = self.applier.applied_seq(tenant_id, document_id)
        anchored = self.applier.is_anchored(tenant_id, document_id)
        if base > 0 and not anchored:
            raise RuntimeError(
                f"applier coverage for {tenant_id}/{document_id} is not "
                f"anchored and the log is truncated below seq {base}: "
                "the prefix is not provably in the device state")
        pkg = "default"
        first_channel_seq = 0
        last_channel_seq = 0
        # restart-window check: a checkpoint-restored anchor is only valid
        # if NO channel op was sequenced between the checkpoint and the
        # point the feed resumed — such ops are in the log but not in the
        # restored device state
        gap = self.applier.restore_gap(tenant_id, document_id)
        gap_lo, gap_hi = (gap if gap is not None else (None, None))
        if gap_lo is not None and base > gap_lo:
            # the log was truncated beyond the checkpoint point (a client
            # summary committed during/after the downtime): the restart
            # window is no longer inspectable, so coverage is unprovable
            raise RuntimeError(
                f"doc {tenant_id}/{document_id}: retention base {base} "
                f"passed the applier's checkpoint seq {gap_lo} while its "
                "restart window is unverified — keep client summaries")
        for m in orderer.scriptorium.get_deltas(
                tenant_id, document_id, base, 10**9):
            if m.type != MessageType.OPERATION:
                continue
            env = m.contents
            if not isinstance(env, dict):
                continue
            kind = env.get("kind")
            if kind == "attach":
                if env.get("id") != self.ds_id:
                    raise RuntimeError(
                        f"doc {tenant_id}/{document_id} has a data store "
                        f"{env.get('id')!r} the device does not model — "
                        "keep client summaries for this doc")
                pkg = env.get("pkg", pkg)
                foreign = set((env.get("snapshot") or {})
                              .get("channels") or {}) - {self.channel_id}
                if foreign:
                    raise RuntimeError(
                        f"doc {tenant_id}/{document_id} attached with "
                        f"non-modeled channels {sorted(foreign)}")
            elif kind == "chanop":
                inner = env.get("contents") or {}
                if env.get("address") != self.ds_id or \
                        inner.get("address") != self.channel_id:
                    raise RuntimeError(
                        f"doc {tenant_id}/{document_id} has ops for "
                        f"{env.get('address')}/{inner.get('address')} the "
                        "device does not model — keep client summaries")
                if "attach" not in inner:
                    last_channel_seq = m.sequence_number
                    if not first_channel_seq:
                        first_channel_seq = m.sequence_number
                    if gap_lo is not None and m.sequence_number > gap_lo \
                            and (gap_hi is None
                                 or m.sequence_number < gap_hi):
                        raise RuntimeError(
                            f"doc {tenant_id}/{document_id} has channel op "
                            f"seq {m.sequence_number} sequenced in the "
                            f"applier's restart window (checkpoint at "
                            f"{gap_lo}, feed resumed at {gap_hi}): the "
                            "restored state does not contain it")
        if applied < last_channel_seq:
            raise RuntimeError(
                f"applier lags the stream for {tenant_id}/{document_id}: "
                f"applied seq {applied} < last channel op "
                f"{last_channel_seq}; feed the applier before summarizing")
        if not anchored and first_channel_seq and \
                self.applier.first_seq(tenant_id, document_id) \
                > first_channel_seq:
            raise RuntimeError(
                f"applier for {tenant_id}/{document_id} started ingesting "
                f"at seq {self.applier.first_seq(tenant_id, document_id)} "
                f"but the doc's channel history starts at "
                f"{first_channel_seq}: coverage is incomplete")
        if base > 0:
            # content below the base is only reachable through the prior
            # acked summary — it must not hold anything we would drop
            prior = self.server.storage(tenant_id,
                                 document_id).get_snapshot_tree()
            stores = ((prior or {}).get("runtime") or {}) \
                .get("dataStores") or {}
            foreign_ds = set(stores) - {self.ds_id}
            ours = (stores.get(self.ds_id) or {})
            foreign_ch = set((ours.get("snapshot") or {})
                             .get("channels") or {}) - {self.channel_id}
            if foreign_ds or foreign_ch:
                raise RuntimeError(
                    f"prior summary of {tenant_id}/{document_id} holds "
                    f"non-modeled content (stores {sorted(foreign_ds)}, "
                    f"channels {sorted(foreign_ch)}) — keep client "
                    "summaries for this doc")
            pkg = ours.get("pkg", pkg)
        return pkg

    def summarize_all(self, tenant_id: str, documents: list[str],
                      min_seq: Optional[int] = None) -> int:
        """The batch pass (BASELINE config 5): one device fence, then a
        decode+upload per doc. Returns the number summarized; docs the
        refusal gate rejects are SKIPPED (recorded in ``self.refusals``),
        not allowed to abort the rest of the fleet — they simply keep
        client summaries."""
        self.applier.finalize()  # one fence for the whole batch
        self.refusals: list[tuple[str, str, str]] = []
        n = 0
        for doc in documents:
            orderer = self.server._get_orderer(tenant_id, doc)
            if min_seq is not None and \
                    orderer.deli.sequence_number < min_seq:
                continue
            try:
                self.summarize_doc(tenant_id, doc)
            except RuntimeError as e:
                self.refusals.append((tenant_id, doc, str(e)))
                continue
            n += 1
        return n
