"""Presence lane: the ephemeral signal tier (ISSUE 12).

Ref: the reference relays every signal through the same socket.io
broadcast machinery as ops (alfred io.ts submitSignal); at read scale
that makes 100k cursor moves 100k broadcast fan-outs. Here signals are
promoted to a first-class ephemeral tier: per-(doc, client, type)
last-writer-wins coalescing server-side, a flush tick, and batched
FT-framed delivery — presence never touches deli, never hits the
durable log, and a burst of cursor moves from one client collapses to
ONE entry per flush window.

The lane is owned by a NetworkFrontEnd (and by a relay Gateway for its
local fan-out): ``publish`` is called on signal ingress, ``flush`` on
the front's presence tick. Delivery is subscriber-shaped: the front
registers one callback per watching session (or per downstream gateway
link), and each callback picks its wire form off a shared
:class:`PresenceBatch` whose encodings are computed AT MOST ONCE per
flush per topic — binary clients share one FT_PRESENCE frame, backbone
links share one FT_FPRESENCE frame, legacy JSON sessions share one
dict list.

Ordering contract: the flush tick runs on the same loop that pushes
sequenced op batches, strictly after any op delivery already queued —
a signal submitted after an op can never overtake that op's broadcast.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..protocol import binwire
from ..protocol.messages import Signal
from ..protocol.serialization import message_to_dict
from ..utils.telemetry import Counters
from ..utils.affinity import loop_only

#: default flush tick — one frame per watcher per window, however many
#: cursor moves arrived inside it
FLUSH_INTERVAL_S = 0.02


class PresenceBatch:
    """One topic's coalesced signals for one flush, with every wire
    form lazily encoded exactly once no matter how many subscribers
    pull it."""

    __slots__ = ("topic", "signals", "_pframe", "_fframe", "_dicts")

    def __init__(self, topic: str, signals: list[Signal]):
        self.topic = topic
        self.signals = signals
        self._pframe: Optional[bytes] = None
        self._fframe: Optional[bytes] = None
        self._dicts: Optional[list] = None

    def presence_frame(self) -> bytes:
        """Framed FT_PRESENCE (client form) — shared by every binary
        direct subscriber."""
        if self._pframe is None:
            self._pframe = binwire.frame(
                binwire.encode_presence(self.signals))
        return self._pframe

    def fpresence_frame(self) -> bytes:
        """Framed FT_FPRESENCE (backbone form, topic prefix) — shared
        by every downstream gateway link; a relay strips the topic with
        a byte splice, never re-encoding."""
        if self._fframe is None:
            self._fframe = binwire.frame(
                binwire.encode_presence(self.signals, topic=self.topic))
        return self._fframe

    def signal_dicts(self) -> list:
        """Legacy JSON form for non-binary sessions."""
        if self._dicts is None:
            self._dicts = [message_to_dict(s) for s in self.signals]
        return self._dicts


class PresenceLane:
    """LWW-coalescing store + subscriber registry for one serving tier.

    Single-threaded by construction: publish and flush both run on the
    owning tier's event loop, so no locking is needed (or wanted — this
    is the hot path of a 100k-viewer doc)."""

    def __init__(self, counters: Counters,
                 flush_interval: float = FLUSH_INTERVAL_S):
        self.counters = counters
        self.flush_interval = flush_interval
        # topic -> {(client_id, type): Signal} — insertion order is
        # arrival order of the winning writes, preserved into the batch
        self._store: dict[str, dict] = {}
        self._subs: dict[str, list] = {}

    # --------------------------------------------------------- ingress

    @loop_only("core")
    def publish(self, topic: str, signal: Signal) -> None:
        self.counters.inc("presence.lane.signals")
        bucket = self._store.setdefault(topic, {})
        key = (signal.client_id, signal.type)
        if key in bucket:
            # the whole point: a later cursor move REPLACES the
            # unflushed one — loss of an intermediate is invisible
            self.counters.inc("presence.lane.coalesced")
        bucket[key] = signal

    # ----------------------------------------------------- subscribers

    def subscribe(self, topic: str,
                  fn: Callable[[PresenceBatch], None]) -> None:
        self._subs.setdefault(topic, []).append(fn)

    def unsubscribe(self, topic: str, fn) -> None:
        subs = self._subs.get(topic)
        if subs is None:
            return
        try:
            subs.remove(fn)
        except ValueError:
            return
        if not subs:
            del self._subs[topic]

    def watching(self, topic: str) -> bool:
        return bool(self._subs.get(topic))

    # ----------------------------------------------------------- flush

    @loop_only("core")
    def flush(self) -> int:
        """Drain every dirty topic to its subscribers; returns the
        number of subscriber deliveries."""
        if not self._store:
            return 0
        store, self._store = self._store, {}
        delivered = 0
        for topic, bucket in store.items():
            subs = self._subs.get(topic)
            if not subs:
                continue  # nobody watches this doc: presence evaporates
            batch = PresenceBatch(topic, list(bucket.values()))
            for fn in list(subs):
                try:
                    fn(batch)
                    delivered += 1
                except Exception:
                    pass  # a dying session must not poison the tick
        self.counters.inc("presence.lane.flushes")
        if delivered:
            self.counters.inc("presence.lane.delivered", delivered)
        return delivered
