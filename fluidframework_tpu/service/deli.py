"""Deli: the per-document sequencer — THE hot loop of the service.

Ref: lambdas/src/deli/lambda.ts (handler :171 → ticket :253). For each raw
client message: validate (dup/gap on clientSeq, stale refSeq vs msn),
assign ``sequenceNumber++``, recompute the document-wide
``minimumSequenceNumber`` as the min reference seq over connected clients
(clientSeqManager.ts), stamp a trace hop, and emit the sequenced op.
Idle clients are expired (5 min default, lambdaFactory.ts:29) so the msn
can advance past dead clients; state checkpoints as
``(log_offset, sequence_number, clients)`` (checkpointContext.ts:49) and
restart replays the log from the checkpoint, skipping already-ticketed
offsets (lambda.ts:173).

The scalar form below is the semantic reference; the sharded TPU form
(parallel/sharded_apply.py + a counter per doc slot) batches the same
ticket rules across thousands of docs.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..protocol.messages import (
    DocumentMessage,
    MessageType,
    Nack,
    NackErrorType,
    SequencedDocumentMessage,
    TraceHop,
)
from .core import QueuedMessage

DEFAULT_CLIENT_TIMEOUT = 5 * 60.0  # ref: ClientSequenceTimeout, 5 minutes


@dataclass
class RawMessage:
    """Alfred → deli envelope (ref: core RawOperationMessage)."""

    tenant_id: str
    document_id: str
    client_id: Optional[str]  # None for server/system-generated messages
    operation: DocumentMessage
    timestamp: float = 0.0


def _raw_to_dict(raw: RawMessage) -> dict:
    from ..protocol.serialization import message_to_dict

    return {
        "tenant_id": raw.tenant_id,
        "document_id": raw.document_id,
        "client_id": raw.client_id,
        "operation": message_to_dict(raw.operation),
        "timestamp": raw.timestamp,
    }


def _raw_from_dict(d: dict) -> RawMessage:
    from ..protocol.serialization import message_from_dict

    return RawMessage(
        tenant_id=d["tenant_id"],
        document_id=d["document_id"],
        client_id=d["client_id"],
        operation=message_from_dict(d["operation"]),
        timestamp=d["timestamp"],
    )


def _register_raw_codec() -> None:
    from ..protocol.serialization import register_message_type

    register_message_type("raw", RawMessage, _raw_to_dict, _raw_from_dict)


_register_raw_codec()


@dataclass
class ClientState:
    """Per-client sequencing state (ref: deli/clientSeqManager.ts)."""

    client_id: str
    client_sequence_number: int = 0
    reference_sequence_number: int = 0
    last_update: float = 0.0
    can_evict: bool = True  # summarizer/system clients are not evicted
    detail: Any = None


@dataclass
class DeliCheckpoint:
    """Restartable state (ref: deli/checkpointContext.ts:49-92)."""

    log_offset: int = -1
    sequence_number: int = 0
    clients: list[dict] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "log_offset": self.log_offset,
            "sequence_number": self.sequence_number,
            "clients": self.clients,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "DeliCheckpoint":
        return cls(d["log_offset"], d["sequence_number"], list(d["clients"]))


class DeliLambda:
    """Sequencer for ONE document (the document-router demuxes per doc)."""

    def __init__(
        self,
        tenant_id: str,
        document_id: str,
        send_sequenced: Callable[[SequencedDocumentMessage], None],
        send_nack: Callable[[str, Nack], None],
        checkpoint: Optional[DeliCheckpoint] = None,
        client_timeout: float = DEFAULT_CLIENT_TIMEOUT,
        clock: Callable[[], float] = time.time,
        send_raw: Optional[Callable[["RawMessage"], None]] = None,
    ):
        self.tenant_id = tenant_id
        self.document_id = document_id
        self._send = send_sequenced
        self._nack = send_nack
        # deli → raw-topic backchannel (ref: deli sendToAlfred :631) for
        # control messages that must be ticketed deterministically on
        # crash replay (idle-eviction leaves)
        self._send_raw = send_raw
        self._clock = clock
        self._client_timeout = client_timeout
        cp = checkpoint or DeliCheckpoint()
        self.sequence_number = cp.sequence_number
        self.log_offset = cp.log_offset
        self.clients: dict[str, ClientState] = {
            c["client_id"]: ClientState(**c) for c in cp.clients
        }

    # ------------------------------------------------------------------ api

    def handler(self, message: QueuedMessage) -> None:
        # idempotent replay after restart (ref: deli/lambda.ts:173)
        if message.offset <= self.log_offset:
            return
        self.log_offset = message.offset
        raw: RawMessage = message.value
        self._ticket(raw)

    def checkpoint(self) -> DeliCheckpoint:
        return DeliCheckpoint(
            log_offset=self.log_offset,
            sequence_number=self.sequence_number,
            clients=[
                {
                    "client_id": c.client_id,
                    "client_sequence_number": c.client_sequence_number,
                    "reference_sequence_number": c.reference_sequence_number,
                    "last_update": c.last_update,
                    "can_evict": c.can_evict,
                    "detail": c.detail,
                }
                for c in self.clients.values()
            ],
        )

    def check_idle_clients(self) -> None:
        """Expire clients idle past the timeout so the msn can advance
        (ref: deli lambda checkIdleClients / ClientSequenceTimeout).

        Leaves route through the raw-ops log (``send_raw``, the reference's
        sendToAlfred backchannel) rather than being sequenced directly: a
        crash after eviction but before a checkpoint must replay raw ops
        into the SAME sequence numbers already persisted/broadcast, which
        only holds if the eviction itself is a raw-log record. ``_ticket``'s
        duplicate-leave check makes redelivery idempotent."""
        now = self._clock()
        for client_id in [
            c.client_id
            for c in self.clients.values()
            if c.can_evict and now - c.last_update > self._client_timeout
        ]:
            if self._send_raw is not None:
                self._send_raw(
                    RawMessage(
                        tenant_id=self.tenant_id,
                        document_id=self.document_id,
                        client_id=None,
                        operation=DocumentMessage(
                            client_sequence_number=-1,
                            reference_sequence_number=-1,
                            type=MessageType.CLIENT_LEAVE,
                            contents={"clientId": client_id},
                        ),
                        timestamp=now,
                    )
                )
            else:  # no raw backchannel wired (bare-lambda unit tests)
                self._sequence_system(
                    MessageType.CLIENT_LEAVE, {"clientId": client_id}, now
                )

    def close(self) -> None:
        pass

    # ------------------------------------------------------------- internal

    def _min_ref_seq(self) -> int:
        """msn = min reference seq over connected clients; with no clients
        the msn rides the sequence number (ref: clientSeqManager heap)."""
        if not self.clients:
            return self.sequence_number
        return min(c.reference_sequence_number for c in self.clients.values())

    def _ticket(self, raw: RawMessage) -> None:
        op = raw.operation
        now = raw.timestamp or self._clock()

        if op.type == MessageType.CLIENT_JOIN:
            # system message from the front end; content names the client
            content = op.contents or {}
            client_id = content.get("clientId")
            if client_id in self.clients:
                return  # duplicate join
            self.clients[client_id] = ClientState(
                client_id=client_id,
                reference_sequence_number=self.sequence_number,
                last_update=now,
                can_evict=content.get("canEvict", True),
                detail=content.get("detail"),
            )
            self._sequence_system(MessageType.CLIENT_JOIN, content, now)
            return

        if op.type == MessageType.CLIENT_LEAVE:
            client_id = (op.contents or {}).get("clientId")
            if client_id not in self.clients:
                return  # duplicate leave
            self._sequence_system(MessageType.CLIENT_LEAVE, op.contents, now)
            return

        if raw.client_id is None:
            # other server-originated messages (scribe's summary ack/nack,
            # control) sequence without client bookkeeping
            self._sequence_system(op.type, op.contents, now)
            return

        # client-originated: must be joined
        client = self.clients.get(raw.client_id)
        if client is None:
            self._nack(
                raw.client_id,
                Nack(
                    operation=op,
                    sequence_number=self.sequence_number,
                    code=400,
                    type=NackErrorType.BAD_REQUEST,
                    message="client not connected (no join on record)",
                ),
            )
            return

        # clientSeq dup/gap detection (ref: deli lambda.ts:264-271)
        expected = client.client_sequence_number + 1
        if op.client_sequence_number < expected:
            return  # duplicate: already sequenced (reconnect replay)
        if op.client_sequence_number > expected:
            self._nack(
                raw.client_id,
                Nack(
                    operation=op,
                    sequence_number=self.sequence_number,
                    code=400,
                    type=NackErrorType.BAD_REQUEST,
                    message=f"clientSeq gap: expected {expected}, "
                    f"got {op.client_sequence_number}",
                ),
            )
            return

        # refSeq below the collaboration window floor is unresolvable
        msn = self._min_ref_seq()
        if op.reference_sequence_number < msn:
            self._nack(
                raw.client_id,
                Nack(
                    operation=op,
                    sequence_number=self.sequence_number,
                    code=400,
                    type=NackErrorType.BAD_REQUEST,
                    message=f"refSeq {op.reference_sequence_number} below msn {msn}",
                ),
            )
            return

        client.client_sequence_number = op.client_sequence_number
        client.reference_sequence_number = op.reference_sequence_number
        client.last_update = now

        self.sequence_number += 1
        traces = list(op.traces)
        traces.append(TraceHop(service="deli", action="sequence", timestamp=now))
        self._send(
            SequencedDocumentMessage(
                client_id=raw.client_id,
                sequence_number=self.sequence_number,
                minimum_sequence_number=self._min_ref_seq(),
                client_sequence_number=op.client_sequence_number,
                reference_sequence_number=op.reference_sequence_number,
                type=op.type,
                contents=op.contents,
                metadata=op.metadata,
                timestamp=now,
                traces=traces,
            )
        )

    def _sequence_system(
        self, type: MessageType, contents: Any, timestamp: Optional[float] = None
    ) -> None:
        """Sequence a server-generated message (join/leave/noClient).

        ``timestamp`` is the raw message's timestamp when ticketing from
        the log — replay must reproduce byte-identical sequenced records,
        so the wall clock is only a fallback for direct (non-log) calls."""
        if type == MessageType.CLIENT_LEAVE:
            self.clients.pop((contents or {}).get("clientId"), None)
        self.sequence_number += 1
        self._send(
            SequencedDocumentMessage(
                client_id=None,
                sequence_number=self.sequence_number,
                minimum_sequence_number=self._min_ref_seq(),
                client_sequence_number=-1,
                reference_sequence_number=-1,
                type=type,
                contents=contents,
                timestamp=self._clock() if timestamp is None else timestamp,
                traces=[TraceHop(service="deli", action="sequence")],
            )
        )
