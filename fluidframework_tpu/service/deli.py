"""Deli: the per-document sequencer — THE hot loop of the service.

Ref: lambdas/src/deli/lambda.ts (handler :171 → ticket :253). For each raw
client message: validate (dup/gap on clientSeq, stale refSeq vs msn),
assign ``sequenceNumber++``, recompute the document-wide
``minimumSequenceNumber`` as the min reference seq over connected clients
(clientSeqManager.ts), stamp a trace hop, and emit the sequenced op.
Idle clients are expired (5 min default, lambdaFactory.ts:29) so the msn
can advance past dead clients; state checkpoints as
``(log_offset, sequence_number, clients)`` (checkpointContext.ts:49) and
restart replays the log from the checkpoint, skipping already-ticketed
offsets (lambda.ts:173).

Two lanes share the same per-document state:

- ``_ticket`` — the scalar semantic reference, one raw message at a time.
- ``_ticket_boxcar`` — the batched fast lane (the "deli-tpu" marshal of
  the north star): a client's submitted batch rides the raw log as ONE
  :class:`RawBoxcar` record (ref: IBoxcarMessage,
  services-core/src/messages.ts) and is ticketed in one pass with the
  clientSeq/refSeq/msn rules vectorized over the boxcar (numpy). The fast
  lane emits byte-identical sequenced messages to the scalar lane
  (tests/test_deli_boxcar.py fuzzes the equivalence) and falls back to
  the scalar lane per-op whenever a precondition fails (dup/gap, stale
  ref, non-op message types, unjoined client).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import numpy as np

from ..protocol.messages import (
    DocumentMessage,
    MessageType,
    Nack,
    NackErrorType,
    SequencedDocumentMessage,
    TraceHop,
)
from ..utils.telemetry import HOP_DELI
from .array_batch import ArrayBoxcar, SequencedArrayBatch
from .core import QueuedMessage

DEFAULT_CLIENT_TIMEOUT = 5 * 60.0  # ref: ClientSequenceTimeout, 5 minutes


@dataclass
class RawMessage:
    """Alfred → deli envelope (ref: core RawOperationMessage)."""

    tenant_id: str
    document_id: str
    client_id: Optional[str]  # None for server/system-generated messages
    operation: DocumentMessage
    timestamp: float = 0.0


def _raw_to_dict(raw: RawMessage) -> dict:
    from ..protocol.serialization import message_to_dict

    return {
        "tenant_id": raw.tenant_id,
        "document_id": raw.document_id,
        "client_id": raw.client_id,
        "operation": message_to_dict(raw.operation),
        "timestamp": raw.timestamp,
    }


def _raw_from_dict(d: dict) -> RawMessage:
    from ..protocol.serialization import message_from_dict

    return RawMessage(
        tenant_id=d["tenant_id"],
        document_id=d["document_id"],
        client_id=d["client_id"],
        operation=message_from_dict(d["operation"]),
        timestamp=d["timestamp"],
    )


@dataclass
class RawBoxcar:
    """One client's submitted batch as a single raw-log record.

    Ref: IBoxcarMessage (services-core/src/messages.ts) — the Kafka
    producer coalesces a connection's messages into one partition record;
    deli unwraps and tickets them in order. Durability/replay semantics are
    identical to per-op records: the boxcar occupies one log offset, and
    deli's ``log_offset`` checkpoint skips already-ticketed boxcars whole.
    """

    tenant_id: str
    document_id: str
    client_id: str
    ops: list[DocumentMessage]
    timestamp: float = 0.0


def _boxcar_to_dict(box: RawBoxcar) -> dict:
    from ..protocol.serialization import message_to_dict

    return {
        "tenant_id": box.tenant_id,
        "document_id": box.document_id,
        "client_id": box.client_id,
        "ops": [message_to_dict(op) for op in box.ops],
        "timestamp": box.timestamp,
    }


def _boxcar_from_dict(d: dict) -> RawBoxcar:
    from ..protocol.serialization import message_from_dict

    return RawBoxcar(
        tenant_id=d["tenant_id"],
        document_id=d["document_id"],
        client_id=d["client_id"],
        ops=[message_from_dict(op) for op in d["ops"]],
        timestamp=d["timestamp"],
    )


def _register_raw_codec() -> None:
    from ..protocol.serialization import register_message_type

    register_message_type("raw", RawMessage, _raw_to_dict, _raw_from_dict)
    register_message_type("rawbox", RawBoxcar, _boxcar_to_dict, _boxcar_from_dict)


_register_raw_codec()


@dataclass
class ClientState:
    """Per-client sequencing state (ref: deli/clientSeqManager.ts)."""

    client_id: str
    client_sequence_number: int = 0
    reference_sequence_number: int = 0
    last_update: float = 0.0
    can_evict: bool = True  # summarizer/system clients are not evicted
    detail: Any = None


@dataclass
class DeliCheckpoint:
    """Restartable state (ref: deli/checkpointContext.ts:49-92)."""

    log_offset: int = -1
    sequence_number: int = 0
    clients: list[dict] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "log_offset": self.log_offset,
            "sequence_number": self.sequence_number,
            "clients": self.clients,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "DeliCheckpoint":
        return cls(d["log_offset"], d["sequence_number"], list(d["clients"]))


class DeliLambda:
    """Sequencer for ONE document (the document-router demuxes per doc)."""

    def __init__(
        self,
        tenant_id: str,
        document_id: str,
        send_sequenced: Callable[[SequencedDocumentMessage], None],
        send_nack: Callable[[str, Nack], None],
        checkpoint: Optional[DeliCheckpoint] = None,
        client_timeout: float = DEFAULT_CLIENT_TIMEOUT,
        clock: Callable[[], float] = time.time,
        send_raw: Optional[Callable[["RawMessage"], None]] = None,
        send_sequenced_batch: Optional[
            Callable[[list[SequencedDocumentMessage]], None]
        ] = None,
        logger=None,
    ):
        self.tenant_id = tenant_id
        self.document_id = document_id
        # telemetry on exceptional paths only (nacks, evictions) — the
        # ticket hot loop stays logging-free
        self._log = logger
        self._send = send_sequenced
        self._send_batch = send_sequenced_batch
        self._nack = self._nack_logged(send_nack)
        # deli → raw-topic backchannel (ref: deli sendToAlfred :631) for
        # control messages that must be ticketed deterministically on
        # crash replay (idle-eviction leaves)
        self._send_raw = send_raw
        self._clock = clock
        self._client_timeout = client_timeout
        cp = checkpoint or DeliCheckpoint()
        self.sequence_number = cp.sequence_number
        self.log_offset = cp.log_offset
        # fast-lane accounting (bench asserts the hot path stayed hot)
        self.boxcars_fast = 0
        self.boxcars_fallback = 0
        self.noops_consolidated = 0
        # clients whose idle-eviction leave is already riding the raw log
        # (re-emitting every check would bloat the log with duplicates
        # that replay forever after restarts)
        self._pending_leaves: set[str] = set()
        self.clients: dict[str, ClientState] = {
            c["client_id"]: ClientState(**c) for c in cp.clients
        }

    # ------------------------------------------------------------------ api

    #: placement fence (placement_plane): a callable returning the CURRENT
    #: routing-table epoch when this partition's claim is stale (another
    #: core claimed a newer epoch), else None. Checked on EVERY record —
    #: a fenced deli must never sequence, even with buffered raw records.
    epoch_fence = None

    def handler(self, message: QueuedMessage) -> None:
        # idempotent replay after restart (ref: deli/lambda.ts:173)
        if message.offset <= self.log_offset:
            return
        fence = self.epoch_fence
        if fence is not None:
            current = fence()
            if current is not None:
                # stale-epoch admission refusal: consume the offset (the
                # record must not replay into a double-sequence later)
                # and nack with the current epoch so the client rebases
                # against the new owner. Counted under placement.*.
                self.log_offset = message.offset
                self._refuse_stale_epoch(message.value, current)
                return
        self.log_offset = message.offset
        raw = message.value
        if type(raw) is RawBoxcar:
            self._ticket_boxcar(raw)
        elif type(raw) is ArrayBoxcar:
            self._ticket_array_boxcar(raw)
        else:
            self._ticket(raw)

    def checkpoint(self) -> DeliCheckpoint:
        return DeliCheckpoint(
            log_offset=self.log_offset,
            sequence_number=self.sequence_number,
            clients=[
                {
                    "client_id": c.client_id,
                    "client_sequence_number": c.client_sequence_number,
                    "reference_sequence_number": c.reference_sequence_number,
                    "last_update": c.last_update,
                    "can_evict": c.can_evict,
                    "detail": c.detail,
                }
                for c in self.clients.values()
            ],
        )

    def check_idle_clients(self) -> None:
        """Expire clients idle past the timeout so the msn can advance
        (ref: deli lambda checkIdleClients / ClientSequenceTimeout).

        Leaves route through the raw-ops log (``send_raw``, the reference's
        sendToAlfred backchannel) rather than being sequenced directly: a
        crash after eviction but before a checkpoint must replay raw ops
        into the SAME sequence numbers already persisted/broadcast, which
        only holds if the eviction itself is a raw-log record. ``_ticket``'s
        duplicate-leave check makes redelivery idempotent."""
        now = self._clock()
        for client_id in [
            c.client_id
            for c in self.clients.values()
            if c.can_evict and now - c.last_update > self._client_timeout
            and c.client_id not in self._pending_leaves
        ]:
            if self._log is not None:
                self._log.info("idle_client_evicted", client_id=client_id,
                               doc=self.document_id)
            if self._send_raw is not None:
                self._pending_leaves.add(client_id)
                self._send_raw(
                    RawMessage(
                        tenant_id=self.tenant_id,
                        document_id=self.document_id,
                        client_id=None,
                        operation=DocumentMessage(
                            client_sequence_number=-1,
                            reference_sequence_number=-1,
                            type=MessageType.CLIENT_LEAVE,
                            contents={"clientId": client_id},
                        ),
                        timestamp=now,
                    )
                )
            else:  # no raw backchannel wired (bare-lambda unit tests)
                self._sequence_system(
                    MessageType.CLIENT_LEAVE, {"clientId": client_id}, now
                )

    def close(self) -> None:
        pass

    def _refuse_stale_epoch(self, raw, current_epoch: int) -> None:
        """Placement fence tripped: this core's claim on the partition is
        older than the routing table's. Refuse WITHOUT sequencing — nack
        client records with the current epoch (the redirect hint), drop
        system records (the new owner re-derives joins/leaves)."""
        from .placement_plane import placement_counters

        placement_counters().inc("placement.epoch.stale_nacks")
        msg = (f"stale placement epoch: partition now at epoch "
               f"{current_epoch}; reconnect")
        if type(raw) is RawBoxcar:
            for op in raw.ops:
                self._nack(raw.client_id, Nack(
                    operation=op, sequence_number=self.sequence_number,
                    code=410, type=NackErrorType.BAD_REQUEST, message=msg))
        elif type(raw) is ArrayBoxcar:
            self._nack(raw.client_id, Nack(
                operation=None, sequence_number=self.sequence_number,
                code=410, type=NackErrorType.BAD_REQUEST, message=msg))
        elif getattr(raw, "client_id", None) is not None:
            self._nack(raw.client_id, Nack(
                operation=raw.operation,
                sequence_number=self.sequence_number,
                code=410, type=NackErrorType.BAD_REQUEST, message=msg))

    def _nack_logged(self, send_nack):
        def nack(client_id, n):
            if self._log is not None:
                self._log.send("error", "nack", client_id=client_id,
                               doc=self.document_id, code=n.code,
                               reason=n.message)
            send_nack(client_id, n)
        return nack

    # ---------------------------------------------------- boxcar fast lane

    def _ticket_boxcar(self, box: RawBoxcar) -> None:
        """Ticket a client's batch in one vectorized pass.

        Fast-lane preconditions (else per-op scalar fallback):
        the client is joined, every op is a plain OPERATION, clientSeqs are
        consecutive from the stored counter, and refSeqs are non-decreasing
        starting at/above the stored refSeq.

        Under those preconditions the scalar rules collapse:

        - no nack can fire: the pre-op msn for op i is
          ``min(others_min, rseq[i-1]) <= rseq[i-1] <= rseq[i]`` (and for
          op 0, ``min(others_min, stored) <= stored <= rseq[0]``), so
          ``rseq[i] < msn`` is impossible;
        - only this client's refSeq moves during the boxcar, so the
          post-op msn for op i is exactly ``min(others_min, rseq[i])``
          with ``others_min`` hoisted out of the loop — the
          clientSeqManager heap reduced to one vectorized ``minimum``;
        - sequence numbers are ``seq+1 .. seq+n``.
        """
        ops = box.ops
        client = self.clients.get(box.client_id)
        if not ops or client is None:
            self._fallback_boxcar(box)
            return
        n = len(ops)
        op_t = MessageType.OPERATION
        if n >= 128:  # numpy wins only on big boxcars: at n=32 the two
            # fromiter+diff round trips cost ~3× the scalar check loop
            # big boxcar: the checks and the msn rule as numpy array ops
            cseq = np.fromiter(
                (op.client_sequence_number for op in ops), np.int64, n)
            rseq = np.fromiter(
                (op.reference_sequence_number for op in ops), np.int64, n)
            if not (
                cseq[0] == client.client_sequence_number + 1
                and rseq[0] >= client.reference_sequence_number
                and (np.diff(cseq) == 1).all()
                and (np.diff(rseq) >= 0).all()
                and all(op.type is op_t for op in ops)
            ):
                self._fallback_boxcar(box)
                return
            last_cseq = int(cseq[-1])
            last_rseq = int(rseq[-1])
        else:
            # small boxcar: array setup costs more than it saves
            prev_c = client.client_sequence_number
            prev_r = client.reference_sequence_number
            for op in ops:
                if (
                    op.type is not op_t
                    or op.client_sequence_number != prev_c + 1
                    or op.reference_sequence_number < prev_r
                ):
                    self._fallback_boxcar(box)
                    return
                prev_c += 1
                prev_r = op.reference_sequence_number
            last_cseq = prev_c
            last_rseq = prev_r
            rseq = None

        now = box.timestamp or self._clock()
        others_min = min(
            (
                c.reference_sequence_number
                for c in self.clients.values()
                if c is not client
            ),
            default=None,
        )
        seq = self.sequence_number
        if rseq is not None:
            msns = (rseq if others_min is None
                    else np.minimum(rseq, others_min)).tolist()
        else:
            msns = None

        self.sequence_number = seq + n
        client.client_sequence_number = last_cseq
        client.reference_sequence_number = last_rseq
        client.last_update = now

        out = []
        cid = box.client_id
        # sampled tracing (ref: deli's sampled message tracing): the hop
        # is stamped only onto ops the CLIENT pre-traced — load workers
        # stamp one op per boxcar — so the per-op trace encode/decode
        # cost scales with the sampling rate, not the op rate. ONE hop
        # object is shared across the batch (hops are never mutated,
        # only copied — consumers that extend traces build their own)
        hop = None
        empty: list = []
        for i, op in enumerate(ops):
            ref = op.reference_sequence_number
            if msns is not None:
                msn = msns[i]
            else:
                msn = ref if (others_min is None or ref < others_min) \
                    else others_min
            seq += 1
            if op.traces:
                if hop is None:
                    hop = TraceHop(service="deli", action="sequence",
                                   timestamp=now)
                traces = list(op.traces)
                traces.append(hop)
            else:
                traces = empty
            out.append(
                SequencedDocumentMessage(
                    client_id=cid,
                    sequence_number=seq,
                    minimum_sequence_number=msn,
                    client_sequence_number=op.client_sequence_number,
                    reference_sequence_number=ref,
                    type=op.type,
                    contents=op.contents,
                    metadata=op.metadata,
                    timestamp=now,
                    traces=traces,
                )
            )
        self.boxcars_fast += 1
        if self._send_batch is not None:
            self._send_batch(out)
        else:
            for msg in out:
                self._send(msg)

    def _ticket_array_boxcar(self, box) -> None:
        """Ticket an ArrayBoxcar (service/array_batch.py) in one
        vectorized pass — the array lane of the boxcar fast path.

        Same preconditions as _ticket_boxcar (joined client, consecutive
        clientSeqs, non-decreasing refSeqs ≥ stored — under which no
        nack can fire and the msn rule collapses to one minimum); a miss
        falls back to the scalar lane on the EQUIVALENT dict boxcar.
        Emits a SequencedArrayBatch carrying seq range + per-op msns; no
        per-op message objects are built (cold consumers materialize)."""
        client = self.clients.get(box.client_id)
        n = box.n
        if n == 0 or client is None:
            self._fallback_boxcar(box.to_raw_boxcar())
            return
        cseq, rseq = box.cseq, box.rseq
        if not (
            int(cseq[0]) == client.client_sequence_number + 1
            and int(rseq[0]) >= client.reference_sequence_number
            and (np.diff(cseq) == 1).all()
            and (np.diff(rseq) >= 0).all()
        ):
            self._fallback_boxcar(box.to_raw_boxcar())
            return
        now = box.timestamp or self._clock()
        others_min = min(
            (c.reference_sequence_number
             for c in self.clients.values() if c is not client),
            default=None,
        )
        rs = rseq.astype(np.int64)
        msns = rs if others_min is None else np.minimum(rs, others_min)
        base_seq = self.sequence_number + 1
        self.sequence_number += n
        client.client_sequence_number = int(cseq[-1])
        client.reference_sequence_number = int(rseq[-1])
        client.last_update = now
        self.boxcars_fast += 1
        if box.hops is not None:
            # sampled boxcar: the stamp timestamp IS deli's ticket time
            # (matches what scan_ops reports as deli_ts for cols frames)
            box.hops.append((HOP_DELI, now))
        batch = SequencedArrayBatch(boxcar=box, base_seq=base_seq,
                                    msns=msns, timestamp=now)
        if self._send_batch is not None:
            self._send_batch(batch)
        else:
            for msg in batch.messages():
                self._send(msg)

    def _fallback_boxcar(self, box: RawBoxcar) -> None:
        """Scalar lane for boxcars that miss a fast-path precondition."""
        self.boxcars_fallback += 1
        for op in box.ops:
            self._ticket(
                RawMessage(
                    tenant_id=box.tenant_id,
                    document_id=box.document_id,
                    client_id=box.client_id,
                    operation=op,
                    timestamp=box.timestamp,
                )
            )

    # ------------------------------------------------------------- internal

    def _min_ref_seq(self) -> int:
        """msn = min reference seq over connected clients; with no clients
        the msn rides the sequence number (ref: clientSeqManager heap)."""
        if not self.clients:
            return self.sequence_number
        return min(c.reference_sequence_number for c in self.clients.values())

    def _ticket(self, raw: RawMessage) -> None:
        op = raw.operation
        now = raw.timestamp or self._clock()

        if op.type == MessageType.CLIENT_JOIN:
            # system message from the front end; content names the client
            content = op.contents or {}
            client_id = content.get("clientId")
            if client_id in self.clients:
                return  # duplicate join
            self.clients[client_id] = ClientState(
                client_id=client_id,
                reference_sequence_number=self.sequence_number,
                last_update=now,
                can_evict=content.get("canEvict", True),
                detail=content.get("detail"),
            )
            self._sequence_system(MessageType.CLIENT_JOIN, content, now)
            return

        if op.type == MessageType.CLIENT_LEAVE:
            client_id = (op.contents or {}).get("clientId")
            self._pending_leaves.discard(client_id)
            if client_id not in self.clients:
                return  # duplicate leave
            self._sequence_system(MessageType.CLIENT_LEAVE, op.contents, now)
            if not self.clients:
                # the doc went quiet: the NoClient marker tells scribe a
                # service summary can capture final state (ref: deli
                # sending NoClient, protocol.ts MessageType.noClient)
                self._sequence_system(MessageType.NO_CLIENT, None, now)
            return

        if raw.client_id is None:
            # other server-originated messages (scribe's summary ack/nack,
            # control) sequence without client bookkeeping
            self._sequence_system(op.type, op.contents, now)
            return

        # client-originated: must be joined
        client = self.clients.get(raw.client_id)
        if client is None:
            self._nack(
                raw.client_id,
                Nack(
                    operation=op,
                    sequence_number=self.sequence_number,
                    code=400,
                    type=NackErrorType.BAD_REQUEST,
                    message="client not connected (no join on record)",
                ),
            )
            return

        # clientSeq dup/gap detection (ref: deli lambda.ts:264-271)
        expected = client.client_sequence_number + 1
        if op.client_sequence_number < expected:
            return  # duplicate: already sequenced (reconnect replay)
        if op.client_sequence_number > expected:
            self._nack(
                raw.client_id,
                Nack(
                    operation=op,
                    sequence_number=self.sequence_number,
                    code=400,
                    type=NackErrorType.BAD_REQUEST,
                    message=f"clientSeq gap: expected {expected}, "
                    f"got {op.client_sequence_number}",
                ),
            )
            return

        # refSeq below the collaboration window floor is unresolvable
        msn = self._min_ref_seq()
        if op.reference_sequence_number < msn:
            self._nack(
                raw.client_id,
                Nack(
                    operation=op,
                    sequence_number=self.sequence_number,
                    code=400,
                    type=NackErrorType.BAD_REQUEST,
                    message=f"refSeq {op.reference_sequence_number} below msn {msn}",
                ),
            )
            return

        msn_before = msn  # nothing mutated since the nack check above
        client.client_sequence_number = op.client_sequence_number
        client.reference_sequence_number = op.reference_sequence_number
        client.last_update = now

        if op.type == MessageType.NOOP and self._min_ref_seq() == msn_before:
            # noop consolidation (ref: deli's noop timer): a heartbeat
            # that does NOT move the document msn has nothing to tell
            # anyone — the refSeq bookkeeping above is its whole effect,
            # so it takes no sequence number. A floor-moving noop still
            # sequences (ONE message makes the new msn visible, which is
            # what lets quorum proposals commit). Deterministic on
            # replay: a pure function of the record + prior state.
            self.noops_consolidated += 1
            return

        self.sequence_number += 1
        # sampled tracing: stamp only client-traced ops (see fast lane)
        traces = list(op.traces)
        if traces:
            traces.append(TraceHop(service="deli", action="sequence",
                                   timestamp=now))
        self._send(
            SequencedDocumentMessage(
                client_id=raw.client_id,
                sequence_number=self.sequence_number,
                minimum_sequence_number=self._min_ref_seq(),
                client_sequence_number=op.client_sequence_number,
                reference_sequence_number=op.reference_sequence_number,
                type=op.type,
                contents=op.contents,
                metadata=op.metadata,
                timestamp=now,
                traces=traces,
            )
        )

    def _sequence_system(
        self, type: MessageType, contents: Any, timestamp: Optional[float] = None
    ) -> None:
        """Sequence a server-generated message (join/leave/noClient).

        ``timestamp`` is the raw message's timestamp when ticketing from
        the log — replay must reproduce byte-identical sequenced records,
        so the wall clock is only a fallback for direct (non-log) calls."""
        if type == MessageType.CLIENT_LEAVE:
            self.clients.pop((contents or {}).get("clientId"), None)
        self.sequence_number += 1
        now = self._clock() if timestamp is None else timestamp
        self._send(
            SequencedDocumentMessage(
                client_id=None,
                sequence_number=self.sequence_number,
                minimum_sequence_number=self._min_ref_seq(),
                client_sequence_number=-1,
                reference_sequence_number=-1,
                type=type,
                contents=contents,
                timestamp=now,
                # trace stamped at the record timestamp, not the wall
                # clock: crash replay must reproduce byte-identical records
                traces=[TraceHop(service="deli", action="sequence",
                                 timestamp=now)],
            )
        )
