"""Lease-based doc-partition placement for the sharded ordering core.

Ref: memory-orderer/src/reservationManager.ts:21 — the reference's
multi-node orderer takes Mongo lease reservations on documents and
proxies connections to the owner node (remoteNode.ts:92). Here the unit
of ownership is the doc PARTITION (``stage_runner.doc_partition`` —
md5(doc) mod N, the same stable map the pipeline stages shard by), and
the registry is a shared lease DIRECTORY: one file per partition,
heartbeat by mtime, atomic takeover by rename. A partition's lease names
its owner's client-facing address, which is also the key to its durable
state: partition k's log lives in ``<shard_dir>/log-<k>``, so whoever
holds the lease resumes the partition's pipeline from its checkpoints —
ownership and durability move together.

Liveness: owners touch their lease every ``heartbeat_s``; a lease older
than ``ttl_s`` is STALE and any core may take it over. Takeover is an
atomic rename, so two racing claimants cannot both win (the loser's
rename replaces the winner's file only if it also observed staleness
within the same race window — the subsequent ``owner_of`` read settles
on one file content, and the heartbeat loop self-corrects: a core that
reads another owner's id in its supposed lease drops the partition).
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Optional

from ..utils.affinity import holds_lock

DEFAULT_TTL_S = 3.0


class PlacementDir:
    """Shared-directory lease registry over ``n_partitions`` partitions."""

    def __init__(self, directory: str, n_partitions: int,
                 ttl_s: float = DEFAULT_TTL_S):
        self.directory = directory
        self.n = n_partitions
        self.ttl_s = ttl_s
        os.makedirs(directory, exist_ok=True)

    def _path(self, k: int) -> str:
        return os.path.join(self.directory, f"part-{k}.lease")

    def _read(self, k: int) -> Optional[dict]:
        try:
            with open(self._path(k)) as f:
                rec = json.load(f)
            rec["_age"] = time.time() - os.stat(self._path(k)).st_mtime
            return rec
        except (OSError, json.JSONDecodeError):
            return None

    # ------------------------------------------------------------- owners

    def _lock(self, k: int):
        """flock-serialized claim critical section: two racing claimants
        cannot both observe staleness and both install their lease (the
        rename-and-reread scheme allowed exactly that). The lock file is
        separate from the lease so readers never block."""
        import contextlib
        import fcntl

        @contextlib.contextmanager
        def held():
            fd = os.open(self._path(k) + ".lock",
                         os.O_CREAT | os.O_RDWR, 0o644)
            try:
                fcntl.flock(fd, fcntl.LOCK_EX)
                yield
            finally:
                fcntl.flock(fd, fcntl.LOCK_UN)
                os.close(fd)
        return held()

    @holds_lock("partition_claim_flock")
    def try_claim(self, k: int, owner_id: str, address: str) -> bool:
        """Claim partition ``k`` if it is unowned or its lease is stale.
        Returns True when this owner holds the lease afterwards."""
        with self._lock(k):
            cur = self._read(k)
            if cur is not None and cur.get("owner") != owner_id \
                    and cur["_age"] < self.ttl_s:
                return False  # live lease held by someone else
            fd, tmp = tempfile.mkstemp(dir=self.directory,
                                       prefix=".lease-")
            with os.fdopen(fd, "w") as f:
                json.dump({"owner": owner_id, "address": address}, f)
            os.replace(tmp, self._path(k))
            return True

    @holds_lock("partition_claim_flock")
    def heartbeat(self, k: int, owner_id: str) -> bool:
        """Refresh the lease mtime; returns False if the lease was lost
        (taken over) — the caller must stop serving the partition.

        Read-check-utime runs under the SAME flock as try_claim: a
        stalled ex-owner whose heartbeat resumes mid-takeover would
        otherwise re-read its own (stale) lease, then utime the file the
        claimant just replaced — two cores each believing they hold the
        lease (the two-writer window)."""
        with self._lock(k):
            cur = self._read(k)
            if cur is None or cur.get("owner") != owner_id:
                return False
            os.utime(self._path(k))
            return True

    @holds_lock("partition_claim_flock")
    def transfer(self, k: int, from_owner: str, to_owner: str,
                 to_address: str) -> bool:
        """Migration handoff: atomically rewrite ``k``'s lease from
        ``from_owner`` to ``to_owner`` under the claim flock. Unlike
        release-then-claim there is NO unowned window a third core could
        steal, and unlike ``try_claim`` it succeeds while the source's
        lease is still FRESH — the source consents by naming itself.
        Returns False (and changes nothing) if the lease is no longer
        ``from_owner``'s (it crashed and was taken over mid-handoff)."""
        with self._lock(k):
            cur = self._read(k)
            if cur is None or cur.get("owner") != from_owner:
                return False
            fd, tmp = tempfile.mkstemp(dir=self.directory,
                                       prefix=".lease-")
            with os.fdopen(fd, "w") as f:
                json.dump({"owner": to_owner, "address": to_address}, f)
            os.replace(tmp, self._path(k))
            return True

    @holds_lock("partition_claim_flock")
    def release(self, k: int, owner_id: str) -> None:
        # same flock as try_claim/heartbeat: a release racing a takeover
        # must not unlink the NEW owner's lease after a stale read
        with self._lock(k):
            cur = self._read(k)
            if cur is not None and cur.get("owner") == owner_id:
                try:
                    os.unlink(self._path(k))
                except OSError:
                    pass

    # ------------------------------------------------------------ routers

    def owner_of(self, k: int) -> Optional[str]:
        """The owning core's address, or None (unowned / stale lease)."""
        cur = self._read(k)
        if cur is None or cur["_age"] >= self.ttl_s:
            return None
        return cur.get("address")

    def table(self) -> dict[int, Optional[str]]:
        return {k: self.owner_of(k) for k in range(self.n)}
