"""mmap'd reader over the durable log's columnar segment streams.

The write side lives in native/oplog.cpp (``oplog_seg_append``: packed
column blocks into fixed-size ``<stream>.seg<k>`` files + one 32-byte
entry per block in ``<stream>.segidx``); this module is the read side:

- the index mmaps as ONE numpy structured array (``SEG_IDX_DTYPE``
  matches the C ``SegEntry`` layout bit for bit), so recovery replay and
  backfill never decode per-record framing — one ``np.frombuffer`` per
  stream, then integer slicing;
- a ``[from_seq, to_seq]``-overlap query is two ``np.searchsorted``
  calls over the sorted first/last columns plus raw byte-range copies of
  the already-encoded blocks (the Kafka segment+index trick, SURVEY
  §2.9) — zero re-encode, zero per-op materialization;
- tail validation mirrors ``oplog_seg_refresh``: an index entry is
  admitted only once its block bytes fully landed in the segment file,
  so tailing a live producer never surfaces a torn block.

Readers re-mmap lazily as files grow; admitted entries are stable (the
writer's torn-tail recovery only ever cuts entries whose bytes never
landed, which a reader by construction never admitted).
"""

from __future__ import annotations

import mmap
import os
from typing import Callable, Optional

import numpy as np

# bit-for-bit the C SegEntry (native/oplog.cpp): i64 first/last seq span,
# u32 segment ordinal / byte offset / byte length / block type
SEG_IDX_DTYPE = np.dtype([("first", "<i8"), ("last", "<i8"), ("seg", "<u4"),
                          ("off", "<u4"), ("len", "<u4"), ("btype", "<u4")])


class SegmentReader:
    """Zero-copy-indexed view over one segment stream.

    ``flush`` is the same-process producer's flush hook (page-cache
    visibility for bytes still in libc buffers); cross-process readers
    pass None and rely on the producer's drain-boundary flush contract.
    """

    def __init__(self, directory: str, stream: str,
                 flush: Optional[Callable[[], None]] = None):
        self.directory = directory
        self.stream = stream
        self._flush = flush
        self._idx_mm: Optional[mmap.mmap] = None
        self._idx: Optional[np.ndarray] = None
        self._n = 0  # validated (admitted) block count
        self._seg_mm: dict[int, mmap.mmap] = {}

    def _idx_path(self) -> str:
        return os.path.join(self.directory, self.stream + ".segidx")

    def _seg_path(self, seg: int) -> str:
        return os.path.join(self.directory, f"{self.stream}.seg{seg}")

    @property
    def count(self) -> int:
        return self._n

    def refresh(self) -> int:
        """Admit newly landed blocks; returns the validated block count."""
        if self._flush is not None:
            self._flush()
        try:
            size = os.path.getsize(self._idx_path())
        except OSError:
            return self._n
        item = SEG_IDX_DTYPE.itemsize
        n_disk = size // item
        if n_disk <= self._n:
            return self._n
        if self._idx_mm is None or len(self._idx_mm) < n_disk * item:
            if self._idx_mm is not None:
                self._idx = None  # release the buffer export before close
                self._idx_mm.close()
            with open(self._idx_path(), "rb") as f:
                self._idx_mm = mmap.mmap(f.fileno(), n_disk * item,
                                         access=mmap.ACCESS_READ)
        idx = np.frombuffer(self._idx_mm, SEG_IDX_DTYPE, n_disk)
        n = self._n
        sized_seg, sized = -1, 0
        while n < n_disk:
            e = idx[n]
            seg = int(e["seg"])
            if seg != sized_seg:
                sized_seg = seg
                try:
                    sized = os.path.getsize(self._seg_path(seg))
                except OSError:
                    sized = 0
            if int(e["off"]) + int(e["len"]) > sized:
                break  # mid-write tail: invisible until the bytes land
            n += 1
        self._idx = idx
        self._n = n
        return n

    def _seg_map(self, seg: int, need: int) -> mmap.mmap:
        mm = self._seg_mm.get(seg)
        if mm is None or len(mm) < need:
            if mm is not None:
                mm.close()
            with open(self._seg_path(seg), "rb") as f:
                mm = mmap.mmap(f.fileno(), os.fstat(f.fileno()).st_size,
                               access=mmap.ACCESS_READ)
            self._seg_mm[seg] = mm
        return mm

    def entry(self, ordinal: int) -> tuple[int, int, int]:
        """(btype, first_seq, last_seq) of an admitted block."""
        e = self._idx[ordinal]
        return int(e["btype"]), int(e["first"]), int(e["last"])

    def block(self, ordinal: int) -> tuple[int, int, int, bytes]:
        """(btype, first_seq, last_seq, payload) — one raw byte-range
        copy out of the segment mmap, no decoding."""
        if not 0 <= ordinal < self._n:
            raise IndexError(f"no block {ordinal} in {self.stream!r}")
        e = self._idx[ordinal]
        off, ln = int(e["off"]), int(e["len"])
        mm = self._seg_map(int(e["seg"]), off + ln)
        return (int(e["btype"]), int(e["first"]), int(e["last"]),
                bytes(mm[off:off + ln]))

    def range_blocks(self, from_seq: int, to_seq: int) -> list[int]:
        """Ordinals of blocks holding any seq with from_seq < seq <
        to_seq (the REST /deltas exclusive-bounds contract): binary
        search over the seq-span columns, O(log blocks) + O(answer).

        Spans are ALMOST sorted by ordinal, but a deli crash-replay can
        re-append blocks whose spans regress below earlier entries
        (at-least-once duplicates), so plain searchsorted over the raw
        columns is unsound. Searching the running-max of ``last`` and
        the suffix-min of ``first`` — both sorted by construction —
        yields a tight superset, and the exact overlap mask trims it."""
        n = self._n
        if n == 0:
            return []
        first = self._idx["first"][:n].astype(np.int64, copy=False)
        last = self._idx["last"][:n].astype(np.int64, copy=False)
        last_cm = np.maximum.accumulate(last)
        first_sm = np.minimum.accumulate(first[::-1])[::-1]
        lo = int(np.searchsorted(last_cm, from_seq, side="right"))
        hi = int(np.searchsorted(first_sm, to_seq, side="left"))
        if hi <= lo:
            return []
        mask = (last[lo:hi] > from_seq) & (first[lo:hi] < to_seq)
        return [lo + int(i) for i in np.nonzero(mask)[0]]

    def first_covering(self, seq: int) -> int:
        """Ordinal of the first block that may hold any seq' ≥ ``seq``
        (0 when seq ≤ 1 or the stream is empty). Blocks below it have
        running-max ``last`` < seq, so a tail subscription starting
        here misses nothing — the lazy cold-boot replay entry point.
        Duplicate blocks above it (crash-replay span regressions) are
        redelivered and absorbed by the consumers' idempotent skip."""
        n = self._n
        if n == 0 or seq <= 1:
            return 0
        last = self._idx["last"][:n].astype(np.int64, copy=False)
        return int(np.searchsorted(np.maximum.accumulate(last), seq - 1,
                                   side="right"))

    def close(self) -> None:
        for mm in self._seg_mm.values():
            mm.close()
        self._seg_mm.clear()
        if self._idx_mm is not None:
            self._idx = None
            self._idx_mm.close()
            self._idx_mm = None
        self._n = 0
