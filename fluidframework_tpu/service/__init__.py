"""The ordering service: sequencer pipeline + storage + front end.

Ref: server/routerlicious (SURVEY §2.8). The service does NO merge logic —
it assigns each op a position in a per-document total order, persists it,
and fans it out; clients do the merging. The pipeline stages are pure
lambdas (services-core lambdas.ts:36) connected by an ordered log, so the
same stage code runs over the in-memory log (tests, Tinylicious analog) or
the C++ sharded log (production analog).

- ``core``         stage/queue/db abstractions (services-core analog)
- ``local_log``    in-memory ordered log (memory-orderer LocalKafka analog)
- ``deli``         the sequencer (lambdas/src/deli)
- ``broadcaster``  fan-out to subscribers (lambdas/src/broadcaster)
- ``scriptorium``  durable op store for backfill (lambdas/src/scriptorium)
- ``scribe``       protocol replica + summary commits (lambdas/src/scribe)
- ``local_orderer``wires real lambdas over the local log (memory-orderer)
- ``local_server`` in-proc service endpoint (local-server / tinylicious)
"""

from .core import CheckpointManager, InMemoryDb, Lambda, LambdaContext
from .deli import DeliCheckpoint, DeliLambda, RawMessage
from .local_log import LocalLog
from .local_orderer import LocalOrderer
from .local_server import LocalServer, ServerConnection

__all__ = [
    "CheckpointManager",
    "InMemoryDb",
    "Lambda",
    "LambdaContext",
    "DeliCheckpoint",
    "DeliLambda",
    "RawMessage",
    "LocalLog",
    "LocalOrderer",
    "LocalServer",
    "ServerConnection",
]

from .front_end import NetworkFrontEnd  # noqa: E402

__all__.append("NetworkFrontEnd")
