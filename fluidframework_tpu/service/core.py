"""Service plugin surface: every concrete stage implements these.

Ref: server/routerlicious/packages/services-core — IPartitionLambda /
IPartitionLambdaFactory (lambdas.ts:36,52), IProducer/IConsumer with boxcar
batching (messages.ts), ICollection (db.ts), ICheckpointManager. Stages are
pure functions of (checkpoint state, ordered message stream); the host owns
offsets and restart.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Protocol


@dataclass
class QueuedMessage:
    """A message with its position in an ordered log partition."""

    offset: int
    topic: str
    partition: int
    value: Any


class LambdaContext:
    """Host services handed to a lambda: checkpointing + error escalation.

    Ref: IContext (services-core/src/lambdas.ts): ``checkpoint(offset)``
    records progress; ``error(err, restart)`` asks the host to restart the
    partition from the last checkpoint.
    """

    def __init__(
        self,
        checkpoint_fn: Callable[[int], None],
        error_fn: Optional[Callable[[Exception, bool], None]] = None,
    ):
        self._checkpoint = checkpoint_fn
        self._error = error_fn
        self.checkpointed_offset: int = -1

    def checkpoint(self, offset: int) -> None:
        self.checkpointed_offset = offset
        self._checkpoint(offset)

    def error(self, err: Exception, restart: bool = True) -> None:
        if self._error:
            self._error(err, restart)
        else:
            raise err


class Lambda(Protocol):
    """One pipeline stage (ref: IPartitionLambda.handler)."""

    def handler(self, message: QueuedMessage) -> None: ...

    def close(self) -> None: ...


class CheckpointManager:
    """Tracks the lowest contiguous processed offset per partition.

    Ref: lambdas-driver/src/kafka-service/checkpointManager.ts — offsets
    commit monotonically; on restart the partition replays from the last
    committed offset and lambdas skip already-applied messages by offset.
    """

    def __init__(self):
        self._offsets: dict[tuple[str, int], int] = {}

    def checkpoint(self, topic: str, partition: int, offset: int) -> None:
        key = (topic, partition)
        if offset > self._offsets.get(key, -1):
            self._offsets[key] = offset

    def get(self, topic: str, partition: int) -> int:
        return self._offsets.get((topic, partition), -1)


def summary_versions_collection(tenant_id: str, document_id: str) -> str:
    """Db collection holding a document's summary version chain — shared
    by the storage driver (upload) and scribe (validation/commit)."""
    return f"summary-versions/{tenant_id}/{document_id}"


@dataclass
class InMemoryDb:
    """Dict-of-collections store (the Mongo stand-in for tests).

    Ref: server/routerlicious/packages/test-utils testDbFactory /
    tinylicious inMemorycollection.ts. Collections hold dict documents keyed
    by ``_id``; upsert semantics match what deli/scribe checkpointing needs.
    """

    collections: dict[str, dict[str, dict]] = field(default_factory=dict)

    def collection(self, name: str) -> dict[str, dict]:
        return self.collections.setdefault(name, {})

    def upsert(self, name: str, _id: str, value: dict) -> None:
        self.collection(name)[_id] = dict(value, _id=_id)

    def find_one(self, name: str, _id: str) -> Optional[dict]:
        return self.collection(name).get(_id)

    def insert(self, name: str, _id: str, value: dict) -> None:
        col = self.collection(name)
        if _id in col:
            raise KeyError(f"duplicate _id {_id} in {name}")
        col[_id] = dict(value, _id=_id)

    def find_range(
        self, name: str, key_fn: Callable[[dict], int], lo: int, hi: int
    ) -> list[dict]:
        """All docs with lo <= key < hi, sorted by key (delta backfill)."""
        docs = [d for d in self.collection(name).values() if lo <= key_fn(d) < hi]
        return sorted(docs, key=key_fn)
