"""Server-side content-addressed blob stores (the gitrest/libgit2 role).

Two implementations of one surface — ``put(bytes) -> id``,
``get(id) -> bytes``, ``has(id)`` — plus shared usage counters so tests
and ops can assert dedup/handle-reuse behavior:

- :class:`DbBlobStore`: blobs in the in-memory db (test default).
- :class:`NativeBlobStore`: the C++ chunk store (native/chunkstore.cpp,
  sha256 fan-out, tmp+rename crash safety) — the production path, used
  whenever the server is given a storage directory.
"""

from __future__ import annotations

import hashlib

from .core import InMemoryDb


class BlobStoreStats:
    def __init__(self):
        self.puts = 0  # put() calls
        self.new_blobs = 0  # puts that stored new content
        self.deduped = 0  # puts that hit existing content

    def as_dict(self) -> dict:
        return {"puts": self.puts, "new_blobs": self.new_blobs,
                "deduped": self.deduped}


class DbBlobStore:
    def __init__(self, db: InMemoryDb, collection: str = "blobs"):
        self._db = db
        self._col = collection
        self.stats = BlobStoreStats()

    def put(self, content: bytes) -> str:
        blob_id = hashlib.sha256(content).hexdigest()
        self.stats.puts += 1
        if self._db.find_one(self._col, blob_id) is None:
            self.stats.new_blobs += 1
            self._db.upsert(self._col, blob_id, {"hex": content.hex()})
        else:
            self.stats.deduped += 1
        return blob_id

    def get(self, blob_id: str) -> bytes:
        doc = self._db.find_one(self._col, blob_id)
        if doc is None:
            raise KeyError(f"unknown blob {blob_id}")
        return bytes.fromhex(doc["hex"])

    def has(self, blob_id: str) -> bool:
        return self._db.find_one(self._col, blob_id) is not None

    def delete(self, blob_id: str) -> bool:
        """Unlink one blob (history-plane chunk GC). Returns whether it
        existed. ONLY the GC may call this — deletion is safe exactly
        when no ref-reachable commit names the chunk."""
        col = self._db.collection(self._col)
        return col.pop(blob_id, None) is not None


class NativeBlobStore:
    def __init__(self, directory: str):
        from ..native import NativeChunkStore

        self._cas = NativeChunkStore(directory)
        self._dir = directory
        self.stats = BlobStoreStats()

    def put(self, content: bytes) -> str:
        self.stats.puts += 1
        blob_id = hashlib.sha256(content).hexdigest()
        if self._cas.has(blob_id):
            self.stats.deduped += 1
        else:
            self.stats.new_blobs += 1
        stored = self._cas.put(content)
        assert stored == blob_id, "host/native hash disagreement"
        return stored

    def get(self, blob_id: str) -> bytes:
        return self._cas.get(blob_id)

    def has(self, blob_id: str) -> bool:
        return self._cas.has(blob_id)

    def delete(self, blob_id: str) -> bool:
        """Unlink one blob from the sha-fan-out object layout
        (``dir/aa/rest``) — the native store exposes no remove, and GC
        runs host-side anyway. Returns whether the blob existed."""
        import os

        path = os.path.join(self._dir, blob_id[:2], blob_id[2:])
        try:
            os.unlink(path)
            return True
        except FileNotFoundError:
            return False

    def close(self) -> None:
        self._cas.close()
