"""Standalone storage process: blob/tree/commit/ref RPCs + read cache.

Ref: the reference's storage micro-services — gitrest (the object
store, server/gitrest/src/routes/git) behind historian (the caching
proxy, server/historian, services-client/src/historian.ts:29) — run as
their own deployments; the ordering service and every client reach
summaries only through them. This process is both roles in one: the
native C++ chunk store holds blobs/trees/commits (content-addressed,
crash-safe), GitStore holds the commit DAG + durable refs, and an LRU
over blob reads is the historian cache (hit stats served over RPC).

Wire protocol: the framed JSON request/response used by the rest of the
service (front_end.py framing; every request carries a ``rid`` echoed
in the reply).

Deployment:

    python -m fluidframework_tpu.service.storage_server --dir DATA \
        [--port N]

The ordering core connects with ``front_end --storage-server PORT``;
clients then boot from the doc's named ref via this process.
"""

from __future__ import annotations

import argparse
import asyncio
import functools
import json
from typing import Optional

from .blob_store import NativeBlobStore
from .front_end import _encode_frame, _read_body
from .git_store import GitStore, head_ref
from .summary_trees import materialize_tree, upload_summary_obj

CACHE_SIZE = 4096


class StorageService:
    """The RPC surface, transport-independent (tests drive it directly)."""

    def __init__(self, directory: str):
        import os

        os.makedirs(directory, exist_ok=True)
        self.blobs = NativeBlobStore(directory)
        from ..native.oplog import NativeOpLog

        self.git = GitStore(self.blobs,
                            refs_log=NativeOpLog(directory + "/refs"))
        self.stats = {"blobs_written": 0, "trees_written": 0,
                      "handles_reused": 0}
        # historian-role read cache: blobs are content-addressed and
        # immutable, so an LRU needs no invalidation ever
        self._cached_get = functools.lru_cache(maxsize=CACHE_SIZE)(
            self.blobs.get)

    def read_blob(self, blob_id: str) -> bytes:
        return self._cached_get(blob_id)

    def write_blob(self, content: bytes) -> str:
        return self.blobs.put(content)

    def upload_summary(self, tenant: str, doc: str, summary,
                       parent: Optional[str]) -> dict:
        """Store a summary as tree objects + an (unacked) commit whose
        parent is the prior version's commit; returns the version
        record. The commit joins the ref chain only when the scribe
        acks it (commit_ref)."""
        from ..protocol.summary import (
            SummaryAttachment,
            SummaryBlob,
            SummaryHandle,
            SummaryTree,
            is_summary_wire,
            summary_from_wire,
        )

        if is_summary_wire(summary):
            summary = summary_from_wire(summary)
        parent_meta = {}
        parent_root = None
        if parent is not None:
            pc = self.git.read_commit(parent)
            parent_meta = pc.get("meta", {})
            parent_root = {"k": "tree", "id": pc["tree"]}
        if isinstance(summary, (SummaryTree, SummaryBlob, SummaryHandle,
                                SummaryAttachment)):
            class _CountingBlobs:
                put = staticmethod(self.blobs.put)
                get = staticmethod(self.read_blob)
            root = upload_summary_obj(_CountingBlobs, summary, parent_root,
                                      self.stats)
            tree_id = root["id"]
        else:
            # legacy monolithic dict summary
            tree_id = self.blobs.put(json.dumps(summary).encode())
        n = parent_meta.get("n", -1) + 1
        commit_id = self.git.write_commit(
            tree_id, [parent] if parent else [],
            meta={"n": n, "tenant": tenant, "doc": doc})
        return {"id": commit_id,
                "record": {"n": n, "tree_id": tree_id, "parent": parent}}

    def commit_ref(self, tenant: str, doc: str, commit_id: str) -> None:
        """Advance the doc's named head — the scribe-ack ref update."""
        self.git.read_commit(commit_id)  # refuse dangling refs
        self.git.set_ref(head_ref(tenant, doc), commit_id)

    def get_ref(self, tenant: str, doc: str) -> Optional[str]:
        return self.git.get_ref(head_ref(tenant, doc))

    def get_versions(self, tenant: str, doc: str, count: int = 1) -> list:
        head = self.get_ref(tenant, doc)
        if head is None:
            return []
        return [{"id": c["id"], "tree_id": c["tree"]}
                for c in self.git.history(head, limit=count)]

    def history(self, tenant: str, doc: str, count: int = 50) -> list:
        head = self.get_ref(tenant, doc)
        return [] if head is None else self.git.history(head, limit=count)

    def get_tree(self, tenant: str, doc: str,
                 version: Optional[dict] = None):
        if version is None:
            versions = self.get_versions(tenant, doc, 1)
            if not versions:
                return None
            version = versions[0]
        raw = json.loads(self.read_blob(version["tree_id"]).decode())
        if raw.get("t") == "snapcols":
            from .summary_trees import materialize_snapcols

            return materialize_snapcols(self.read_blob, raw)
        if raw.get("t") != "tree":
            return raw  # legacy single-blob summary
        return materialize_tree(self.read_blob,
                                {"k": "tree", "id": version["tree_id"]})

    def cache_stats(self) -> dict:
        info = self._cached_get.cache_info()
        return {"hits": info.hits, "misses": info.misses,
                "cached": info.currsize, **self.stats,
                **self.blobs.stats.as_dict()}

    # ------------------------------------------------------------ dispatch

    def handle(self, frame: dict) -> dict:
        t = frame.get("t")
        tenant, doc = frame.get("tenant"), frame.get("doc")
        if t == "read_blob":
            return {"t": "blob", "hex": self.read_blob(frame["id"]).hex()}
        if t == "write_blob":
            return {"t": "blob_id",
                    "id": self.write_blob(bytes.fromhex(frame["hex"]))}
        if t == "upload_summary":
            out = self.upload_summary(tenant, doc, frame["summary"],
                                      frame.get("parent"))
            return {"t": "version_id", **out}
        if t == "commit_ref":
            self.commit_ref(tenant, doc, frame["id"])
            return {"t": "ok"}
        if t == "get_ref":
            return {"t": "ref", "id": self.get_ref(tenant, doc)}
        if t == "get_versions":
            return {"t": "versions",
                    "versions": self.get_versions(tenant, doc,
                                                  frame.get("count", 1))}
        if t == "history":
            return {"t": "history",
                    "commits": self.history(tenant, doc,
                                            frame.get("count", 50))}
        if t == "get_tree":
            return {"t": "tree",
                    "tree": self.get_tree(tenant, doc,
                                          frame.get("version"))}
        if t == "stats":
            return {"t": "stats", "stats": self.cache_stats()}
        raise ValueError(f"unknown storage rpc {t!r}")


class StorageServer:
    def __init__(self, directory: str, host: str = "127.0.0.1",
                 port: int = 0, table_door=None):
        self.service = StorageService(directory)
        self.host, self.port = host, port
        # placement table door (service/table_client.TableDoorService):
        # when set, ``admin_table_*`` frames are served on THIS socket —
        # the placement host's flock keeps serializing every table
        # write, remote host groups just reach it over the wire
        self.table_door = table_door

    async def _handle_conn(self, reader, writer) -> None:
        try:
            while True:
                body = await _read_body(reader)
                if body is None:
                    break
                frame = json.loads(body.decode())
                rid = frame.get("rid")
                try:
                    if self.table_door is not None and str(
                            frame.get("t", "")).startswith("admin_table_"):
                        reply = self.table_door.handle(frame)
                    else:
                        reply = self.service.handle(frame)
                except Exception as e:  # noqa: BLE001 — reply, don't die
                    reply = {"t": "error", "message": str(e)}
                reply["rid"] = rid
                writer.write(_encode_frame(reply))
                await writer.drain()
        except (ValueError, json.JSONDecodeError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def serve_forever(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def start():
            server = await asyncio.start_server(
                self._handle_conn, self.host, self.port, backlog=256)
            self.port = server.sockets[0].getsockname()[1]

        loop.run_until_complete(start())
        print(f"LISTENING {self.host}:{self.port}", flush=True)
        loop.run_forever()


def main() -> None:
    p = argparse.ArgumentParser(description="Fluid TPU storage process")
    p.add_argument("--dir", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--table-dir", default=None,
                   help="serve the placement table door (admin_table_*) "
                        "over this shard dir's flocked lease/epoch files")
    p.add_argument("--shards", type=int, default=0,
                   help="partition count for the table door")
    p.add_argument("--lease-ttl", type=float, default=None,
                   help="lease TTL for the table door's PlacementDir")
    args = p.parse_args()
    door = None
    if args.table_dir:
        from .placement import DEFAULT_TTL_S
        from .table_client import TableDoorService

        door = TableDoorService(
            args.table_dir, args.shards,
            ttl_s=(args.lease_ttl if args.lease_ttl is not None
                   else DEFAULT_TTL_S))
    StorageServer(args.dir, host=args.host, port=args.port,
                  table_door=door).serve_forever()


if __name__ == "__main__":
    main()
