"""Scribe: the durable protocol replica + summary commit validator.

Ref: lambdas/src/scribe/lambda.ts:39,71,113 — consumes the sequenced
stream, maintains a server-side ProtocolOpHandler replica (the same class
the client runs — protocol-base is shared code), and on a client
``summarize`` op validates the proposed summary's parentage against the
last acked head (summaryWriter.ts:69-192 writeClientSummary) before
acknowledging it into the total order. Acks/nacks travel BACK through the
sequencer (send-to-deli), so every client sees them at the same stream
position.

Storage model: clients upload summary trees to the content-addressed
store first (driver upload_summary → version record with parent link);
scribe checks the chain and flips the version's ``acked`` flag — the
analog of scribe creating the git commit + ref update.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..protocol.messages import (
    DocumentMessage,
    MessageType,
    SequencedDocumentMessage,
)
from ..protocol.quorum import ProtocolOpHandler
from .core import InMemoryDb, QueuedMessage, summary_versions_collection
from .deli import RawMessage

SCRIBE_CHECKPOINT_COLLECTION = "scribe-checkpoints"


class ScribeLambda:
    def __init__(
        self,
        tenant_id: str,
        document_id: str,
        db: InMemoryDb,
        send_to_deli: Callable[[RawMessage], None],
        checkpoint: Optional[dict] = None,
        on_summary_committed: Optional[Callable[[int], None]] = None,
        persist_version: Optional[Callable[[str, dict], None]] = None,
    ):
        self.tenant_id = tenant_id
        self.document_id = document_id
        self._db = db
        self._send_to_deli = send_to_deli
        # fires with the committed summary's capture seq — the hook log
        # retention hangs off (ops the summary covers may truncate)
        self._on_committed = on_summary_committed
        # persists the acked version RECORD outside the db (the durable
        # log), so summaries survive full process death — without it a
        # truncated log + dead db leaves the doc unbootable
        self._persist_version = persist_version
        self._versions_col = summary_versions_collection(tenant_id, document_id)
        if checkpoint:
            self.protocol = ProtocolOpHandler.load(checkpoint["protocol"])
            self.last_summary_head: Optional[str] = checkpoint["head"]
            self.last_offset: int = checkpoint["offset"]
        else:
            self.protocol = ProtocolOpHandler()
            self.last_summary_head = None
            self.last_offset = -1

    def handler(self, message: QueuedMessage) -> None:
        if message.offset <= self.last_offset:
            return  # replay after restart
        self.last_offset = message.offset
        abatch = message.value.get("abatch")
        if abatch is not None:
            # array-lane run: plain operations by construction
            self.protocol.observe_operation_run(
                abatch.base_seq, abatch.last_seq, abatch.last_msn)
            return
        batch = message.value.get("boxcar")
        if batch is not None:
            # boxcars are plain-operation runs by construction (the deli
            # fast lane emits them); the replica only needs the window
            # advanced once per run — proposals the window passes settle
            # identically (values are order-independent; approval_seq is
            # not persisted in snapshots)
            self.protocol.observe_operation_run(
                batch[0].sequence_number,
                batch[-1].sequence_number,
                batch[-1].minimum_sequence_number,
            )
            return
        msg: SequencedDocumentMessage = message.value["message"]
        # deli crash-replay re-appends already-sequenced records at NEW
        # topic offsets, so the offset gate above doesn't catch them;
        # process_message dedupes by seq and reports it — an already-acked
        # summarize must not re-run _handle_summarize (it would emit a
        # spurious nack: parent no longer matches head)
        applied = self.protocol.process_message(msg)
        if msg.type == MessageType.SUMMARIZE and applied:
            self._handle_summarize(msg)

    def close(self) -> None:
        pass

    # ------------------------------------------------------------ summaries

    def _handle_summarize(self, msg: SequencedDocumentMessage) -> None:
        contents = msg.contents or {}
        handle = contents.get("handle")
        parent = contents.get("parent")
        head = contents.get("head")
        version = self._db.find_one(self._versions_col, handle) if handle else None

        if version is None:
            self._nack(msg, f"unknown summary handle {handle!r}")
            return
        if parent != self.last_summary_head:
            # parent must be the last acked head (summaryWriter.ts:85)
            self._nack(
                msg,
                f"summary parent {parent!r} does not match head "
                f"{self.last_summary_head!r}",
            )
            return
        if not isinstance(head, int) or head > msg.sequence_number:
            # a summary claiming to cover sequence numbers beyond the
            # stream would poison every future boot (clients would resume
            # at the bogus seq and drop real ops as duplicates)
            self._nack(msg, f"summary head {head!r} is ahead of the stream")
            return

        self.commit_version(handle, head, version=version)
        self._send_to_deli(
            RawMessage(
                tenant_id=self.tenant_id,
                document_id=self.document_id,
                client_id=None,
                operation=DocumentMessage(
                    client_sequence_number=-1,
                    reference_sequence_number=-1,
                    type=MessageType.SUMMARY_ACK,
                    contents={
                        "handle": handle,
                        "summarySequenceNumber": msg.sequence_number,
                    },
                ),
            )
        )

    def commit_version(self, handle: str, head: int,
                       version: Optional[dict] = None) -> None:
        """Commit a version as the acked head — the single ref-update path.

        Used by both client summaries (_handle_summarize) and service
        summaries (service_summarizer.py): flips acked, appends to the
        durable versions topic, updates the head, and fires the retention
        callback. Writing around this (e.g. upserting acked=True directly
        in the db) makes the summary vanish on full process death and
        never advances log retention."""
        if version is None:
            version = self._db.find_one(self._versions_col, handle)
            if version is None:
                raise KeyError(f"unknown summary handle {handle!r}")
        already_acked = bool(version.get("acked"))
        # the capture seq rides the acked record: retention clamps its
        # trim to the latest acked version's seq, so a booting client's
        # backfill base (the snapshot's seq) is always ≥ the retained base
        acked_version = dict(version, acked=True, seq=head)
        self._db.upsert(self._versions_col, handle, acked_version)
        self.last_summary_head = handle
        if self._persist_version is not None and not already_acked:
            # a post-restart replay re-commits an already-restored
            # version; appending again would grow the durable topic
            # with duplicates on every restart
            self._persist_version(handle, acked_version)
        if self._on_committed is not None:
            self._on_committed(head)

    def _nack(self, msg: SequencedDocumentMessage, reason: str) -> None:
        # boot visibility needs no marking here: only versions scribe acks
        # (acked=True) are served by storage get_versions
        handle = (msg.contents or {}).get("handle")
        self._send_to_deli(
            RawMessage(
                tenant_id=self.tenant_id,
                document_id=self.document_id,
                client_id=None,
                operation=DocumentMessage(
                    client_sequence_number=-1,
                    reference_sequence_number=-1,
                    type=MessageType.SUMMARY_NACK,
                    contents={
                        "handle": handle,
                        "summarySequenceNumber": msg.sequence_number,
                        "message": reason,
                    },
                ),
            )
        )

    # ----------------------------------------------------------- checkpoint

    def checkpoint_state(self) -> dict:
        return {
            "protocol": self.protocol.snapshot(),
            "head": self.last_summary_head,
            "offset": self.last_offset,
        }

    def checkpoint(self) -> None:
        self._db.upsert(
            SCRIBE_CHECKPOINT_COLLECTION,
            f"{self.tenant_id}/{self.document_id}",
            {"state": self.checkpoint_state()},
        )
