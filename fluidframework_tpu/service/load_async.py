"""Asyncio load worker: thousands of socket clients per process.

Ref: packages/test/service-load-test/src/nodeStressTest.ts — the
reference's orchestrator spawns runner processes, each hosting many
socket.io clients on one Node event loop. The thread-per-connection
driver stack (driver/network.py) is the right shape for a real client
app but caps a load WORKER at a few hundred connections; this worker
hosts each client as an asyncio task on one loop, which is what makes
the BASELINE config-4 geometry (1k docs × 10 clients = 10k sockets)
drivable from a handful of processes.

Clients speak the production wire protocol (front_end.py): JSON connect
handshake, binwire submit boxcars, binwire ops broadcasts. Each client
submits ``rounds`` boxcars of ``batch`` ops paced at ``rate_hz`` rounds
per second (absolute schedule, so pacing error does not accumulate), and
samples op-ack latency once per boxcar (submit → own last-op broadcast).

One JSON result line on stdout, same shape as load_gen's thread worker:
``{"ops", "acked", "seconds", "lat_ms", "hops"}``.
"""

from __future__ import annotations

import asyncio
import json
import random
import socket as _socket
import time
from collections import defaultdict
from typing import Optional

from ..protocol import binwire
from ..protocol.messages import DocumentMessage, MessageType, TraceHop
from ..utils.telemetry import HOP_ACK, HOP_SUBMIT, hop_pairs
from .synthetic import SyntheticEditor


def _op_from_fields(d: dict) -> DocumentMessage:
    """Rebuild a DocumentMessage from the nack's echoed op fields (the
    wire dict has no _kind discriminator; traces are dropped — a
    resubmitted boxcar re-arms tracing itself if sampled)."""
    return DocumentMessage(
        client_sequence_number=d["client_sequence_number"],
        reference_sequence_number=d["reference_sequence_number"],
        type=MessageType(d["type"]),
        contents=d.get("contents"),
        metadata=d.get("metadata"))


class _AsyncClient:
    """One synthetic client: connection + editor + pacing schedule."""

    def __init__(self, host: str, port: int, tenant: str, doc: str,
                 rng: random.Random, batch: int, rounds: int,
                 trace_sample_n: int = 0):
        self.host, self.port = host, port
        self.tenant, self.doc = tenant, doc
        self.editor = SyntheticEditor(rng)
        self.batch = batch
        self.rounds = rounds
        #: 1-in-N columnar boxcar tracing (0 = disarmed): sampled frames
        #: carry the hoptail, and _observe folds it into the full
        #: per-tier breakdown instead of the two-leg deli split
        self.trace_sample_n = trace_sample_n
        # random phase spreads the fleet across the round period —
        # without it every client submits at the same instant and the
        # measurement becomes burst queueing, not steady-state load
        self.phase = rng.random()
        self.client_id: Optional[str] = None
        # boxcar-last cseq → (perf t0, wall t0)
        self.pending: dict[int, tuple] = {}
        self.lat_ms: list[float] = []
        self.acked = 0
        self.submitted = 0
        self.nacked = 0
        # admission-shed retry state: shed nacks echo the op back with
        # retry_after_ms; held here (keyed by cseq so resubmission can
        # restore clientSeq order) until the jittered deadline
        self.shed = 0
        self._rng = rng
        self._shed_ops: dict[int, dict] = {}
        self._resubmit_at: Optional[float] = None
        # per-hop splits: the two-leg deli split from the record's deli
        # stamp, or the full hoptail breakdown on sampled cols frames
        self.hops: dict[str, list] = defaultdict(list)
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self.error: Optional[str] = None

    async def connect(self) -> None:
        self.reader, self.writer = await asyncio.open_connection(
            self.host, self.port)
        sock = self.writer.get_extra_info("socket")
        if sock is not None:
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        body = json.dumps({"t": "connect", "tenant": self.tenant,
                           "doc": self.doc, "bin": 1, "rid": 1},
                          separators=(",", ":")).encode()
        self.writer.write(len(body).to_bytes(4, "big") + body)
        await self.writer.drain()
        # the connected reply may be preceded by pushed frames
        while self.client_id is None:
            frame = await self._read()
            if frame is None:
                raise ConnectionError("closed during handshake")

    async def _read(self):
        """Read one frame; dispatch pushes; return JSON reply dicts."""
        header = await self.reader.readexactly(4)
        body = await self.reader.readexactly(int.from_bytes(header, "big"))
        if binwire.is_binary(body):
            self._observe(body)
            return {}
        frame = json.loads(body.decode())
        if frame.get("t") == "connected":
            self.client_id = frame["clientId"]
        elif frame.get("t") == "nack":
            self._on_nack(frame.get("nack") or {})
        elif frame.get("t") == "error":
            raise RuntimeError(frame.get("message"))
        return frame

    def _on_nack(self, d: dict) -> None:
        retry_ms = d.get("retry_after_ms")
        op = d.get("operation")
        if not retry_ms or op is None:
            self.nacked += 1
            return
        # shed: honor the server's backoff with jitter, then resubmit.
        # The pending t0 stays untouched — the sampled latency includes
        # the backoff, which is exactly what an overloaded user feels.
        self.shed += 1
        self._shed_ops[op["client_sequence_number"]] = op
        self._resubmit_at = max(
            self._resubmit_at or 0.0,
            time.perf_counter()
            + (retry_ms / 1000.0) * (1.0 + 0.5 * self._rng.random()))

    async def shed_flush_loop(self) -> None:
        """Resubmit shed ops (cseq order) once their deadline passes;
        a re-shed just lands them back here with a fresh deadline."""
        try:
            while True:
                await asyncio.sleep(0.02)
                if not self._shed_ops:
                    continue
                at = self._resubmit_at
                if at is not None and time.perf_counter() < at:
                    continue
                items = sorted(self._shed_ops.items())
                self._shed_ops = {}
                self._resubmit_at = None
                ops = [_op_from_fields(d) for _, d in items]
                body = binwire.encode_submit_columns(ops)
                if body is None:
                    body = binwire.encode_submit(ops)
                self.writer.write(binwire.frame(body))
                await self.writer.drain()
        except (asyncio.CancelledError, ConnectionResetError, OSError):
            pass

    def _observe(self, body: bytes) -> None:
        """Track a broadcast via the lazy scan — no message objects.

        The editor only needs its visible-length lower bound and the
        latest ref seq; full decode of every subscriber's copy was the
        workers' largest CPU item at the knee."""
        me = self.client_id
        ed = self.editor
        # a sampled cols frame carries the accumulated hoptail at its
        # end (one boxcar = one submitting client, so the hops are ours
        # exactly when a record below matches our pending cseq)
        frame_hops = (binwire.read_hoptail(body)
                      if len(body) >= 2
                      and body[1] == binwire.FT_COLS_OPS else [])
        for cid, seq, cseq, deli_ts, delta in binwire.scan_ops(body):
            ed.ref_seq = seq
            if cid is None or me is None:
                continue
            if cid == me:
                self.acked += 1
                t0 = self.pending.pop(cseq, None)
                if t0 is not None:
                    now = time.perf_counter()
                    self.lat_ms.append((now - t0[0]) * 1e3)
                    wall = time.time()
                    if frame_hops:
                        # full breakdown: local submit/ack close the
                        # chain; the frame's own submit stamp (later in
                        # the list) wins over the local t0 fallback
                        for name, ms in hop_pairs(
                                [(HOP_SUBMIT, t0[1])] + list(frame_hops)
                                + [(HOP_ACK, wall)]):
                            self.hops[name].append(ms)
                    elif deli_ts is not None:
                        self.hops["submit_to_deli"].append(
                            (deli_ts - t0[1]) * 1e3)
                        self.hops["deli_to_ack"].append(
                            (wall - deli_ts) * 1e3)
            elif delta > 0:
                ed.length += delta
            elif delta < 0:
                ed.length = max(0, ed.length + delta)

    async def read_loop(self) -> None:
        try:
            while True:
                await self._read()
        except (asyncio.IncompleteReadError, ConnectionResetError,
                asyncio.CancelledError):
            pass
        except Exception as e:  # server error frame etc. — a silently
            # dead reader would surface only as a missing-acks timeout
            # with the actual cause lost (stderr is discarded)
            self.error = f"{type(e).__name__}: {e}"

    async def run_rounds(self, t0: float, rate_hz: float) -> None:
        for i in range(self.rounds):
            target = t0 + (i + self.phase) / rate_hz
            delay = target - time.perf_counter()
            if delay > 0:
                await asyncio.sleep(delay)
            ops = self.editor.next_ops(self.batch)
            # latency is timed per boxcar on its last op. Columnar
            # frames carry no traces — the deli stamp timestamp in the
            # sequenced frame IS the deli time for every record
            # (scan_ops yields it), so the hop split needs no per-op
            # trace. The rec-frame fallback keeps the client trace
            # stamp: deli's SAMPLED tracing only stamps pre-traced ops
            # (deli.py fast lane), and the stamp is what brings the
            # deli timestamp back for the hop split (submit→deli,
            # deli→ack) computed locally on ack
            body = binwire.encode_submit_columns(ops)
            if body is None:
                ops[-1].traces.append(TraceHop(
                    service="client", action="submit",
                    timestamp=time.time()))
                body = binwire.encode_submit(ops)
            elif self.trace_sample_n \
                    and i % self.trace_sample_n == 0:
                # arm the hoptail on every Nth columnar boxcar: tiers
                # append their hops in place and the ack broadcast
                # brings the chain back for the local breakdown
                body = binwire.append_hop(
                    body, HOP_SUBMIT, time.time())
            self.pending[ops[-1].client_sequence_number] = (
                time.perf_counter(), time.time())
            self.writer.write(binwire.frame(body))
            self.submitted += len(ops)
            await self.writer.drain()

    def close(self) -> None:
        try:
            self.writer.close()
        except Exception:
            pass


async def run_load(host: str, port: int, n_docs: int, clients_per_doc: int,
                   rounds: int, batch: int, rate_hz: float, seed: int,
                   doc_prefix: str, tenant: str = "bench",
                   connect_concurrency: int = 64,
                   timeout: float = 120.0,
                   start_at: Optional[float] = None,
                   trace_sample_n: int = 0) -> dict:
    rng = random.Random(seed)
    clients = [
        _AsyncClient(host, port, tenant, f"{doc_prefix}{d}",
                     random.Random(rng.random()), batch, rounds,
                     trace_sample_n=trace_sample_n)
        for d in range(n_docs) for _ in range(clients_per_doc)
    ]
    # staged connects: a 10k-connection stampede overruns the listen
    # backlog and makes join storms the measurement instead of steady load
    sem = asyncio.Semaphore(connect_concurrency)

    async def staged_connect(c):
        async with sem:
            await c.connect()

    await asyncio.gather(*(staged_connect(c) for c in clients))
    readers = [asyncio.ensure_future(c.read_loop()) for c in clients]
    shed_flushers = [asyncio.ensure_future(c.shed_flush_loop())
                     for c in clients]

    late_s = 0.0
    if start_at is not None:
        # cross-worker synchronized start: the orchestrator hands every
        # worker the same wall-clock instant so no worker's trial runs
        # against another worker's connect storm. If connects overran
        # the margin, the trial is TAINTED (it measures the join storm,
        # not steady load) — report how late so the orchestrator can
        # retry with a wider margin instead of publishing the taint.
        delay = start_at - time.time()
        if delay > 0:
            await asyncio.sleep(delay)
        else:
            late_s = -delay
    t0 = time.perf_counter()
    await asyncio.gather(*(c.run_rounds(t0, rate_hz) for c in clients))
    expected = sum(c.submitted for c in clients)
    deadline = time.time() + timeout
    while time.time() < deadline:
        if sum(c.acked for c in clients) >= expected:
            break
        await asyncio.sleep(0.01)
    seconds = time.perf_counter() - t0

    lat = []
    hops: dict[str, list] = defaultdict(list)
    for c in clients:
        lat.extend(c.lat_ms)
        for name, vals in c.hops.items():
            hops[name].extend(vals)
    hops = dict(hops)
    for r in readers:
        r.cancel()
    for f in shed_flushers:
        f.cancel()
    for c in clients:
        c.close()
    return {
        "ops": expected,
        "acked": sum(c.acked for c in clients),
        "seconds": seconds,
        "lat_ms": lat,
        "hops": hops,
        "shed": sum(c.shed for c in clients),
        "errors": [c.error for c in clients if c.error],
        "late_s": round(late_s, 1),
    }


def main() -> None:
    import argparse
    import gc
    import sys

    p = argparse.ArgumentParser(description="asyncio socket load worker")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--docs", type=int, default=32)
    p.add_argument("--clients-per-doc", type=int, default=2)
    p.add_argument("--rounds", type=int, default=20)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--rate", type=float, default=2.0,
                   help="boxcar rounds per second per client")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--doc-prefix", default="netdoc")
    p.add_argument("--tenant", default="bench")
    p.add_argument("--timeout", type=float, default=120.0,
                   help="ack-wait ceiling after the rounds complete")
    p.add_argument("--start-at", type=float, default=None,
                   help="wall-clock epoch at which to start submitting")
    p.add_argument("--trace-sample-n", type=int, default=16,
                   help="arm the hoptail on every Nth columnar boxcar "
                        "(0 disables tracing)")
    args = p.parse_args()

    # the worker's op path allocates acyclic graphs only; the cycle
    # collector's periodic scans would show up directly as ack-latency
    # spikes in the measurement (the process is short-lived — leaked
    # cycles die with it)
    gc.collect()
    gc.freeze()
    gc.disable()
    result = asyncio.run(run_load(
        args.host, args.port, args.docs, args.clients_per_doc,
        args.rounds, args.batch, args.rate, args.seed, args.doc_prefix,
        tenant=args.tenant, timeout=args.timeout,
        start_at=args.start_at, trace_sample_n=args.trace_sample_n))
    json.dump(result, sys.stdout)
    print()


if __name__ == "__main__":
    main()
