"""Array-native boxcars: the deli-tpu marshal (SURVEY §7).

Ref role: the reference's pipeline carries one JS object per op end to
end (IBoxcarMessage of IDocumentMessages), which caps a Python port of
the pipeline at dict-walk speed. This module is the TPU-first redesign
the survey prescribes: a client's submitted boxcar of merge-tree text
ops rides the pipeline as STRUCTURE-OF-ARRAYS — int32 fields + one
concatenated text blob — so deli tickets it with numpy comparisons, the
applier bulk-loads it into device staging without touching a per-op
dict, and only COLD consumers (REST backfill, summarizer reads, legacy
connections) materialize per-op message objects, lazily and cached.

The array lane is an optimization, not a fork of semantics: an
``ArrayBoxcar`` is exactly equivalent to a ``RawBoxcar`` of chanop
``DocumentMessage``s (``to_raw_boxcar``), deli's array ticketing is
fuzz-checked against the scalar lane, and a ``SequencedArrayBatch``
materializes byte-identical ``SequencedDocumentMessage``s.

Op kinds (matching the merge-tree wire ops, dds/sequence → chanop):

- 0 insert:   a = pos;   text run in ``text[text_off[i]:text_off[i+1]]``
- 1 remove:   a = start, b = end
- 2 annotate: a = start, b = end, props in ``props[i]``
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..protocol.messages import (
    DocumentMessage,
    MessageType,
    SequencedDocumentMessage,
)

KIND_INSERT = 0
KIND_REMOVE = 1
KIND_ANNOTATE = 2


@dataclass
class ArrayBoxcar:
    """One client's submitted boxcar of text chanops, SoA form.

    All ops target ONE channel (``ds_id``/``channel_id``) — the shape
    the synthetic load and text-heavy apps produce; anything else rides
    the general dict boxcar."""

    tenant_id: str
    document_id: str
    client_id: str
    ds_id: str
    channel_id: str
    kind: np.ndarray      # int8 [n]
    a: np.ndarray         # int32 [n] pos/start
    b: np.ndarray         # int32 [n] end (removes/annotates)
    cseq: np.ndarray      # int32 [n]
    rseq: np.ndarray      # int32 [n]
    text: str             # concatenated insert payloads
    text_off: np.ndarray  # int32 [n+1] offsets into text (non-inserts 0-len)
    props: Optional[list] = None  # per-op props dict or None (annotates)
    timestamp: float = 0.0
    # raw binwire column section the boxcar arrived as (columnar ingress):
    # broadcast stamping splices these bytes verbatim instead of
    # re-encoding. Transport cache only — deliberately OUTSIDE the
    # durable codecs below (a replayed boxcar re-encodes on demand).
    wire_cols: Optional[bytes] = field(default=None, repr=False,
                                       compare=False)
    # accumulated trace hops [(hop_id, ts), ...] from the frame's
    # hoptail (sampled boxcars only; None when tracing is unarmed).
    # Each tier APPENDS its hop in place; the egress encode packs the
    # list back into the broadcast frame's hoptail. Transport-only,
    # like wire_cols: deliberately outside the durable codecs.
    hops: Optional[list] = field(default=None, repr=False, compare=False)

    @property
    def n(self) -> int:
        return len(self.kind)

    def wire_op(self, i: int) -> dict:
        k = int(self.kind[i])
        if k == KIND_INSERT:
            return {"type": 0, "pos": int(self.a[i]),
                    "text": self.text[int(self.text_off[i]):
                                      int(self.text_off[i + 1])]}
        if k == KIND_REMOVE:
            return {"type": 1, "start": int(self.a[i]), "end": int(self.b[i])}
        return {"type": 2, "start": int(self.a[i]), "end": int(self.b[i]),
                "props": dict(self.props[i]) if self.props else {}}

    def contents(self, i: int) -> dict:
        return {"kind": "chanop", "address": self.ds_id,
                "contents": {"address": self.channel_id,
                             "contents": self.wire_op(i)}}

    def to_raw_boxcar(self):
        """The exactly-equivalent dict boxcar (deli scalar fallback)."""
        from .deli import RawBoxcar

        ops = [
            DocumentMessage(
                client_sequence_number=int(self.cseq[i]),
                reference_sequence_number=int(self.rseq[i]),
                type=MessageType.OPERATION,
                contents=self.contents(i))
            for i in range(self.n)
        ]
        return RawBoxcar(tenant_id=self.tenant_id,
                         document_id=self.document_id,
                         client_id=self.client_id, ops=ops,
                         timestamp=self.timestamp)


@dataclass
class SequencedArrayBatch:
    """A ticketed ArrayBoxcar: seqs are ``base_seq + i``; per-op msns.

    ``messages()`` materializes (and caches) the per-op
    SequencedDocumentMessage list for cold consumers."""

    boxcar: ArrayBoxcar
    base_seq: int         # seq of op 0
    msns: np.ndarray      # int64 [n]
    timestamp: float
    _materialized: Optional[list] = field(default=None, repr=False)

    @property
    def n(self) -> int:
        return self.boxcar.n

    @property
    def last_seq(self) -> int:
        return self.base_seq + self.n - 1

    @property
    def last_msn(self) -> int:
        return int(self.msns[-1])

    def message(self, i: int) -> SequencedDocumentMessage:
        if self._materialized is not None:
            return self._materialized[i]
        box = self.boxcar
        return SequencedDocumentMessage(
            client_id=box.client_id,
            sequence_number=self.base_seq + i,
            minimum_sequence_number=int(self.msns[i]),
            client_sequence_number=int(box.cseq[i]),
            reference_sequence_number=int(box.rseq[i]),
            type=MessageType.OPERATION,
            contents=box.contents(i),
            timestamp=self.timestamp,
        )

    def messages(self) -> list:
        if self._materialized is None:
            self._materialized = [self.message(i) for i in range(self.n)]
        return self._materialized


# ------------------------------------------------------- durable-log codec
# Array fields serialize as base64 of their little-endian bytes —
# json-encoding an int list costs ~10× a b64encode of the same data,
# and these records ARE the durable hot path in the split deployment.

import base64 as _b64


def _enc(arr: np.ndarray) -> str:
    return _b64.b64encode(np.ascontiguousarray(arr).tobytes()).decode()


def _dec(s: str, dtype) -> np.ndarray:
    return np.frombuffer(_b64.b64decode(s), dtype=dtype)


def _boxcar_to_dict(box: ArrayBoxcar) -> dict:
    return {
        "tenant_id": box.tenant_id, "document_id": box.document_id,
        "client_id": box.client_id, "ds": box.ds_id, "ch": box.channel_id,
        "kind": _enc(box.kind), "a": _enc(box.a), "b": _enc(box.b),
        "cseq": _enc(box.cseq), "rseq": _enc(box.rseq),
        "text": box.text, "text_off": _enc(box.text_off),
        "props": box.props, "timestamp": box.timestamp,
    }


def _boxcar_from_dict(d: dict) -> ArrayBoxcar:
    return ArrayBoxcar(
        tenant_id=d["tenant_id"], document_id=d["document_id"],
        client_id=d["client_id"], ds_id=d["ds"], channel_id=d["ch"],
        kind=_dec(d["kind"], np.int8),
        a=_dec(d["a"], np.int32), b=_dec(d["b"], np.int32),
        cseq=_dec(d["cseq"], np.int32),
        rseq=_dec(d["rseq"], np.int32),
        text=d["text"], text_off=_dec(d["text_off"], np.int32),
        props=d.get("props"), timestamp=d["timestamp"],
    )


def _abatch_to_dict(batch: SequencedArrayBatch) -> dict:
    return {
        "boxcar": _boxcar_to_dict(batch.boxcar),
        "base_seq": batch.base_seq,
        "msns": _enc(batch.msns),
        "timestamp": batch.timestamp,
    }


def _abatch_from_dict(d: dict) -> SequencedArrayBatch:
    return SequencedArrayBatch(
        boxcar=_boxcar_from_dict(d["boxcar"]), base_seq=d["base_seq"],
        msns=_dec(d["msns"], np.int64), timestamp=d["timestamp"],
    )


def _register_codecs() -> None:
    from ..protocol.serialization import register_message_type

    register_message_type("abox", ArrayBoxcar, _boxcar_to_dict,
                          _boxcar_from_dict)
    register_message_type("abatch", SequencedArrayBatch, _abatch_to_dict,
                          _abatch_from_dict)


_register_codecs()
