"""TpuDocumentApplier: batched server-side merge-tree replica farm.

THE TPU-differentiating service component. The reference's server keeps no
document state (architecture.md: server sequences, clients merge) and pays
for it when it needs content — scribe replays whole op logs in JS to build
service summaries (scribe writeServiceSummary, SURVEY §3.4). Here the
service maintains thousands of documents as ONE device-resident
structure-of-arrays batch (ops/doc_state.DocState with a leading doc dim)
and applies every sequenced merge-tree op as a vmapped tensor program
(ops/apply.py), optionally sharded over a ('docs','seg') mesh
(parallel/sharded_apply.py). That turns BASELINE config 5 (10k-doc scribe
replay) into a handful of XLA dispatches.

Semantics guardrails:
- Ops ingest ONLY from the sequenced stream, so the server-side invariants
  hold (every stamp below the incoming seq; tie-break = earliest
  boundary — see ops/apply.py docstring).
- Insert/remove/annotate all stay on the device. Anything the kernel does
  not model (slot-capacity, remove-overlap, or property-table overflow)
  flips the doc to HOST mode: the scalar oracle (mergetree/) replays the
  doc's authoritative op log from scriptorium. This is the
  overflow-to-host escape hatch of SURVEY §7(e).
- Every staged op carries the msn deli stamped on its sequenced message,
  so device zamboni (ops/apply.compact) runs fused after every wave at
  the exact collaboration-window floor — slot usage stays bounded under
  churn instead of growing until escalation.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..mergetree.client import MergeTreeClient
from ..mergetree.ops import AnnotateOp, GroupOp, InsertOp, RemoveOp, op_from_wire
from ..ops.apply import (
    NO_VAL,
    OP_ANNOTATE,
    OP_FIELDS,
    OP_INSERT,
    OP_REMOVE,
    apply_ops_batch,
    compact_batch,
    make_op,
    wave_min_seq,
)
from ..ops.doc_state import FLAG_MARKER, DocState, PropTable, TextArena, decode_state
from ..protocol.messages import MessageType, SequencedDocumentMessage
from ..parallel.placement import DocPlacement

MARKER_GLYPH = "￼"  # arena placeholder byte for markers (flags classify)

# interned id for server/system-originated stamps (never collides with the
# dense per-doc table, which grows upward from 0)
SYSTEM_CLIENT = (1 << 30) - 1


def channel_stream(server, tenant_id: str, document_id: str,
                   ds_id: str, channel_id: str):
    """Extract one channel's merge-tree messages from the document's
    sequenced op log (scriptorium) — the applier's replay source and the
    scribe-replay entry point (BASELINE config 5)."""
    for m in server.get_deltas(tenant_id, document_id, 0, 10**9):
        if m.type != MessageType.OPERATION:
            continue
        env = m.contents
        if not isinstance(env, dict) or env.get("kind") != "chanop":
            continue
        if env["address"] != ds_id:
            continue
        inner = env["contents"]
        if inner.get("address") != channel_id or "attach" in inner:
            continue
        yield replace(m, contents=inner["contents"])


class TpuDocumentApplier:
    """Maintains [D, S] device doc states fed by sequenced op streams."""

    def __init__(
        self,
        max_docs: int = 256,
        max_slots: int = 256,
        ops_per_dispatch: int = 16,
        mesh=None,
    ):
        self.max_docs = max_docs
        self.max_slots = max_slots
        self.K = ops_per_dispatch
        self.placement = DocPlacement(n_shards=1, slots_per_shard=max_docs)
        self.state: DocState = jax.vmap(lambda _: DocState.empty(max_slots))(
            jnp.arange(max_docs)
        )
        self.arenas: list[TextArena] = [TextArena() for _ in range(max_docs)]
        self.prop_table = PropTable()  # shared across docs; ids are dense
        # per-doc dense client interning — collision-free by construction
        # (the round-1 truncated-hash scheme could merge two clients'
        # own-op visibility at the 24-bit birthday bound)
        self._client_ids: dict[int, dict[str, int]] = {}
        self._staged: dict[int, list[np.ndarray]] = {}
        self._host_docs: dict[int, MergeTreeClient] = {}  # escalated docs
        self._doc_keys: dict[int, tuple[str, str]] = {}
        self._mesh = mesh
        if mesh is not None:
            from ..parallel.sharded_apply import make_sharded_step, shard_state

            self.state = shard_state(self.state, mesh)
            self._step = make_sharded_step(mesh)
        else:
            self._step = jax.jit(self._local_step, donate_argnums=(0,))
        self.dispatches = 0
        self.ops_applied = 0
        self.host_escalations = 0

    @staticmethod
    def _local_step(state: DocState, ops: jax.Array):
        state = apply_ops_batch(state, ops)
        state = compact_batch(state, wave_min_seq(ops))
        return state, {}

    # ------------------------------------------------------------- ingest

    def slot_of(self, tenant_id: str, document_id: str) -> int:
        shard, slot = self.placement.place(tenant_id, document_id)
        self._doc_keys.setdefault(slot, (tenant_id, document_id))
        return slot

    def _intern_client(self, slot: int, client_id: Optional[str]) -> int:
        if client_id is None:
            return SYSTEM_CLIENT
        table = self._client_ids.setdefault(slot, {})
        cid = table.get(client_id)
        if cid is None:
            cid = len(table)
            table[client_id] = cid
        return cid

    def ingest(
        self,
        tenant_id: str,
        document_id: str,
        msg: SequencedDocumentMessage,
        wire_op: dict,
    ) -> None:
        """Stage one sequenced merge-tree wire op for batched apply."""
        if isinstance(wire_op, dict) and wire_op.get("type") == "interval":
            return  # interval metadata: no effect on text content
        slot = self.slot_of(tenant_id, document_id)
        if slot in self._host_docs:
            self._apply_host(slot, msg, wire_op)
            return
        ops = self._vectorize(slot, msg, op_from_wire(wire_op))
        if ops is None:
            self._escalate(slot, msg, wire_op)
        else:
            self._staged.setdefault(slot, []).extend(ops)

    def _vectorize(self, slot, msg, op) -> Optional[list[np.ndarray]]:
        if isinstance(op, GroupOp):
            out = []
            for sub in op.ops:
                vecs = self._vectorize(slot, msg, sub)
                if vecs is None:
                    return None
                out.extend(vecs)
            return out
        common = dict(
            seq=msg.sequence_number,
            ref_seq=msg.reference_sequence_number,
            client=self._intern_client(slot, msg.client_id),
            msn=msg.minimum_sequence_number,
        )
        if isinstance(op, InsertOp):
            if op.marker is not None:
                start = self.arenas[slot].append(MARKER_GLYPH)
                tlen = 1
                vecs = [make_op(OP_INSERT, pos=op.pos, text_len=1,
                                text_start=start, flags=FLAG_MARKER, **common)]
            else:
                text = op.text or ""
                start = self.arenas[slot].append(text)
                tlen = len(text)
                vecs = [make_op(OP_INSERT, pos=op.pos, text_len=tlen,
                                text_start=start, **common)]
            # insert-with-props (oracle attaches props to the new segment):
            # at the insert's OWN perspective the visible span
            # [pos, pos+len) is exactly the new slot, so follow-up
            # annotates stamp precisely it
            vecs.extend(self._annotate_vecs(op.pos, op.pos + tlen,
                                            op.props or {}, common))
            return vecs
        if isinstance(op, RemoveOp):
            return [make_op(OP_REMOVE, pos=op.start, end=op.end, **common)]
        if isinstance(op, AnnotateOp):
            return self._annotate_vecs(op.start, op.end, op.props, common)
        return None

    def _annotate_vecs(self, start, end, props: dict, common: dict) -> list:
        # one device op per key; in-order apply gives per-key LWW
        return [
            make_op(
                OP_ANNOTATE, pos=start, end=end,
                key=self.prop_table.intern_key(k),
                val=NO_VAL if v is None else self.prop_table.intern_val(v),
                **common,
            )
            for k, v in props.items()
        ]

    # -------------------------------------------------------------- flush

    def flush(self) -> int:
        """Dispatch all staged ops to the device in [D, K] waves."""
        total = 0
        while self._staged:
            batch = np.zeros((self.max_docs, self.K, OP_FIELDS), np.int32)
            drained = []
            for slot, ops in self._staged.items():
                take = min(len(ops), self.K)
                batch[slot, :take] = ops[:take]
                total += take
                if take == len(ops):
                    drained.append(slot)
                else:
                    self._staged[slot] = ops[take:]
            for slot in drained:
                del self._staged[slot]
            ops_dev = jnp.asarray(batch)
            if self._mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                ops_dev = jax.device_put(
                    ops_dev, NamedSharding(self._mesh, P("docs")))
            self.state, _ = self._step(self.state, ops_dev)
            self.dispatches += 1
        self.ops_applied += total
        self._check_overflow()
        return total

    def _check_overflow(self) -> None:
        flags = np.asarray(self.state.overflow)
        for slot in np.nonzero(flags)[0]:
            if int(slot) not in self._host_docs:
                self._escalate(int(slot), None, None)

    # ------------------------------------------------------------- queries

    def slot_count(self, tenant_id: str, document_id: str) -> int:
        """Live device slots for a doc (bounded under churn by zamboni)."""
        slot = self.slot_of(tenant_id, document_id)
        return int(np.asarray(self.state.count)[slot])

    def _device_slot(self, slot: int) -> DocState:
        return jax.tree.map(lambda a: np.asarray(a)[slot], self.state)

    def get_text(self, tenant_id: str, document_id: str) -> str:
        slot = self.slot_of(tenant_id, document_id)
        if self._staged.get(slot):
            self.flush()
        if slot in self._host_docs:
            return self._host_docs[slot].get_text()
        single = self._device_slot(slot)
        out, arena = [], self.arenas[slot]
        for i in range(int(single.count)):
            if single.rem_seq[i] != -1:
                continue
            if single.flags[i] & FLAG_MARKER:
                continue  # markers contribute length, not text
            out.append(arena.slice(int(single.text_start[i]), int(single.length[i])))
        return "".join(out)

    def get_tree(self, tenant_id: str, document_id: str) -> "MergeTreeClient":
        """Decode the doc to an oracle tree (summaries / inspection)."""
        slot = self.slot_of(tenant_id, document_id)
        if self._staged.get(slot):
            self.flush()
        if slot in self._host_docs:
            return self._host_docs[slot]
        tree = decode_state(self._device_slot(slot), self.arenas[slot],
                            self.prop_table)
        replica = MergeTreeClient(f"tpu-applier/{tenant_id}/{document_id}")
        replica.tree = tree
        return replica

    def get_properties_at(self, tenant_id: str, document_id: str,
                          pos: int) -> dict:
        """Properties of the visible character at ``pos`` (final
        perspective) — the annotate-path query surface."""
        slot = self.slot_of(tenant_id, document_id)
        if self._staged.get(slot):
            self.flush()
        if slot in self._host_docs:
            return self._host_docs[slot].get_properties_at(pos)
        single = self._device_slot(slot)
        cum = 0
        for i in range(int(single.count)):
            if single.rem_seq[i] != -1:
                continue
            if cum <= pos < cum + int(single.length[i]):
                props = {}
                for p in range(single.prop_key.shape[-1]):
                    kid = int(single.prop_key[i, p])
                    if kid != -1:
                        props[self.prop_table.key(kid)] = self.prop_table.val(
                            int(single.prop_val[i, p]))
                return props
            cum += int(single.length[i])
        raise IndexError(pos)

    # ---------------------------------------------------- host escalation

    def _escalate(self, slot: int, msg, wire_op) -> None:
        """Rebuild the doc on the scalar oracle from its authoritative op
        log and continue host-side (SURVEY §7(e) escape hatch)."""
        tenant_id, document_id = self._doc_keys[slot]
        if self._replay_log is None:
            # degrading to an empty replica would silently lose the doc
            raise RuntimeError(
                f"doc {tenant_id}/{document_id} needs host escalation but no "
                "replay source is configured (set_replay_source)")
        self.host_escalations += 1
        replica = MergeTreeClient(f"tpu-applier/{tenant_id}/{document_id}")
        self._host_docs[slot] = replica
        self._staged.pop(slot, None)
        for m in self._replay_log(tenant_id, document_id):
            if m.type == MessageType.OPERATION:
                replica.apply_msg(m, local=False)
        if msg is not None:
            self._apply_host(slot, msg, wire_op)

    def _apply_host(self, slot: int, msg, wire_op) -> None:
        replica = self._host_docs[slot]
        if msg.sequence_number <= replica.tree.current_seq:
            return  # already covered by the escalation replay
        replica.apply_msg(replace(msg, contents=wire_op), local=False)

    # the host replay source: fn(tenant, doc) -> [SequencedDocumentMessage]
    # of CHANNEL-LEVEL merge-tree messages; wired by the service host
    _replay_log = None

    def set_replay_source(self, fn) -> None:
        self._replay_log = fn
