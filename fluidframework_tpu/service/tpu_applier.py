"""TpuDocumentApplier: batched server-side merge-tree replica farm.

THE TPU-differentiating service component. The reference's server keeps no
document state (architecture.md: server sequences, clients merge) and pays
for it when it needs content — scribe replays whole op logs in JS to build
service summaries (scribe writeServiceSummary, SURVEY §3.4). Here the
service maintains thousands of documents as ONE device-resident
structure-of-arrays batch (ops/doc_state.DocState with a leading doc dim)
and applies every sequenced merge-tree op as a vmapped tensor program
(ops/apply.py), optionally sharded over a ('docs','seg') mesh
(parallel/sharded_apply.py). That turns BASELINE config 5 (10k-doc scribe
replay) into a handful of XLA dispatches.

Semantics guardrails:
- Ops ingest ONLY from the sequenced stream, so the server-side invariants
  hold (every stamp below the incoming seq; tie-break = earliest
  boundary — see ops/apply.py docstring).
- Insert/remove/annotate all stay on the device. Anything the kernel does
  not model (slot-capacity, remove-overlap, or property-table overflow)
  flips the doc to HOST mode: the scalar oracle (mergetree/) replays the
  doc's authoritative op log from scriptorium. This is the
  overflow-to-host escape hatch of SURVEY §7(e).
- Every staged op carries the msn deli stamped on its sequenced message,
  so device zamboni (ops/apply.compact) runs fused after every wave at
  the exact collaboration-window floor — slot usage stays bounded under
  churn instead of growing until escalation.
"""

from __future__ import annotations

import time
from dataclasses import replace
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..mergetree.client import MergeTreeClient
from ..ops.apply import (
    F_CLIENT,
    F_END,
    F_KEY,
    F_MSN,
    F_POS,
    F_REFSEQ,
    F_SEQ,
    F_TLEN,
    F_TSTART,
    F_TYPE,
    F_VAL,
    NO_VAL,
    OP_ANNOTATE,
    OP_FIELDS,
    OP_INSERT,
    OP_REMOVE,
    SYSTEM_CLIENT,
    apply_ops_batch,
    compact_batch,
    pack_wave_rows,
    unpack_wave16,
    wave_min_seq,
)
from ..ops.doc_state import FLAG_MARKER, DocState, PropTable, TextArena, decode_state
from ..protocol.messages import MessageType, SequencedDocumentMessage
from ..parallel.placement import DocPlacement
from ..utils.contracts import register_kernel_contract
from ..utils.affinity import blocking

MARKER_GLYPH = "￼"  # arena placeholder byte for markers (flags classify)

# SYSTEM_CLIENT / PACK_SYSTEM and the int16-delta wire format now live in
# ops/apply.py (shared with the mesh lane's packed sharded step)

# jitted dense steps shared across applier instances, keyed (D, K):
# per-instance closures would each re-trace/re-compile every shape bucket
_DENSE_STEP_CACHE: dict = {}

# process-wide small thread pool for per-shard staging jobs: the numpy
# fancy-index scatter and device_put both release the GIL, so active
# shards stage concurrently on multi-core hosts (shared across applier
# instances — worker threads are lazy and cheap, lifecycles are not)
_STAGE_POOL = None


def _stage_executor():
    global _STAGE_POOL
    if _STAGE_POOL is None:
        from concurrent.futures import ThreadPoolExecutor

        _STAGE_POOL = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="applier-stage")
    return _STAGE_POOL


class _StagedWave:
    """The output of the stage half of a dispatch: device-resident input
    buffers plus what the execute half needs to run and account for the
    wave. Holding one of these means the wave's ops have LEFT the staged
    dict but have not yet been issued to the device."""

    __slots__ = ("lane", "wide", "arrays", "n", "nbytes", "flip")

    def __init__(self, lane: str, wide: bool, arrays: tuple, n: int,
                 nbytes: int):
        self.lane = lane        # "dense" | "mesh" (metrics label)
        self.wide = wide        # int32 escape lane (range / force_wide)
        self.arrays = arrays    # device arrays, step-call order
        self.n = n              # op rows in the wave
        self.nbytes = nbytes    # host bytes staged
        self.flip = 0           # which staging-buffer set holds the wave


def _resolve_kernel(kernel, use_pallas, cfg, tile_docs: int) -> bool:
    """Resolve the applier's contract kernel to use_pallas.

    Precedence: an explicit ``use_pallas`` bool (the pre-selection API)
    wins, then config ``applier_use_pallas`` when set, then
    ``kernel``/``applier_kernel``. ``auto`` selects Pallas only on real
    TPU devices AND when the doc geometry tiles (R=8 docs per grid
    instance); a forced ``pallas`` raises on bad geometry instead of
    silently degrading, while ``auto`` falls back to the XLA scan."""
    if use_pallas is None:
        use_pallas = cfg.applier_use_pallas
    if use_pallas is not None:
        use, origin = bool(use_pallas), "applier_use_pallas"
    else:
        kernel = kernel if kernel is not None else cfg.applier_kernel
        if kernel not in ("auto", "pallas", "xla"):
            raise ValueError(
                f"applier_kernel={kernel!r}: expected auto|pallas|xla")
        if kernel == "auto":
            return jax.default_backend() == "tpu" and tile_docs % 8 == 0
        use, origin = kernel == "pallas", "applier_kernel=pallas"
    if use and tile_docs % 8:
        raise ValueError(
            f"{origin} requires docs-per-shard % 8 == 0 (got {tile_docs})")
    return use


def _count_trace(kernel: str, shape: str) -> None:
    """Runs at TRACE time only (a Python side effect inside a jitted
    body): each call is one XLA recompile of ``kernel`` for a new shape
    bucket. The registry series makes kernel-count swings between runs
    attributable (tools/profile_applier.py prints the breakdown)."""
    from ..obs import get_registry

    get_registry().inc("applier.kernel.recompiled",
                       kernel=kernel, shape=shape)


def _dense_step_for(D: int, K: int, use_pallas: bool = False,
                    pallas_interpret: bool = False):
    """The wave arrives PACKED from the host: int16[D, K, F] deltas plus
    int32[D, 2] per-doc bases (seq, text_start), unpacked to the kernel's
    int32 field layout on device with elementwise math (the shared wire
    format — rationale and codec in ops/apply.py's packed-wave section).

    Why this shape: the host↔device link is the op path's bottleneck, so
    bytes-per-op is the number to minimize. Device-side scatter/row-gather
    of compact rows would avoid padding but costs ~400-550 ms per 64k rows
    on TPU; shipping the padded [D, K] wave and halving it to int16 is
    both simpler and faster. The host checks the delta ranges and falls
    back to the int32 wave when any field escapes (huge docs, giant
    windows).
    """
    fn = _DENSE_STEP_CACHE.get((D, K, use_pallas, pallas_interpret))
    if fn is None:
        if use_pallas:
            from ..ops.pallas_apply import pallas_apply_ops_batch

            def apply_fn(state, wave):
                return pallas_apply_ops_batch(
                    state, wave, interpret=pallas_interpret)
        else:
            apply_fn = apply_ops_batch

        def dense_step(state, wave16, bases):
            _count_trace("dense_step", f"{D}x{K}")
            wave = unpack_wave16(wave16, bases)
            state = apply_fn(state, wave)
            return compact_batch(state, wave_min_seq(wave)), {}

        def dense_step_wide(state, wave):
            _count_trace("dense_step_wide", f"{D}x{K}")
            state = apply_fn(state, wave)
            return compact_batch(state, wave_min_seq(wave)), {}

        from ..parallel.sharded_apply import donation_supported

        # donation gated by backend: the CPU client runs donating
        # computations synchronously, which would serialize the
        # stage/execute overlap pipeline (see donation_supported)
        don = (0,) if donation_supported() else ()
        fn = (jax.jit(dense_step, donate_argnums=don),
              jax.jit(dense_step_wide, donate_argnums=don))
        _DENSE_STEP_CACHE[(D, K, use_pallas, pallas_interpret)] = fn
    return fn


def _contract_build():
    """The int16 packed wave applier at a small fixed geometry."""
    D, K = 8, 4
    packed_fn, _wide_fn = _dense_step_for(D, K)

    def example():
        S = 16
        state = jax.vmap(lambda _: DocState.empty(S))(jnp.arange(D))
        wave16 = jnp.zeros((D, K, OP_FIELDS), jnp.int16)
        bases = jnp.zeros((D, 2), jnp.int32)
        return (state, wave16, bases), {}

    return packed_fn, example


# contract: the wave arrives int16 and must be EXPLICITLY widened before
# any arithmetic (no_int16_arithmetic catches silent promotion); the
# unpack+apply is gather-free, the fused zamboni repack owns the only
# gathers (one per DocState field, once per wave, off the K-amplified
# path); one compile per (D, K) geometry.
register_kernel_contract(
    "service.dense_step_packed",
    build=_contract_build,
    no_scatter=True,
    max_gathers=10,
    no_int16_arithmetic=True,
    single_jit=True,
    notes="int16 packed-wave unpack + batched apply + fused zamboni",
)


def _contract_build_pallas():
    """The same packed-wave applier with kernel=pallas selected
    (interpret mode so the contract checks run on any backend — the
    traced program is identical to the Mosaic-lowered one)."""
    D, K = 8, 4
    packed_fn, _wide_fn = _dense_step_for(D, K, use_pallas=True,
                                          pallas_interpret=True)

    def example():
        S = 16
        state = jax.vmap(lambda _: DocState.empty(S))(jnp.arange(D))
        wave16 = jnp.zeros((D, K, OP_FIELDS), jnp.int16)
        bases = jnp.zeros((D, 2), jnp.int32)
        return (state, wave16, bases), {}

    return packed_fn, example


# contract: the default-on Pallas lane must honor the SAME wire-format
# invariants as the XLA lane — the checker walks INTO the pallas_call
# jaxpr, so a scatter or int16 promotion smuggled into the Mosaic body
# fails identically; zamboni's once-per-wave repack owns the only gathers
register_kernel_contract(
    "service.dense_step_packed_pallas",
    build=_contract_build_pallas,
    no_scatter=True,
    max_gathers=10,
    no_int16_arithmetic=True,
    single_jit=True,
    notes="int16 packed wave through the Pallas VMEM apply lane "
          "(applier.kernel=pallas selection of the dense step)",
)


def channel_stream(server, tenant_id: str, document_id: str,
                   ds_id: str, channel_id: str, from_seq: int = 0):
    """Extract one channel's merge-tree messages from the document's
    sequenced op log (scriptorium) — the applier's replay source and the
    scribe-replay entry point (BASELINE config 5).

    Truncated logs raise (scriptorium.LogTruncatedError): with log
    retention active, a from-zero replay would silently rebuild WRONG
    state once ops behind an acked summary have been dropped — such
    deployments must give the applier a summary-aware replay source.
    Reads go straight through a stateless ScriptoriumLambda over the db
    so inspecting a doc never lazily constructs its whole pipeline."""
    from .scriptorium import ScriptoriumLambda

    for m in ScriptoriumLambda(server.db).get_deltas(
            tenant_id, document_id, from_seq, 10**9):
        if m.type != MessageType.OPERATION:
            continue
        env = m.contents
        if not isinstance(env, dict) or env.get("kind") != "chanop":
            continue
        if env["address"] != ds_id:
            continue
        inner = env["contents"]
        if inner.get("address") != channel_id or "attach" in inner:
            continue
        yield replace(m, contents=inner["contents"])


class TpuDocumentApplier:
    """Maintains [D, S] device doc states fed by sequenced op streams."""

    #: chaos seam (fluidframework_tpu/chaos): forced device escalations —
    #: the int32 wide dispatch path and the overflow-to-host flip — so the
    #: rare lanes run under the soak, not only when a doc organically
    #: exceeds int16 / device capacity. None = disarmed, one branch.
    fault_plane = None

    def __init__(
        self,
        max_docs: Optional[int] = None,
        max_slots: Optional[int] = None,
        ops_per_dispatch: Optional[int] = None,
        mesh=None,
        overflow_check_every: Optional[int] = None,
        async_dispatch: bool = False,
        min_wave_ops: Optional[int] = None,
        use_pallas: Optional[bool] = None,
        pallas_interpret: bool = False,
        kernel: Optional[str] = None,
        overlap: Optional[bool] = None,
    ):
        from ..config import DEFAULT as _CFG

        # geometry defaults come from the unified config registry
        max_docs = max_docs if max_docs is not None else _CFG.applier_max_docs
        max_slots = (max_slots if max_slots is not None
                     else _CFG.applier_max_slots)
        ops_per_dispatch = (ops_per_dispatch if ops_per_dispatch is not None
                            else _CFG.applier_ops_per_dispatch)
        overflow_check_every = (
            overflow_check_every if overflow_check_every is not None
            else _CFG.applier_overflow_check_every)
        min_wave_ops = (min_wave_ops if min_wave_ops is not None
                        else _CFG.applier_min_wave_ops)
        self.max_docs = max_docs
        self.max_slots = max_slots
        self.K = ops_per_dispatch
        # overflow flags live on-device; reading them is a host sync that
        # stalls the whole dispatch pipeline (very expensive over a
        # tunneled device), so flush() only polls every N dispatches.
        # Deferral is safe: the flag is sticky (ops/apply.py ORs into it)
        # and escalation replays the doc from its authoritative log, so
        # late detection loses nothing. Queries and finalize() always
        # check before exposing state.
        self.overflow_check_every = overflow_check_every
        self._dispatches_since_check = 0
        # an int mesh is shorthand for a docs-only axis of that many
        # shards — callers above the parallel layer (chaos soak) can ask
        # for a mesh without importing mesh construction themselves
        if isinstance(mesh, int):
            from ..parallel.mesh import make_mesh

            mesh = make_mesh(mesh, seg_shards=1)
        # the doc→shard routing table (partition-router role). In mesh
        # mode each 'docs'-axis device owns a contiguous block of state
        # rows (NamedSharding splits axis 0 in mesh order), so placement
        # shard s IS device s and the global row is shard*slots + slot.
        if mesh is not None:
            n_shards = mesh.shape["docs"]
            if max_docs % n_shards:
                raise ValueError(
                    f"max_docs={max_docs} not divisible by the mesh's "
                    f"docs axis ({n_shards})")
            self.placement = DocPlacement(
                n_shards=n_shards, slots_per_shard=max_docs // n_shards)
        else:
            self.placement = DocPlacement(n_shards=1,
                                          slots_per_shard=max_docs)
        self.state: DocState = jax.vmap(lambda _: DocState.empty(max_slots))(
            jnp.arange(max_docs)
        )
        self.arenas: list[TextArena] = [TextArena() for _ in range(max_docs)]
        self.prop_table = PropTable()  # shared across docs; ids are dense
        # per-doc dense client interning — collision-free by construction
        # (the round-1 truncated-hash scheme could merge two clients'
        # own-op visibility at the 24-bit birthday bound)
        self._client_ids: dict[int, dict[str, int]] = {}
        # staged device ops as 12-tuples in ops/apply field order; one
        # np.array() per slot per flush instead of one per op
        # staged device ops per slot, as a list of int32 [n, OP_FIELDS]
        # CHUNKS (one per ingested batch — the array lane appends its
        # vectorized rows directly; the dict lane converts its tuple
        # batch once); _staged_ops tracks the total row count
        self._staged: dict[int, list] = {}
        self._staged_ops = 0
        self._host_docs: dict[int, MergeTreeClient] = {}  # escalated docs
        self._doc_keys: dict[int, tuple[str, str]] = {}
        self._mesh = mesh
        # mesh-lane staging-cost counters (the multichip smoke and
        # bench_multichip read these: per-wave staged bytes must scale
        # with ACTIVE shards, never with max_docs)
        self.mesh_waves = 0
        self.mesh_active_shards = 0
        self.mesh_staged_bytes = 0
        self.mesh_stage_seconds = 0.0
        # ---- overlap-staged dispatch (stage/execute split) ----
        # Two rotating host staging buffer sets: wave N+1 scatters into
        # one set while the other set's device_put (wave N) may still be
        # copying — _rotate_stage_buffers fences a set's previous
        # transfers before handing it out again, so async H2D and state
        # donation stay sound even when the backend copies lazily.
        self._overlap = (overlap if overlap is not None
                         else _CFG.applier_overlap)
        self._stage_pool: tuple = ({}, {})
        self._stage_inflight: list = [None, None]
        self._stage_flip = 0
        # the last dispatched step's output: is_ready() is the
        # non-blocking "device still executing" probe the overlap-ratio
        # accounting keys on; _drain_device() fences it at seams
        self._exec_marker = None
        # host-stage vs device-execute split, BOTH lanes (the pre-overlap
        # code only took t0 in mesh mode, so the dense lane reported zero
        # staging cost and the kernel-plateau analysis had no split)
        self.stage_seconds = 0.0
        self.stage_overlap_seconds = 0.0
        self.stage_bytes = 0
        self.exec_seconds = 0.0
        self.waves_staged = 0
        self._registry = None
        # contract-kernel selection: auto = Pallas on real TPU, XLA scan
        # elsewhere; dense lane's tile is max_docs (= slots_per_shard of
        # the 1-shard placement), mesh lane's is slots-per-shard
        use_pallas = _resolve_kernel(kernel, use_pallas, _CFG,
                                     self.placement.slots_per_shard)
        self.kernel_lane = "pallas" if use_pallas else "xla"
        if mesh is not None:
            from ..parallel.sharded_apply import (
                doc_sharding, make_sharded_packed_step, shard_state)

            self.state = shard_state(self.state, mesh)
            # the mesh twin of _dense_step_for: same int16 packed wave,
            # unpacked per shard inside shard_map, state donated, stats
            # psum'd — the dispatch path below is otherwise identical to
            # the local dense lane (async worker, min-wave, force_wide)
            self._sharded_step = make_sharded_packed_step(
                mesh, use_pallas=use_pallas,
                pallas_interpret=pallas_interpret,
                trace_hook=_count_trace)
            self._mesh_sharding = doc_sharding(mesh)
            sps = self.placement.slots_per_shard
            # device → docs-shard map for pre-partitioned wave assembly:
            # P("docs") splits axis 0 into contiguous blocks in mesh
            # order, so the device whose block starts at shard*sps IS
            # that placement shard (with a 'seg' axis, its replicas too)
            by_shard: dict[int, list] = {}
            for dev, idx in self._mesh_sharding.devices_indices_map(
                    (max_docs,)).items():
                by_shard.setdefault((idx[0].start or 0) // sps,
                                    []).append(dev)
            self._shard_devices = [by_shard[s]
                                   for s in range(self.placement.n_shards)]
            # per-device resident zero shards, reused every wave for
            # INACTIVE shards (no host alloc, no transfer)
            self._zero_shards: dict = {}
        else:
            from ..parallel.sharded_apply import donation_supported

            self._step = jax.jit(
                self._local_step,
                donate_argnums=(0,) if donation_supported() else ())
            # dense dispatch: ship the padded [D, K, F] wave packed to
            # int16 deltas (see _dense_step_for for the wire format and
            # why device-side scatter lost)
            self._dense_step = _dense_step_for(
                max_docs, self.K, use_pallas=use_pallas,
                pallas_interpret=pallas_interpret)
        self.dispatches = 0
        self.ops_applied = 0
        self.host_escalations = 0
        # coverage tracking for summary writers (service_summarizer.py):
        # _applied_seq = highest ingested seq per slot (tail-lag check);
        # _first_seq = first ingested seq per slot; _anchored = slots whose
        # state provably covers the doc's WHOLE history (checkpoint
        # restore, authoritative escalation replay, or a summarizer gate
        # pass over an untruncated log). max-seq alone cannot prove an
        # applier fed only the post-truncation tail covers the prefix.
        self._applied_seq: dict[int, int] = {}
        self._first_seq: dict[int, int] = {}
        self._anchored: set[int] = set()
        # checkpoint-restore bookkeeping: ops sequenced while the process
        # was down are not in the restored state, so the summarizer must
        # verify the feed resumed without skipping any (see restore_gap)
        self._restore_applied: dict[int, int] = {}
        self._post_restore_first: dict[int, int] = {}
        # async mode: a worker thread owns wave building + host→device
        # transfer + dispatch, so tunnel transfer latency never blocks the
        # ordering pipeline — the applier becomes a real pipeline stage
        # the way the reference's scribe/scriptorium are separate
        # consumers of the sequenced topic. The worker is the ONLY state mutator; the
        # main thread stages tuples under the lock and escalates at sync
        # points (worker defers overflow escalation to `_overflow_slots`).
        self._async = async_dispatch
        # below this many staged ops the worker holds off dispatching
        # (unless draining): the K-step scan costs the same whether waves
        # are full or nearly empty, and each distinct dense-bucket shape
        # costs a compile — steady waves at one size keep both amortized
        self._min_wave = min_wave_ops
        self._draining = False
        if async_dispatch:
            import threading

            self._lock = threading.Lock()
            self._wake = threading.Event()
            self._idle = threading.Event()
            self._idle.set()
            self._stop = False
            self._overflow_slots: set[int] = set()
            self._worker = threading.Thread(
                target=self._worker_loop, daemon=True, name="tpu-applier")
            self._worker.start()

    @staticmethod
    def _local_step(state: DocState, ops: jax.Array):
        _count_trace("local_step", "x".join(map(str, ops.shape[:2])))
        state = apply_ops_batch(state, ops)
        state = compact_batch(state, wave_min_seq(ops))
        return state, {}

    # ------------------------------------------------------------- ingest

    def slot_of(self, tenant_id: str, document_id: str) -> int:
        """Global state row for a doc: the placement's (shard, slot)
        flattened shard-major, so rows route to their owning device."""
        shard, slot = self.placement.place(tenant_id, document_id)
        row = shard * self.placement.slots_per_shard + slot
        self._doc_keys.setdefault(row, (tenant_id, document_id))
        return row

    def _intern_client(self, slot: int, client_id: Optional[str]) -> int:
        if client_id is None:
            return SYSTEM_CLIENT
        table = self._client_ids.setdefault(slot, {})
        cid = table.get(client_id)
        if cid is None:
            cid = len(table)
            table[client_id] = cid
        return cid

    def ingest(
        self,
        tenant_id: str,
        document_id: str,
        msg: SequencedDocumentMessage,
        wire_op: dict,
    ) -> None:
        """Stage one sequenced merge-tree wire op for batched apply."""
        self.ingest_batch(tenant_id, document_id, [(msg, wire_op)])

    def ingest_batch(
        self,
        tenant_id: str,
        document_id: str,
        pairs: list[tuple[SequencedDocumentMessage, dict]],
    ) -> None:
        """Stage a broadcast batch of sequenced wire ops in one call —
        the deli-tpu marshal's per-boxcar entry point. Staging is plain
        tuple appends; device encoding happens once per flush."""
        slot = self.slot_of(tenant_id, document_id)
        if pairs:
            # sequenced stream ⇒ pairs arrive in seq order; the last is max
            self._applied_seq[slot] = max(
                self._applied_seq.get(slot, 0),
                pairs[-1][0].sequence_number)
            self._first_seq.setdefault(slot, pairs[0][0].sequence_number)
            if slot in self._restore_applied:
                self._post_restore_first.setdefault(
                    slot, pairs[0][0].sequence_number)
        if self.fault_plane is not None and slot not in self._host_docs:
            if self.fault_plane("applier.ingest", slot=slot) \
                    == "escalate_host":
                # forced overflow-to-host flip: same path a doc takes
                # when it outgrows device capacity — replays the
                # authoritative log into a host replica, then applies
                # this batch host-side below
                self._escalate(slot, None, None)
        if slot in self._host_docs:
            for msg, wire_op in pairs:
                self._apply_host(slot, msg, wire_op)
            return
        # stage into a local tuple list; one np conversion per batch at
        # the end (the chunk) — per-op tuple appends beat per-op numpy
        # row writes, and the wave builder concatenates chunks
        staged = []
        table = self._client_ids.setdefault(slot, {})
        arena = self.arenas[slot]
        # hot-loop locals: plain inserts/removes (the overwhelming bulk of
        # real traffic) stage inline without the _stage_op dispatch
        append = staged.append
        arena_append = arena.append
        table_get = table.get
        for i, (msg, wire_op) in enumerate(pairs):
            if type(wire_op) is not dict:
                ok = False
            else:
                cid = msg.client_id
                if cid is None:
                    client = SYSTEM_CLIENT
                else:
                    client = table_get(cid)
                    if client is None:
                        client = len(table)
                        table[cid] = client
                t = wire_op.get("type")
                if t == 0 and "marker" not in wire_op \
                        and not wire_op.get("props"):
                    text = wire_op.get("text") or ""
                    append((OP_INSERT, wire_op["pos"], 0,
                            msg.sequence_number,
                            msg.reference_sequence_number, client,
                            len(text), arena_append(text),
                            msg.minimum_sequence_number, 0, 0, 0))
                    continue
                if t == 1:
                    append((OP_REMOVE, wire_op["start"], wire_op["end"],
                            msg.sequence_number,
                            msg.reference_sequence_number, client, 0, 0,
                            msg.minimum_sequence_number, 0, 0, 0))
                    continue
                ok = self._stage_op(
                    staged, arena, wire_op, msg.sequence_number,
                    msg.reference_sequence_number, client,
                    msg.minimum_sequence_number)
            if not ok:
                # escalation replays the authoritative log (which already
                # holds this batch) and discards partial staging
                self._escalate(slot, msg, wire_op)
                for msg2, wire_op2 in pairs[i + 1:]:
                    self._apply_host(slot, msg2, wire_op2)
                return
        if staged:
            self._push_chunk(slot, np.asarray(staged, np.int32))

    def ingest_array_batch(self, tenant_id: str, document_id: str,
                           batch) -> None:
        """Stage a SequencedArrayBatch (service/array_batch.py) as ONE
        vectorized chunk — the deli-tpu marshal's device on-ramp: no
        per-op dicts, tuples, or message objects. Inserts land in the
        arena as a single concatenated append; annotate rows (the rare
        kind) fill their key/val ids in a small loop over just the
        annotate indices."""
        slot = self.slot_of(tenant_id, document_id)
        box = batch.boxcar
        n = box.n
        if n == 0:
            return
        self._applied_seq[slot] = max(self._applied_seq.get(slot, 0),
                                      batch.last_seq)
        self._first_seq.setdefault(slot, batch.base_seq)
        if slot in self._restore_applied:
            self._post_restore_first.setdefault(slot, batch.base_seq)
        if slot in self._host_docs:
            for i in range(n):
                self._apply_host(slot, batch.message(i), box.wire_op(i))
            return
        table = self._client_ids.setdefault(slot, {})
        client = table.get(box.client_id)
        if client is None:
            client = len(table)
            table[box.client_id] = client
        kind = box.kind
        is_ann = kind == 2  # wire kind 2 = annotate (array_batch.py)
        ann_idx = np.nonzero(is_ann)[0] if is_ann.any() else ()
        # annotates expand to one row PER PROP KEY; with single-key props
        # (the overwhelming case) the chunk stays one row per op; empty
        # or multi-key props take the materialized slow path
        if len(ann_idx) and (
                box.props is None
                or any(len(box.props[int(i)] or {}) != 1 for i in ann_idx)):
            pairs = [(batch.message(i), box.wire_op(i)) for i in range(n)]
            self.ingest_batch(tenant_id, document_id, pairs)
            return
        chunk = np.zeros((n, OP_FIELDS), np.int32)
        # wire kinds (0 ins, 1 rem, 2 ann) → device op codes (1, 2, 3)
        chunk[:, F_TYPE] = kind.astype(np.int32) + 1
        chunk[:, F_POS] = box.a
        chunk[:, F_END] = box.b
        seqs = batch.base_seq + np.arange(n, dtype=np.int64)
        chunk[:, F_SEQ] = seqs
        chunk[:, F_REFSEQ] = box.rseq
        chunk[:, F_CLIENT] = client
        chunk[:, F_MSN] = batch.msns
        arena_start = self.arenas[slot].append(box.text)
        chunk[:, F_TLEN] = np.diff(box.text_off)
        chunk[:, F_TSTART] = arena_start + box.text_off[:-1]
        for i in ann_idx:
            (k, v), = box.props[int(i)].items()
            chunk[i, F_KEY] = self.prop_table.intern_key(k)
            chunk[i, F_VAL] = (NO_VAL if v is None
                               else self.prop_table.intern_val(v))
        self._push_chunk(slot, chunk)

    def _push_chunk(self, slot: int, chunk: np.ndarray) -> None:
        """Append a staged [n, OP_FIELDS] chunk (the ONLY staging-count
        mutation point besides _take_wave_locked/_drop_staged)."""
        if self._async:
            with self._lock:
                self._staged.setdefault(slot, []).append(chunk)
                self._staged_ops += len(chunk)
        else:
            self._staged.setdefault(slot, []).append(chunk)
            self._staged_ops += len(chunk)

    def _drop_staged(self, slot: int) -> None:
        """Discard a slot's staged chunks (escalation path), keeping the
        staged-op count consistent."""
        if self._async:
            with self._lock:
                dropped = self._staged.pop(slot, None)
                if dropped:
                    self._staged_ops -= sum(len(c) for c in dropped)
        else:
            dropped = self._staged.pop(slot, None)
            if dropped:
                self._staged_ops -= sum(len(c) for c in dropped)

    def _stage_op(self, staged, arena, w, seq, ref, client, msn) -> bool:
        """Append a wire op's device tuples (ops/apply field order).
        Returns False when the kernel does not model the op."""
        t = w.get("type")
        if t == 0:  # insert
            pos = w["pos"]
            marker = w.get("marker")
            if marker is not None:
                start = arena.append(MARKER_GLYPH)
                tlen = 1
                staged.append((OP_INSERT, pos, 0, seq, ref, client,
                               1, start, msn, FLAG_MARKER, 0, 0))
            else:
                text = w.get("text") or ""
                start = arena.append(text)
                tlen = len(text)
                staged.append((OP_INSERT, pos, 0, seq, ref, client,
                               tlen, start, msn, 0, 0, 0))
            props = w.get("props")
            if props:
                # insert-with-props (oracle attaches props to the new
                # segment): at the insert's OWN perspective the visible
                # span [pos, pos+len) is exactly the new slot, so
                # follow-up annotates stamp precisely it
                self._stage_annotate(
                    staged, pos, pos + tlen, props, seq, ref, client, msn)
            return True
        if t == 1:  # remove
            staged.append((OP_REMOVE, w["start"], w["end"], seq, ref, client,
                           0, 0, msn, 0, 0, 0))
            return True
        if t == 2:  # annotate
            self._stage_annotate(staged, w["start"], w["end"], w["props"],
                                 seq, ref, client, msn)
            return True
        if t == 3:  # group: all-or-nothing (partial staging is discarded
            # by _escalate if a sub-op is unsupported)
            return all(
                self._stage_op(staged, arena, sub, seq, ref, client, msn)
                for sub in w["ops"]
            )
        if t == "interval":
            return True  # interval metadata: no effect on text content
        return False

    def _stage_annotate(self, staged, start, end, props, seq, ref, client,
                        msn) -> None:
        # one device op per key; in-order apply gives per-key LWW
        intern_key = self.prop_table.intern_key
        intern_val = self.prop_table.intern_val
        for k, v in props.items():
            staged.append((OP_ANNOTATE, start, end, seq, ref, client, 0, 0,
                           msn, 0, intern_key(k),
                           NO_VAL if v is None else intern_val(v)))

    # -------------------------------------------------------------- flush

    def flush(self) -> int:
        """Dispatch all staged ops to the device in [D, K] waves.

        In async mode this just wakes the worker (non-blocking); in sync
        mode it dispatches inline. Either way device execution is only
        fenced by the periodic overflow poll (every
        ``overflow_check_every`` dispatches) or by ``finalize()``/queries.
        """
        if self._async:
            self._wake.set()
            return 0
        return self._flush_sync()

    def _flush_sync(self) -> int:
        total = 0
        while self._staged:
            # one dispatch path for both lanes: _dispatch_wave routes the
            # packed wave to the local dense step or the mesh's sharded
            # step (per-shard staging + pre-partitioned transfer)
            total += self._dispatch_wave(self._take_wave_locked())
        self.ops_applied += total
        if self._dispatches_since_check >= self.overflow_check_every:
            self._check_overflow()
        return total

    # ------------------------------------------------------ async worker

    def _take_wave_locked(self):
        """Pop up to K staged op ROWS per doc (caller holds the lock).

        Returns [(slot, chunk_list, row_count)]; an overflowing chunk is
        split by an array view, so staging order is preserved."""
        if not self._staged:
            return None
        parts = []
        drained = []
        K = self.K
        for slot, chunks in self._staged.items():
            take, rest, count = [], None, 0
            for ci, ch in enumerate(chunks):
                n = len(ch)
                if count + n <= K:
                    take.append(ch)
                    count += n
                else:
                    room = K - count
                    if room > 0:
                        take.append(ch[:room])
                        count = K
                        rest = [ch[room:]] + chunks[ci + 1:]
                    else:
                        rest = chunks[ci:]
                    break
            parts.append((slot, take, count))
            self._staged_ops -= count
            if rest is None:
                drained.append(slot)
            else:
                self._staged[slot] = rest
        for slot in drained:
            del self._staged[slot]
        return parts

    def _dispatch_wave(self, parts) -> int:
        """Stage then execute one wave — the serialized entry point.
        The pipelining callers (_flush_sync, _worker_loop) go through the
        same pair; overlap comes from the execute half being an async
        dispatch, so the NEXT iteration's stage half runs on the host
        while this wave executes on device."""
        staged = self._stage_wave(parts)
        if staged is None:
            return 0
        return self._execute_wave(staged)

    # --------------------------------------------- stage / execute halves

    def _metrics(self):
        if self._registry is None:
            from ..obs import get_registry

            self._registry = get_registry()
        return self._registry

    @blocking("may block_until_ready the execution that last consumed the target buffer set — the PR 11 rotation fence")
    def _rotate_stage_buffers(self) -> None:
        """Flip to the other staging buffer set, fencing the EXECUTION
        that last consumed it (``jax.device_put`` may alias the host
        buffer rather than copy — readiness of the input array proves
        nothing, only step completion makes the memory reusable). By
        rotation the fenced wave is two dispatches old, so with the
        pipeline one wave deep the block is a no-op — it only waits when
        the device has fallen a full double-buffer behind."""
        self._stage_flip ^= 1
        pending = self._stage_inflight[self._stage_flip]
        if pending is not None:
            jax.block_until_ready(pending)
            self._stage_inflight[self._stage_flip] = None

    def _stage_buffer(self, shape: tuple, dtype) -> np.ndarray:
        """A zeroed host staging buffer from the CURRENT rotation set
        (callers run _rotate_stage_buffers once per wave first)."""
        pool = self._stage_pool[self._stage_flip]
        key = (shape, np.dtype(dtype).str)
        buf = pool.get(key)
        if buf is None:
            buf = np.zeros(shape, dtype)
            pool[key] = buf
        else:
            buf.fill(0)
        return buf

    @blocking("block_until_ready on the in-flight wave — the strict-wave-order fence at checkpoint/escalation seams")
    def _drain_device(self) -> None:
        """Fence the in-flight wave. Checkpoint/restore, escalation,
        force_wide, and state queries must never act on a farm with a
        wave still executing — strict wave order at every seam."""
        if self._exec_marker is not None:
            jax.block_until_ready(self._exec_marker)

    def _stage_wave(self, parts) -> Optional[_StagedWave]:
        """The HOST half of a dispatch: concat chunks → pack_wave_rows →
        scatter into rotating staging buffers → device_put. No device
        compute is issued; the returned wave holds resident buffers only
        (ops/apply.py's packed-wave section documents the int16-delta
        wire format).

        One vectorized fancy-index write places every occupied row; the
        flat rows build as ONE ``np.array`` over the concatenated tuple
        list (per-doc conversions were the dominant host cost at high doc
        counts). ``_take_wave_locked`` caps each doc at K ops, so a wave
        always fits. In mesh mode the scatter targets compact per-shard
        buffers for ACTIVE shards only (_stage_wave_mesh) — never an
        O(max_docs) dense host array."""
        if parts is None:
            return None
        t0 = time.perf_counter()
        all_chunks: list = []
        slots: list[int] = []
        lens: list[int] = []
        for slot, chunks, count in parts:
            if count == 0:  # interval-only batches stage nothing
                continue
            all_chunks.extend(chunks)
            slots.append(slot)
            lens.append(count)
        if not all_chunks:
            return None
        K = self.K
        flat = (all_chunks[0] if len(all_chunks) == 1
                else np.concatenate(all_chunks))
        n = len(flat)
        lens_a = np.array(lens)
        starts = np.cumsum(lens_a) - lens_a
        slots_a = np.array(slots, np.int64)
        doc_idx = np.repeat(slots_a, lens_a)
        pos_idx = np.arange(n, dtype=np.int64) - np.repeat(starts, lens_a)

        packed, seq_base, text_base = pack_wave_rows(flat, starts, lens_a)

        force_wide = (
            self.fault_plane is not None
            and self.fault_plane("applier.dispatch", ops=n) == "force_wide")
        if force_wide:
            # the forced int32 lane is a different program: drain the
            # pipeline so the width flip never reorders around an
            # in-flight packed wave
            self._drain_device()
        fits16 = (not force_wide
                  and packed.min() >= -32768 and packed.max() <= 32767)
        self._rotate_stage_buffers()
        if self._mesh is not None:
            staged = self._stage_wave_mesh(
                flat, packed if fits16 else None, doc_idx, pos_idx,
                slots_a, seq_base, text_base, n)
        elif fits16:
            wave16 = self._stage_buffer((self.max_docs, K, OP_FIELDS),
                                        np.int16)
            wave16[doc_idx, pos_idx] = packed.astype(np.int16)
            bases = self._stage_buffer((self.max_docs, 2), np.int32)
            bases[slots_a, 0] = seq_base
            bases[slots_a, 1] = text_base
            staged = _StagedWave(
                "dense", False,
                (jax.device_put(wave16), jax.device_put(bases)),
                n, wave16.nbytes + bases.nbytes)
        else:
            # a field escaped int16 (giant doc, huge window): ship the
            # wave at full width — rare, pays a 2x transfer + one extra
            # compile the first time it happens
            wave = self._stage_buffer((self.max_docs, K, OP_FIELDS),
                                      np.int32)
            wave[doc_idx, pos_idx] = flat
            staged = _StagedWave("dense", True, (jax.device_put(wave),),
                                 n, wave.nbytes)
        staged.flip = self._stage_flip
        dt = time.perf_counter() - t0
        # overlap accounting: this stage half counts as HIDDEN time when
        # a previously dispatched wave is still executing (is_ready is a
        # non-blocking completion probe, so the measurement never
        # perturbs the pipeline it measures)
        overlapped = (self._exec_marker is not None
                      and not self._exec_marker.is_ready())
        self.waves_staged += 1
        self.stage_seconds += dt
        self.stage_bytes += staged.nbytes
        if overlapped:
            self.stage_overlap_seconds += dt
        if self._mesh is not None:
            self.mesh_stage_seconds += dt
        reg = self._metrics()
        reg.inc("applier.stage.seconds", dt, lane=staged.lane)
        reg.inc("applier.stage.bytes", staged.nbytes, lane=staged.lane)
        reg.set_gauge("applier.stage.overlap_ratio",
                      self.stage_overlap_seconds / self.stage_seconds,
                      lane=staged.lane)
        # applier/stage hop: wall-clock stamp at stage completion, the
        # hoptail's clock — _execute_wave closes the stage→execute leg
        self._last_stage_wall = time.time()
        if self.fault_plane is not None:
            # chaos seam: wave N+1 staged (popped from the staging dict,
            # device buffers resident) but NOT yet executed — a crash
            # here must lose nothing: restore replays it from the log
            self.fault_plane("applier.stage.staged", ops=n)
        return staged

    def _execute_wave(self, staged: _StagedWave) -> int:
        """The DEVICE half: one jitted-step dispatch on already-resident
        buffers. With overlap on the dispatch is asynchronous — the
        caller's next stage half runs while the device executes; overlap
        off blocks until the step completes (the serialized pre-overlap
        behavior, kept for A/B)."""
        t0 = time.perf_counter()
        packed_fn, wide_fn = (self._sharded_step if staged.lane == "mesh"
                              else self._dense_step)
        fn = wide_fn if staged.wide else packed_fn
        self.state, _ = fn(self.state, *staged.arrays)
        self._exec_marker = self.state.count
        # the wave's staging buffers may be reused (and on CPU, where
        # device_put can alias host memory, even READ) only after this
        # execution completes — record its marker against the buffer set
        # the wave staged from, for _rotate_stage_buffers to fence on
        self._stage_inflight[staged.flip] = self._exec_marker
        if not self._overlap:
            jax.block_until_ready(self._exec_marker)
        dt = time.perf_counter() - t0
        self.exec_seconds += dt
        reg = self._metrics()
        reg.inc("applier.exec.seconds", dt, lane=staged.lane)
        # applier/execute hop: the dispatch-split leg of the hop
        # breakdown. Observed directly into the hop family (this wave
        # never rides a wire hoptail), and retained as last_wave_hops so
        # a subprocess ApplierStage can thread the stamps over its
        # backchannel for the parent core's registry.
        stage_wall = getattr(self, "_last_stage_wall", None)
        exec_wall = time.time()
        if stage_wall is not None:
            ms = (exec_wall - stage_wall) * 1e3
            reg.observe("obs.hop.ms", ms, pair="stage_to_execute")
            reg.observe_windowed("obs.hop.window_ms", ms,
                                 pair="stage_to_execute")
            self.last_wave_hops = ((stage_wall, exec_wall))
        self.dispatches += 1
        self._dispatches_since_check += 1
        if self.fault_plane is not None:
            # chaos seam: the wave is IN FLIGHT on device and the next
            # wave is not yet staged — the other overlap-window order
            self.fault_plane("applier.stage.inflight", ops=staged.n)
        return staged.n

    def stage_overlap_ratio(self) -> float:
        """staged-while-executing seconds / total stage seconds."""
        return (self.stage_overlap_seconds / self.stage_seconds
                if self.stage_seconds else 0.0)

    def _stage_wave_mesh(self, flat, packed, doc_idx, pos_idx, slots_a,
                         seq_base, text_base, n: int) -> _StagedWave:
        """Mesh-lane stage: scatter the wave into per-ACTIVE-shard
        buffers and hand each mesh device its own addressable shard, so
        host staging cost and transferred bytes are O(active shards · K),
        never O(max_docs), and the jitted step sees inputs already in its
        layout — no host-side global materialization, no XLA resharding.

        The wave's rows are sorted by shard ONCE (each shard's rows
        become a contiguous slice — the pre-overlap per-shard boolean
        masks rescanned the whole wave per shard, the linear host cost
        MULTICHIP_r06 measured), then the per-shard scatter+transfer jobs
        run on a small thread pool: the numpy fancy-index write and
        device_put both release the GIL, so active shards stage
        concurrently on multi-core hosts. ``packed=None`` ships the int32
        wide wave (int16 range escape / chaos force_wide)."""
        sps = self.placement.slots_per_shard
        K = self.K
        row_shard, local_doc = self.placement.split_rows(doc_idx)
        order = np.argsort(row_shard, kind="stable")
        sorted_shard = row_shard[order]
        active = np.unique(sorted_shard)
        n_active = len(active)
        lo = np.searchsorted(sorted_shard, active, side="left")
        hi = np.searchsorted(sorted_shard, active, side="right")
        ld, pi = local_doc[order], pos_idx[order]
        wide = packed is None
        dtype = np.int32 if wide else np.int16
        rows = (flat if wide else packed.astype(np.int16))[order]
        W = self._stage_buffer((n_active, sps, K, OP_FIELDS), dtype)
        if wide:
            B = dlo = dhi = ls = sb = tb = None
        else:
            B = self._stage_buffer((n_active, sps, 2), np.int32)
            doc_shard, local_slot = self.placement.split_rows(slots_a)
            dorder = np.argsort(doc_shard, kind="stable")
            sorted_doc_shard = doc_shard[dorder]
            dlo = np.searchsorted(sorted_doc_shard, active, side="left")
            dhi = np.searchsorted(sorted_doc_shard, active, side="right")
            ls = local_slot[dorder]
            sb, tb = seq_base[dorder], text_base[dorder]

        def job(i: int):
            a, b = lo[i], hi[i]
            W[i][ld[a:b], pi[a:b]] = rows[a:b]
            if B is not None:
                da, db = dlo[i], dhi[i]
                B[i][ls[da:db], 0] = sb[da:db]
                B[i][ls[da:db], 1] = tb[da:db]

        if n_active > 1:
            list(_stage_executor().map(job, range(n_active)))
        else:
            job(0)
        shard_waves = {int(s): W[i] for i, s in enumerate(active)}
        arrays = (self._mesh_assemble(shard_waves, (K, OP_FIELDS), dtype),)
        staged_bytes = n_active * sps * K * OP_FIELDS * W.itemsize
        if not wide:
            shard_bases = {int(s): B[i] for i, s in enumerate(active)}
            arrays += (self._mesh_assemble(shard_bases, (2,), np.int32),)
            staged_bytes += n_active * sps * 2 * 4
        self.mesh_waves += 1
        self.mesh_active_shards += n_active
        self.mesh_staged_bytes += staged_bytes
        return _StagedWave("mesh", wide, arrays, n, staged_bytes)

    def _mesh_assemble(self, shard_bufs: dict, tail: tuple,
                       dtype) -> jax.Array:
        """A global [max_docs, *tail] device array assembled from per-
        shard host buffers via ``jax.make_array_from_single_device_
        arrays``: every mesh device receives ITS row block directly (one
        device_put of the compact per-shard buffer; 'seg' replicas share
        the same buffer), and INACTIVE shards reuse a zero shard already
        resident on their device — no transfer at all."""
        key = (np.dtype(dtype).str,) + tail
        zeros = self._zero_shards.get(key)
        if zeros is None:
            z = np.zeros((self.placement.slots_per_shard,) + tail, dtype)
            zeros = {dev: jax.device_put(z, dev)
                     for devs in self._shard_devices for dev in devs}
            self._zero_shards[key] = zeros
        arrays = []
        for s, devs in enumerate(self._shard_devices):
            buf = shard_bufs.get(s)
            for dev in devs:
                arrays.append(zeros[dev] if buf is None
                              else jax.device_put(buf, dev))
        return jax.make_array_from_single_device_arrays(
            (self.max_docs,) + tail, self._mesh_sharding, arrays)

    def _worker_loop(self) -> None:
        import time as _time

        while True:
            self._wake.wait()
            if self._stop:
                return
            with self._lock:
                if not self._draining and self._min_wave \
                        and self._staged_ops < self._min_wave:
                    parts = None
                else:
                    parts = self._take_wave_locked()
                if parts is None:
                    self._wake.clear()
                    self._idle.set()
                    continue
                self._idle.clear()
            n = self._dispatch_wave(parts)
            with self._lock:
                self.ops_applied += n
            if self._dispatches_since_check >= self.overflow_check_every:
                # poll from the worker (it owns the device stream); defer
                # the actual escalation replay to the main thread's sync
                self._dispatches_since_check = 0
                flags = np.asarray(self.state.overflow)
                hit = set(int(s) for s in np.nonzero(flags)[0])
                if hit:
                    with self._lock:
                        self._overflow_slots |= hit
            _time.sleep(0)  # yield to the staging thread

    def close(self) -> None:
        if self._async:
            self._stop = True
            self._wake.set()
            self._worker.join(timeout=5)

    def finalize(self) -> None:
        """Flush staged ops and fence the device: after this, every doc's
        state (or its host escalation) reflects everything ingested."""
        if self._async:
            import time as _time

            self._draining = True
            try:
                while True:
                    self._wake.set()
                    with self._lock:
                        empty = not self._staged
                    if empty and self._idle.is_set():
                        break
                    _time.sleep(0.0005)
            finally:
                self._draining = False
            with self._lock:
                pending = sorted(self._overflow_slots)
                self._overflow_slots.clear()
            for slot in pending:
                if slot not in self._host_docs:
                    self._escalate(slot, None, None)
            self._drain_device()
            self._check_overflow()
            return
        self._flush_sync()
        self._drain_device()
        if self._dispatches_since_check:
            self._check_overflow()

    def _check_overflow(self) -> None:
        self._dispatches_since_check = 0
        flags = np.asarray(self.state.overflow)  # host sync point
        for slot in np.nonzero(flags)[0]:
            if int(slot) not in self._host_docs:
                self._escalate(int(slot), None, None)

    # ------------------------------------------------------------- queries

    def slot_count(self, tenant_id: str, document_id: str) -> int:
        """Live device slots for a doc (bounded under churn by zamboni)."""
        slot = self.slot_of(tenant_id, document_id)
        return int(np.asarray(self.state.count)[slot])

    def _device_slot(self, slot: int) -> DocState:
        return jax.tree.map(lambda a: np.asarray(a)[slot], self.state)

    def _sync(self, slot: int) -> None:
        """Flush + overflow-check before exposing a doc's state."""
        if self._async:
            self.finalize()
            return
        if self._staged.get(slot):
            self.flush()
        self._drain_device()
        if self._dispatches_since_check:
            self._check_overflow()

    def get_text(self, tenant_id: str, document_id: str) -> str:
        slot = self.slot_of(tenant_id, document_id)
        self._sync(slot)
        if slot in self._host_docs:
            return self._host_docs[slot].get_text()
        single = self._device_slot(slot)
        out, arena = [], self.arenas[slot]
        for i in range(int(single.count)):
            if single.rem_seq[i] != -1:
                continue
            if single.flags[i] & FLAG_MARKER:
                continue  # markers contribute length, not text
            out.append(arena.slice(int(single.text_start[i]), int(single.length[i])))
        return "".join(out)

    def get_tree(self, tenant_id: str, document_id: str) -> "MergeTreeClient":
        """Decode the doc to an oracle tree (summaries / inspection)."""
        slot = self.slot_of(tenant_id, document_id)
        self._sync(slot)
        if slot in self._host_docs:
            return self._host_docs[slot]
        tree = decode_state(self._device_slot(slot), self.arenas[slot],
                            self.prop_table)
        # flat replica: decode_state produces the flat oracle tree, so
        # don't build (then discard) the client's default blocked one
        replica = MergeTreeClient(f"tpu-applier/{tenant_id}/{document_id}",
                                  blocked=False)
        replica.tree = tree
        # carry the interning table: in-window stamps must translate back
        # to wire client ids when this replica snapshots (service
        # summaries would otherwise lose attribution)
        replica._ids.update(self._client_ids.get(slot, {}))
        return replica

    def applied_seq(self, tenant_id: str, document_id: str) -> int:
        """Highest sequence number ingested for the doc (0 if none).
        Summary writers compare this against the stream's last channel op
        to refuse writing a summary from lagging device state."""
        return self._applied_seq.get(
            self.slot_of(tenant_id, document_id), 0)

    def first_seq(self, tenant_id: str, document_id: str) -> int:
        """First sequence number ever ingested for the doc (0 if none)."""
        return self._first_seq.get(
            self.slot_of(tenant_id, document_id), 0)

    def is_anchored(self, tenant_id: str, document_id: str) -> bool:
        """True when the slot's state provably covers the doc's whole
        history (see the coverage-tracking comment in __init__)."""
        return self.slot_of(tenant_id, document_id) in self._anchored

    def mark_anchored(self, tenant_id: str, document_id: str) -> None:
        """Record a coverage proof established by the caller (the
        summarizer's gate pass). Also discharges any pending
        restore-window condition — the proof subsumes it."""
        slot = self.slot_of(tenant_id, document_id)
        self._anchored.add(slot)
        self._restore_applied.pop(slot, None)
        self._post_restore_first.pop(slot, None)

    def restore_gap(self, tenant_id: str, document_id: str
                    ) -> Optional[tuple[int, Optional[int]]]:
        """(applied seq at checkpoint restore, first seq ingested since)
        for a restored slot, else None. Ops sequenced in between were
        never ingested — the summarizer refuses if the stream shows any."""
        slot = self.slot_of(tenant_id, document_id)
        if slot not in self._restore_applied:
            return None
        return (self._restore_applied[slot],
                self._post_restore_first.get(slot))

    def get_properties_at(self, tenant_id: str, document_id: str,
                          pos: int) -> dict:
        """Properties of the visible character at ``pos`` (final
        perspective) — the annotate-path query surface."""
        slot = self.slot_of(tenant_id, document_id)
        self._sync(slot)
        if slot in self._host_docs:
            return self._host_docs[slot].get_properties_at(pos)
        single = self._device_slot(slot)
        cum = 0
        for i in range(int(single.count)):
            if single.rem_seq[i] != -1:
                continue
            if cum <= pos < cum + int(single.length[i]):
                props = {}
                for p in range(single.prop_key.shape[-1]):
                    kid = int(single.prop_key[i, p])
                    if kid != -1:
                        props[self.prop_table.key(kid)] = self.prop_table.val(
                            int(single.prop_val[i, p]))
                return props
            cum += int(single.length[i])
        raise IndexError(pos)

    # ---------------------------------------------------- host escalation

    def _escalate(self, slot: int, msg, wire_op) -> None:
        """Rebuild the doc on the scalar oracle from its authoritative op
        log and continue host-side (SURVEY §7(e) escape hatch)."""
        tenant_id, document_id = self._doc_keys[slot]
        # strict wave order at the escalation seam: the doc leaves the
        # device farm only after its last in-flight wave lands
        self._drain_device()
        if self._replay_log is None:
            # degrading to an empty replica would silently lose the doc
            raise RuntimeError(
                f"doc {tenant_id}/{document_id} needs host escalation but no "
                "replay source is configured (set_replay_source)")
        self.host_escalations += 1
        replica = MergeTreeClient(f"tpu-applier/{tenant_id}/{document_id}")
        self._host_docs[slot] = replica
        self._drop_staged(slot)
        for m in self._replay_log(tenant_id, document_id):
            if m.type == MessageType.OPERATION:
                replica.apply_msg(m, local=False)
        self._applied_seq[slot] = max(self._applied_seq.get(slot, 0),
                                      replica.tree.current_seq)
        # deliberately NOT anchored: the applier cannot verify the replay
        # source yielded the doc's whole history (a summary-aware source
        # starts at the summary) — the summarizer gate must re-prove
        # coverage before trusting this replica for a service summary
        self._anchored.discard(slot)
        if msg is not None:
            self._apply_host(slot, msg, wire_op)

    def _apply_host(self, slot: int, msg, wire_op) -> None:
        replica = self._host_docs[slot]
        if msg.sequence_number <= replica.tree.current_seq:
            return  # already covered by the escalation replay
        replica.apply_msg(replace(msg, contents=wire_op), local=False)

    # the host replay source: fn(tenant, doc) -> [SequencedDocumentMessage]
    # of CHANNEL-LEVEL merge-tree messages; wired by the service host
    _replay_log = None

    def set_replay_source(self, fn) -> None:
        self._replay_log = fn


# ----------------------------------------------------------- checkpointing

def save_applier_checkpoint(applier: "TpuDocumentApplier",
                            path: str) -> None:
    """Persist the applier's device-resident farm to disk: the [D, S]
    state arrays plus the host sidecars (text arenas, property interning,
    client tables, placement). A warm restart loads this instead of
    replaying every doc's op log through escalation — at 10k docs that is
    the difference between milliseconds and minutes (the applier analog
    of deli's Mongo checkpoint, SURVEY §5.4).

    Call after ``finalize()`` (the state must be fenced); host-mode docs
    are serialized as their oracle snapshots.
    """
    import json as _json

    applier.finalize()
    arrays = {f: np.asarray(getattr(applier.state, f))
              for f in ("length", "text_start", "flags", "ins_seq",
                        "ins_client", "rem_seq", "rem_client_a",
                        "rem_client_b", "prop_key", "prop_val", "count",
                        "overflow")}
    meta = {
        "max_docs": applier.max_docs,
        "max_slots": applier.max_slots,
        "arenas": [a.text() for a in applier.arenas],
        "prop_table": applier.prop_table.snapshot(),
        "client_ids": {str(k): v for k, v in applier._client_ids.items()},
        "doc_keys": {str(k): list(v) for k, v in applier._doc_keys.items()},
        "placement": applier.placement.snapshot(),
        "host_docs": {str(k): replica.snapshot()
                      for k, replica in applier._host_docs.items()},
        "host_doc_names": {str(k): applier._doc_keys[k]
                           for k in applier._host_docs},
        "applied_seq": {str(k): v
                        for k, v in applier._applied_seq.items()},
        "first_seq": {str(k): v for k, v in applier._first_seq.items()},
        "anchored": sorted(applier._anchored),
        # a still-PENDING restart window must survive the save: without
        # it, a save/load cycle would silently discharge an unverified
        # window (load resets gap_lo to the current applied seq, hiding
        # any downtime ops below it from the summarizer's gate)
        "restore_applied": {str(k): v
                            for k, v in applier._restore_applied.items()},
    }
    # crash-atomic commit: a periodic saver can be SIGKILLed mid-write,
    # and a torn .npz must never be what a restart loads. The arrays go
    # to an alternating generation file; the .json (which NAMES the
    # generation) is renamed into place last — the rename is the commit
    # point, and the previous consistent pair survives until then.
    import os as _os

    gen = int(meta.get("gen", 0))
    try:
        with open(path + ".json") as f:
            gen = 1 - int(_json.load(f).get("gen", 0))
    except (OSError, ValueError):
        pass
    meta["gen"] = gen
    npz_path = f"{path}.g{gen}.npz"
    with open(npz_path + ".tmp", "wb") as f:
        np.savez_compressed(f, **arrays)
    _os.replace(npz_path + ".tmp", npz_path)
    with open(path + ".json.tmp", "w") as f:
        _json.dump(meta, f)
    _os.replace(path + ".json.tmp", path + ".json")


def load_applier_checkpoint(path: str, **applier_kwargs
                            ) -> "TpuDocumentApplier":
    """Rebuild a fenced applier from ``save_applier_checkpoint`` output."""
    import json as _json

    from ..ops.doc_state import DocState as _DS

    with open(path + ".json") as f:
        meta = _json.load(f)
    applier = TpuDocumentApplier(max_docs=meta["max_docs"],
                                 max_slots=meta["max_slots"],
                                 **applier_kwargs)
    # generation-named arrays (crash-atomic saver); plain ".npz" is the
    # legacy single-generation layout (tests/golden pins it loadable)
    npz_path = (f"{path}.g{meta['gen']}.npz" if "gen" in meta
                else path + ".npz")
    data = np.load(npz_path)
    applier.state = _DS(**{k: jnp.asarray(data[k]) for k in data.files})
    if applier._mesh is not None:
        # a mesh applier's step requires state committed per P("docs");
        # without this re-shard the first dispatch would silently pay an
        # XLA relayout of every state array (or fail under shard_map)
        from ..parallel.sharded_apply import shard_state

        applier.state = shard_state(applier.state, applier._mesh)
    for slot, text in enumerate(meta["arenas"]):
        arena = TextArena()
        if text:
            arena.append(text)
        applier.arenas[slot] = arena
    applier.prop_table = PropTable.load(meta["prop_table"])
    applier._client_ids = {int(k): dict(v)
                           for k, v in meta["client_ids"].items()}
    applier._doc_keys = {int(k): tuple(v)
                         for k, v in meta["doc_keys"].items()}
    placement = DocPlacement.load(meta["placement"])
    if applier._mesh is not None and \
            placement.n_shards != applier.placement.n_shards:
        # the row↔device mapping is shard-major: restoring a checkpoint
        # onto a mesh with a different docs axis would route every doc
        # to the wrong device's rows
        raise ValueError(
            f"checkpoint placement has {placement.n_shards} shards but "
            f"the mesh's docs axis is {applier.placement.n_shards}")
    applier.placement = placement
    for k, snap in meta["host_docs"].items():
        tenant_id, document_id = meta["host_doc_names"][k]
        applier._host_docs[int(k)] = MergeTreeClient.load(
            f"tpu-applier/{tenant_id}/{document_id}", snap)
    applier._applied_seq = {int(k): v for k, v in
                            meta.get("applied_seq", {}).items()}
    applier._first_seq = {int(k): v for k, v in
                          meta.get("first_seq", {}).items()}
    # a checkpoint written by pre-coverage-tracking code carries no
    # anchor set; such slots restore UNANCHORED and the summarizer
    # refuses until coverage is re-proven — safe, never lossy
    applier._anchored = set(meta.get("anchored", []))
    # restored anchors are conditional: the summarizer additionally
    # verifies no ops were sequenced in the restart window (restore_gap)
    applier._restore_applied = dict(applier._applied_seq)
    # compose with any window the CHECKPOINT itself left unverified:
    # keep the older low bound, so the gate inspects the union
    # (old_lo, new_hi) — conservative (may refuse ops the saved state
    # actually covers, between the old resume point and the save), but
    # never discharges a real hole
    for k, v in meta.get("restore_applied", {}).items():
        slot = int(k)
        applier._restore_applied[slot] = min(
            v, applier._restore_applied.get(slot, v))
    return applier
