"""Doc history plane: commit/ref graph, fork, time-travel, integrate.

The reference protocol reserves ``fork``/``integrate`` MessageTypes it
never implements and models every summary as a git commit with refs
(gitrest). This plane builds that capability on a strictly better
substrate: content-addressed snapcols chunks (cross-generation dedupe)
plus the seq-indexed durable op log.

**Commit graph.** Every service-summarizer commit lands here as a
history commit ``{id, version, base_seq, parents, chunk_ids, ts}`` —
``version`` is the storage version handle (``vN``), ``base_seq`` its
capture seq, ``chunk_ids`` the content-addressed chunks the generation
references. **Refs** are named branch heads: ``refs/main`` follows the
doc's own summary chain; ``fork/<tenant>/<doc>`` on a PARENT pins the
commit a fork was cut from (the retention contract below). Records
persist per-doc in a flocked, torn-tail-tolerant append file
(protocol/refgraph.py) under ``<storage_dir>/history/`` when the server
has a storage dir, else in the db (in-proc restarts still recover).

**Fork** (``fork(tenant, doc, at_seq) -> new doc``) is O(snapshot)
bytes ≈ 0: the fork's v0 version record re-references the parent's root
blob and chunks verbatim (content-addressed, same store), the parent's
post-snapshot tail ``(B, at_seq]`` is adopted — already sequenced —
onto the fork's deltas topic, and deli/scribe/scriptorium checkpoints
are seeded so the fork's pipeline boots at ``at_seq`` exactly as if it
had lived the parent's history. Summarize-family ops in the tail ride
as NOOPs (their handles reference the parent's version chain). Clients
then boot the fork through the ordinary snapshot+bounded-backfill door.

**Time-travel** resolves any historical ``(doc, seq)`` to the nearest
commit at-or-below plus the bounded tail — served read-only through the
normal front_end doors riding read-only sessions (no join op, no quorum
seat); the driver side is driver/history.py ``open_at``.

**Integrate** replays a fork's post-base tail onto the parent through
the ordinary total order: a normal write connection submits the fork's
chanops as fresh client ops (refSeq = join head, which the integrating
client's own presence pins above the msn), so merge semantics come from
the CRDT — no new merge machinery. With a quiet parent the result is
the fork's exact text; with concurrent parent writers it is whatever
the merge tree converges to, identically on every replica.

**Chunk GC / retention pinning.** Scriptorium op-retention is per-doc
and unaffected by forks (the fork copies the tail it needs at fork
time). CHUNK retention is cross-doc: chunks are content-addressed and
shared, so the GC ref-counts across the commit graph — a chunk is live
iff some REF-REACHABLE head (any doc's branch head, any fork pin)
names it; only chunks named by superseded commits of scanned docs are
candidates. Trimming a parent can therefore never unlink blobs a live
fork still boots from.

**Crash atomicity.** Fork writes its commit record first, seeds the new
doc, then flips the refs (pin on the parent, ``refs/main`` on the
fork). A crash in between leaves a *pending* commit and possibly a torn
ref-file tail — recovery (on next load) adopts the fork iff its seeding
reached the durable versions topic, else discards it, atomically in
both directions; a dangling ref is impossible because refs are written
last and a torn ref record is dropped by CRC.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Optional

from ..obs import get_journal, tier_counters
from ..protocol import refgraph
from ..protocol.messages import DocumentMessage, MessageType
from ..utils.affinity import any_thread, loop_only
from .core import summary_versions_collection
from .local_orderer import CHECKPOINT_COLLECTION
from .scribe import SCRIBE_CHECKPOINT_COLLECTION
from .scriptorium import LogTruncatedError

#: ops whose contents reference the PARENT's summary chain — they ride
#: a fork's adopted tail as NOOPs (same seq/msn: the dense invariant
#: and the msn schedule must survive the adoption byte-for-byte)
_SUMMARY_TYPES = (MessageType.SUMMARIZE, MessageType.SUMMARY_ACK,
                  MessageType.SUMMARY_NACK)

MAIN_REF = "refs/main"


def fork_pin_ref(tenant_id: str, document_id: str) -> str:
    """The ref name on a PARENT pinning the commit a fork was cut from."""
    return f"fork/{tenant_id}/{document_id}"


class _DbRecords:
    """Record sink/source in the server db (no storage dir): survives
    in-proc orderer restarts; dies with the process like the rest of the
    db — the durable deployment uses :class:`refgraph.RefLog` instead."""

    def __init__(self, db, col: str):
        self._db = db
        self._col = col

    def load(self) -> list[dict]:
        col = self._db.collection(self._col)
        out = []
        for i in range(len(col)):
            rec = col.get(str(i))
            if rec is None:
                break
            records, _ = refgraph.scan_records(bytes.fromhex(rec["hex"]))
            out.extend(records)
        return out

    def append(self, *payloads: bytes) -> None:
        col = self._db.collection(self._col)
        n = len(col)
        data = b"".join(refgraph.frame_record(p) for p in payloads)
        self._db.upsert(self._col, str(n), {"hex": data.hex()})


class _DocHistory:
    """One doc's loaded commit graph + refs (fold of the record file)."""

    __slots__ = ("records", "commits", "refs", "discarded")

    def __init__(self, sink, records: list[dict]):
        self.records = sink
        self.commits, self.refs, self.discarded = \
            refgraph.replay_records(records)

    def head(self, ref: str = MAIN_REF) -> Optional[dict]:
        cid = self.refs.get(ref)
        return self.commits.get(cid) if cid else None

    def reachable_heads(self) -> list[dict]:
        """Commits some ref points AT (heads only — ancestry does not
        pin chunks; superseded generations are the GC's candidates)."""
        out = []
        for cid in set(self.refs.values()):
            c = self.commits.get(cid)
            if c is not None:
                out.append(c)
        return out


class HistoryPlane:
    """Per-server history service over the commit/ref graph."""

    #: chaos seam (fluidframework_tpu/chaos): crash directives at
    #: ``history.fork`` with ``stage`` = ``commit`` (commit record
    #: written, doc not seeded) or ``seeded`` (doc seeded, refs not yet
    #: flipped) tear the fork mid-flight; recovery must adopt-or-discard
    fault_plane = None

    def __init__(self, server):
        self.server = server
        self.counters = tier_counters("service")
        self._docs: dict = {}
        self._dir = None
        storage_dir = getattr(server, "storage_dir", None)
        if storage_dir:
            import os

            self._dir = os.path.join(storage_dir, "history")

    # ------------------------------------------------------------ store

    def _sink(self, tenant_id: str, document_id: str):
        if self._dir is not None:
            import os

            safe = f"{tenant_id}__{document_id}".replace("/", "_")
            return refgraph.RefLog(os.path.join(self._dir, safe + ".hist"))
        return _DbRecords(self.server.db,
                          f"history-records/{tenant_id}/{document_id}")

    @any_thread
    def _store(self, tenant_id: str, document_id: str) -> _DocHistory:
        key = (tenant_id, document_id)
        doc = self._docs.get(key)
        if doc is None:
            sink = self._sink(tenant_id, document_id)
            doc = _DocHistory(sink, sink.load())
            self._docs[key] = doc
            self._recover(tenant_id, document_id, doc)
        return doc

    def _append(self, doc: _DocHistory, *payloads: bytes) -> None:
        doc.records.append(*payloads)

    def _add_commit(self, doc: _DocHistory, commit: dict) -> None:
        doc.commits[commit["id"]] = commit

    def _set_ref(self, doc: _DocHistory, name: str,
                 commit_id: Optional[str]) -> None:
        if commit_id is None:
            doc.refs.pop(name, None)
        else:
            doc.refs[name] = commit_id

    # --------------------------------------------------------- recovery

    def _recover(self, tenant_id: str, document_id: str,
                 doc: _DocHistory) -> None:
        """Adopt-or-discard pending fork commits (crash mid-fork).

        A *pending* commit is a fork-origin commit no ref covers and no
        discard marker abandons. Adoption requires the fork's seeding to
        have reached the durable versions topic (the v0 record is the
        fork's boot source — without it the doc does not exist); then
        the missing refs are written. Otherwise a discard marker is
        appended and any half-written parent pin is deleted — either
        way the graph is consistent and no ref dangles."""
        covered = set(doc.refs.values())
        for cid, commit in list(doc.commits.items()):
            origin = (commit.get("extra") or {}).get("fork_of")
            if origin is None or cid in covered or cid in doc.discarded:
                continue
            seeded = False
            try:
                topic = f"versions/{tenant_id}/{document_id}"
                seeded = self.server.log.length(topic) > 0
            except Exception:
                seeded = False
            journal = get_journal()
            if seeded:
                pins = [refgraph.encode_ref(MAIN_REF, cid, ts=time.time())]
                self._append(doc, *pins)
                self._set_ref(doc, MAIN_REF, cid)
                pdoc = self._store(origin["tenant"], origin["doc"])
                pin = fork_pin_ref(tenant_id, document_id)
                if pin not in pdoc.refs:
                    self._append(pdoc, refgraph.encode_ref(
                        pin, commit["parents"][0], ts=time.time()))
                    self._set_ref(pdoc, pin, commit["parents"][0])
                action = "adopt"
            else:
                self._append(doc, refgraph.encode_discard(cid))
                doc.discarded.add(cid)
                pdoc = self._store(origin["tenant"], origin["doc"])
                pin = fork_pin_ref(tenant_id, document_id)
                if pin in pdoc.refs:
                    self._append(pdoc, refgraph.encode_ref(pin, None))
                    self._set_ref(pdoc, pin, None)
                action = "discard"
            journal.emit("history.ref.recover", tenant=tenant_id,
                         doc=document_id, commit=cid, action=action)
            self.counters.inc("history.ref.recovered")

    # ----------------------------------------------------------- commits

    @any_thread
    def record_commit(self, tenant_id: str, document_id: str,
                      version_id: str, base_seq: int,
                      chunk_ids: list, parents: Optional[list] = None,
                      extra: Optional[dict] = None,
                      ref: str = MAIN_REF) -> dict:
        """Record one summary generation as a commit and advance ``ref``
        to it — the summarizer's commit hook and the fork path both land
        here (the single graph-update path, like scribe.commit_version
        is for versions)."""
        doc = self._store(tenant_id, document_id)
        if parents is None:
            head = doc.head(ref)
            parents = [head["id"]] if head else []
        commit = {
            "id": self._commit_id(tenant_id, document_id, version_id,
                                  base_seq),
            "version": version_id,
            "base_seq": int(base_seq),
            "parents": list(parents),
            "chunk_ids": list(chunk_ids),
            "ts": time.time(),
            "extra": dict(extra or {}),
        }
        self._append(doc, refgraph.encode_commit(commit),
                     refgraph.encode_ref(ref, commit["id"],
                                         ts=commit["ts"]))
        self._add_commit(doc, commit)
        self._set_ref(doc, ref, commit["id"])
        self.counters.inc("history.commit.records")
        get_journal().emit("history.commit", tenant=tenant_id,
                           doc=document_id, version=version_id,
                           seq=base_seq)
        return commit

    @staticmethod
    def _commit_id(tenant_id: str, document_id: str, version_id: str,
                   base_seq: int) -> str:
        import hashlib

        return hashlib.sha256(
            f"{tenant_id}/{document_id}/{version_id}@{base_seq}".encode()
        ).hexdigest()[:16]

    @any_thread
    def log(self, tenant_id: str, document_id: str,
            count: Optional[int] = None) -> list[dict]:
        """Commits newest-first (the ``history log`` listing), seeded
        lazily from pre-plane acked snapcols versions on first touch."""
        doc = self._ensure_seeded(tenant_id, document_id)
        commits = sorted(doc.commits.values(),
                         key=lambda c: (c["base_seq"], c["ts"]),
                         reverse=True)
        commits = [c for c in commits if c["id"] not in doc.discarded]
        return commits[:count] if count else commits

    @any_thread
    def refs(self, tenant_id: str, document_id: str) -> dict:
        return dict(self._store(tenant_id, document_id).refs)

    @any_thread
    def commit_at(self, tenant_id: str, document_id: str,
                  seq: int) -> Optional[dict]:
        """Nearest commit with ``base_seq <= seq`` (the snapshot a
        time-travel read or fork boots from)."""
        best = None
        for c in self.log(tenant_id, document_id):
            if c["base_seq"] <= seq and (
                    best is None or c["base_seq"] > best["base_seq"]):
                best = c
        return best

    def _ensure_seeded(self, tenant_id: str, document_id: str) -> _DocHistory:
        """Backfill the graph from already-acked snapcols versions the
        summarizer committed before the plane existed (or before this
        server restart in db mode) — history must not start at 'now'."""
        doc = self._store(tenant_id, document_id)
        if doc.commits:
            return doc
        try:
            storage = self.server.storage(tenant_id, document_id)
            versions = storage.get_versions(1000)
        except Exception:
            return doc
        for v in reversed(versions):  # oldest first: parents chain up
            try:
                root = json.loads(storage.read_blob(v["tree_id"]).decode())
            except Exception:
                continue
            if root.get("t") != "snapcols":
                continue
            self.record_commit(tenant_id, document_id, v["id"],
                               root.get("sequence_number", 0),
                               root.get("chunks", ()))
        return doc

    # ------------------------------------------------------ delta reads

    @any_thread
    def read_deltas(self, tenant_id: str, document_id: str,
                    from_seq: int, to_seq: int) -> list:
        """Historical ops ``from_seq < seq < to_seq`` — scriptorium
        first; when retention trimmed below the range, fall back to a
        scan of the durable deltas topic from offset 0 (append-only:
        trimmed seqs are still physically present). History reads are
        *explicitly* historical, so the retention contract that protects
        live boots does not apply here."""
        orderer = self.server._get_orderer(tenant_id, document_id)
        try:
            return orderer.scriptorium.get_deltas(
                tenant_id, document_id, from_seq, to_seq)
        except LogTruncatedError:
            self.counters.inc("history.replay.log_scans")
            return self._scan_log(tenant_id, document_id, from_seq, to_seq)

    def _scan_log(self, tenant_id: str, document_id: str,
                  from_seq: int, to_seq: int) -> list:
        log = self.server.log
        topic = f"deltas/{tenant_id}/{document_id}"
        out: dict[int, object] = {}
        try:
            n = log.length(topic)
        except Exception:
            return []
        for i in range(n):
            rec = log.read(topic, i)
            msgs = None
            if isinstance(rec, dict):
                abatch = rec.get("abatch")
                if abatch is not None:
                    msgs = abatch.messages()
                else:
                    msgs = rec.get("boxcar") or [rec["message"]]
            if not msgs:
                continue
            for m in msgs:
                s = m.sequence_number
                if from_seq < s < to_seq:
                    out[s] = m  # crash-replay overlap: last write wins
        return [out[s] for s in sorted(out)]

    @any_thread
    def replay_read(self, tenant_id: str, document_id: str,
                    seq: int) -> dict:
        """Resolve a time-travel read: the commit to boot from plus its
        version/tree binding (the driver's ``open_at`` consumes this)."""
        commit = self.commit_at(tenant_id, document_id, seq)
        if commit is None:
            raise ValueError(
                f"no committed version at or below seq {seq} for "
                f"{tenant_id}/{document_id} (summarize first)")
        rec = self.server.db.find_one(
            summary_versions_collection(tenant_id, document_id),
            commit["version"])
        if rec is None:
            raise ValueError(f"version {commit['version']} record missing")
        self.counters.inc("history.replay.reads")
        return {"commit": refgraph.commit_to_json(commit),
                "version": {"id": commit["version"],
                            "tree_id": rec["tree_id"]},
                "base_seq": commit["base_seq"]}

    # -------------------------------------------------------------- fork

    @loop_only("core")
    def fork(self, tenant_id: str, document_id: str,
             at_seq: Optional[int] = None,
             new_doc: Optional[str] = None) -> dict:
        """Fork ``document_id`` at ``at_seq`` into ``new_doc``.

        Boots O(snapshot): the fork's v0 re-references the parent's root
        blob and chunks (content-addressed — zero new blob bytes on the
        same store), the already-sequenced tail ``(B, at_seq]`` is
        adopted verbatim onto the fork's topics, and the fork's pipeline
        checkpoints are seeded at ``at_seq``. Runs on the core loop:
        every mutation is new-doc-local except the parent tail read and
        the ref-file appends."""
        server = self.server
        server._check_revoked()
        orderer = server._get_orderer(tenant_id, document_id)
        head = orderer.deli.sequence_number
        if at_seq is None:
            at_seq = head
        if at_seq > head:
            raise ValueError(f"fork seq {at_seq} is beyond head {head}")
        if server._storage_conn is not None:
            raise ValueError(
                "fork over a storage-process deployment is not supported "
                "yet: the fork's v0 record must land in the storage "
                "server's version chain")
        commit = self.commit_at(tenant_id, document_id, at_seq)
        if commit is None:
            raise ValueError(
                f"no committed version at or below seq {at_seq} for "
                f"{tenant_id}/{document_id} (summarize first)")
        base = commit["base_seq"]
        if new_doc is None:
            new_doc = f"{document_id}-fork-{uuid.uuid4().hex[:8]}"
        self._check_fork_target(tenant_id, new_doc)

        parent_rec = server.db.find_one(
            summary_versions_collection(tenant_id, document_id),
            commit["version"])
        if parent_rec is None:
            raise ValueError(f"version {commit['version']} record missing")
        tree_id = parent_rec["tree_id"]
        root = json.loads(server.blob_store.get(tree_id).decode())
        tail = self.read_deltas(tenant_id, document_id, base, at_seq + 1)

        # 1) pending fork commit — crash after this point must leave a
        #    recoverable graph (no ref flips yet)
        fdoc = self._store(tenant_id, new_doc)
        fork_commit = {
            "id": self._commit_id(tenant_id, new_doc, "v0", base),
            "version": "v0",
            "base_seq": base,
            "parents": [commit["id"]],
            "chunk_ids": list(commit["chunk_ids"]),
            "ts": time.time(),
            "extra": {"fork_of": {"tenant": tenant_id, "doc": document_id,
                                  "seq": at_seq}},
        }
        self._append(fdoc, refgraph.encode_commit(fork_commit))
        self._add_commit(fdoc, fork_commit)
        self._chaos("history.fork", stage="commit", tenant=tenant_id,
                    doc=new_doc)

        # 2) seed the fork doc: version record + topics + checkpoints —
        #    all before any orderer exists for it, so construction
        #    rebuilds a consistent pipeline from these alone
        self._seed_fork(tenant_id, document_id, new_doc, root, tree_id,
                        base, at_seq, tail)
        self._chaos("history.fork", stage="seeded", tenant=tenant_id,
                    doc=new_doc)

        # 3) flip the refs: pin on the parent first (a live fork must
        #    never exist unpinned), then the fork's own head
        pdoc = self._store(tenant_id, document_id)
        pin = fork_pin_ref(tenant_id, new_doc)
        self._append(pdoc, refgraph.encode_ref(pin, commit["id"],
                                               ts=time.time()))
        self._set_ref(pdoc, pin, commit["id"])
        self._append(fdoc, refgraph.encode_ref(MAIN_REF, fork_commit["id"],
                                               ts=time.time()))
        self._set_ref(fdoc, MAIN_REF, fork_commit["id"])

        # 4) construct the fork's pipeline now: surfaces any seeding
        #    error at fork time and delivers the adopted tail
        forderer = server._get_orderer(tenant_id, new_doc)
        self._pump_doc(tenant_id, new_doc)

        self.counters.inc("history.fork.boots")
        self.counters.inc("history.fork.tail_ops", len(tail))
        get_journal().emit("history.fork", tenant=tenant_id,
                           doc=document_id, fork=new_doc, seq=at_seq,
                           base=base)
        return {"doc": new_doc, "parent": document_id,
                "base_seq": base, "fork_seq": at_seq,
                "version": commit["version"],
                "commit": fork_commit["id"],
                "shared_chunks": len(commit["chunk_ids"]),
                "tail_ops": len(tail),
                "head": forderer.deli.sequence_number}

    def _check_fork_target(self, tenant_id: str, new_doc: str) -> None:
        server = self.server
        if f"{tenant_id}/{new_doc}" in server._orderers:
            raise ValueError(f"fork target {new_doc!r} already exists")
        if server.db.collection(
                summary_versions_collection(tenant_id, new_doc)):
            raise ValueError(f"fork target {new_doc!r} already exists")
        try:
            if server.log.length(f"deltas/{tenant_id}/{new_doc}") > 0:
                raise ValueError(f"fork target {new_doc!r} already exists")
        except ValueError:
            raise
        except Exception:
            pass  # topic does not exist yet: good

    def _seed_fork(self, tenant_id: str, parent: str, new_doc: str,
                   root: dict, tree_id: str, base: int, at_seq: int,
                   tail: list) -> None:
        import dataclasses

        server = self.server
        # v0 version record: the parent's root blob verbatim — the
        # content-addressed chunks make this the whole O(snapshot) story
        rec = {"n": 0, "tree_id": tree_id, "parent": None,
               "acked": True, "seq": base, "_id": "v0"}
        server.db.upsert(summary_versions_collection(tenant_id, new_doc),
                         "v0", rec)
        server.log.append(f"versions/{tenant_id}/{new_doc}",
                          {"handle": "v0", "version": dict(rec)})
        # adopted tail rides the fork's deltas topic already-sequenced;
        # summarize-family ops neutralize to NOOPs (their handles
        # reference the parent's version chain), same seq/msn so the
        # dense invariant and msn schedule are preserved
        topic = f"deltas/{tenant_id}/{new_doc}"
        for m in tail:
            if m.type in _SUMMARY_TYPES:
                m = dataclasses.replace(m, type=MessageType.NOOP,
                                        contents=None)
            server.log.append(topic, {"tenant_id": tenant_id,
                                      "document_id": new_doc,
                                      "message": m})
        # pipeline checkpoints: deli at at_seq with an empty client table
        # (msn rides the seq until the first join), scribe's protocol at
        # the snapshot — its deltas-topic replay advances it over the
        # adopted tail (offset gate at -1 admits everything)
        key = f"{tenant_id}/{new_doc}"
        deli_state = {"log_offset": -1, "sequence_number": at_seq,
                      "clients": []}
        scribe_state = {"protocol": dict(root["protocol"]), "head": "v0",
                        "offset": -1}
        server.db.upsert(CHECKPOINT_COLLECTION, key, {"state": deli_state})
        server.db.upsert(SCRIBE_CHECKPOINT_COLLECTION, key,
                         {"state": scribe_state})
        # checkpoint-topic record: after full process death the db is
        # gone — the durable log must rebuild the same pipeline state
        server.log.append(f"checkpoints/{tenant_id}/{new_doc}",
                          {"deli": dict(deli_state),
                           "scribe": dict(scribe_state),
                           "scriptorium_base": base})

    def _pump_doc(self, tenant_id: str, document_id: str) -> None:
        """Deliver the doc's own queued topic records without draining
        the whole log (auto_drain=False tests keep their interleaving
        control over OTHER docs)."""
        log = self.server.log
        for topic in (f"deltas/{tenant_id}/{document_id}",
                      f"rawops/{tenant_id}/{document_id}"):
            try:
                while log.step(topic):
                    pass
            except Exception:
                break

    def _chaos(self, point: str, **ctx) -> None:
        plane = self.fault_plane
        if plane is not None:
            plane(point, **ctx)

    # --------------------------------------------------------- integrate

    @loop_only("core")
    def integrate(self, tenant_id: str, fork_doc: str,
                  batch: int = 64) -> dict:
        """Replay the fork's post-base tail onto its parent through the
        ordinary total order.

        A normal write connection joins the parent (its presence pins
        the msn at the join head), then submits the fork's chanops as
        fresh client ops with refSeq = join head. The CRDT does the
        merging: against a quiet parent this reproduces the fork's text
        exactly; against concurrent writers every replica converges to
        the same merge. Seal/revoke fencing and deli admission apply
        exactly as for any client — no side door into the log."""
        fstore = self._store(tenant_id, fork_doc)
        origin = None
        for c in fstore.commits.values():
            o = (c.get("extra") or {}).get("fork_of")
            if o is not None and c["id"] not in fstore.discarded:
                origin = o
                break
        if origin is None:
            raise ValueError(f"{fork_doc!r} is not a fork")
        parent, fork_seq = origin["doc"], origin["seq"]
        forderer = self.server._get_orderer(tenant_id, fork_doc)
        fork_head = forderer.deli.sequence_number
        tail = self.read_deltas(tenant_id, fork_doc, fork_seq,
                                fork_head + 1)
        envs = [m.contents for m in tail
                if m.type == MessageType.OPERATION
                and isinstance(m.contents, dict)
                and m.contents.get("kind") == "chanop"]
        conn = self.server.connect(tenant_id, parent,
                                   details={"integrate": fork_doc})
        try:
            # make sure the join is ticketed, then anchor refSeq at the
            # client's OWN post-join reference seq: the table entry pins
            # the msn at-or-below it, so these ops can never refSeq-nack
            # (the handshake seq alone could be stale if other clients'
            # queued records sequenced between capture and the join)
            self._pump_doc(tenant_id, parent)
            porderer = self.server._get_orderer(tenant_id, parent)
            cstate = porderer.deli.clients.get(conn.client_id)
            ref = (cstate.reference_sequence_number if cstate is not None
                   else conn.initial_sequence_number)
            msgs = [DocumentMessage(client_sequence_number=i + 1,
                                    reference_sequence_number=ref,
                                    type=MessageType.OPERATION,
                                    contents=env)
                    for i, env in enumerate(envs)]
            for i in range(0, len(msgs), batch):
                conn.submit(msgs[i:i + batch])
        finally:
            conn.disconnect()
        self.counters.inc("history.integrate.sessions")
        self.counters.inc("history.integrate.ops", len(envs))
        get_journal().emit("history.integrate", tenant=tenant_id,
                           doc=parent, fork=fork_doc, ops=len(envs),
                           fork_seq=fork_seq)
        return {"parent": parent, "fork": fork_doc, "ops": len(envs),
                "fork_seq": fork_seq, "fork_head": fork_head}

    # -------------------------------------------------------------- GC

    @any_thread
    def pinned_chunks(self, tenant_id: str, document_id: str) -> set:
        """Chunks any ref-reachable head of this doc still names."""
        doc = self._ensure_seeded(tenant_id, document_id)
        live: set = set()
        for c in doc.reachable_heads():
            live.update(c["chunk_ids"])
        return live

    @loop_only("core")
    def gc_chunks(self, tenant_id: str,
                  documents: Optional[list] = None) -> dict:
        """Sweep snapshot chunks no ref-reachable head names.

        Liveness ref-counts across the WHOLE commit graph: every scanned
        doc's branch heads AND every fork pin contribute — so trimming a
        parent whose old generation a live fork still boots from deletes
        nothing that fork needs (the pin holds its commit's chunks). The
        candidate set is restricted to chunks some commit of a scanned
        doc ever named: the blob store also holds roots, tree nodes and
        legacy blobs the graph knows nothing about, and those are never
        touched."""
        if documents is None:
            documents = sorted({d for (t, d) in self._docs
                                if t == tenant_id})
        live: set = set()
        candidates: set = set()
        roots: set = set()
        for d in documents:
            doc = self._ensure_seeded(tenant_id, d)
            for c in doc.commits.values():
                candidates.update(c["chunk_ids"])
            for c in doc.reachable_heads():
                live.update(c["chunk_ids"])
            # the root blob of every ref-reachable head stays too
            for c in doc.reachable_heads():
                rec = self.server.db.find_one(
                    summary_versions_collection(tenant_id, d), c["version"])
                if rec is not None:
                    roots.add(rec["tree_id"])
        store = self.server.blob_store
        delete = getattr(store, "delete", None)
        swept = 0
        dead = candidates - live - roots
        if delete is not None:
            for cid in sorted(dead):
                if delete(cid):
                    swept += 1
        self.counters.inc("history.gc.scanned", len(candidates))
        self.counters.inc("history.gc.pinned", len(live))
        self.counters.inc("history.gc.deleted", swept)
        get_journal().emit("history.gc", tenant=tenant_id,
                           scanned=len(candidates), pinned=len(live),
                           deleted=swept)
        return {"scanned": len(candidates), "pinned": len(live),
                "deleted": swept}
