"""Service-path load generator (BASELINE config 4 analog).

Ref: packages/test/service-load-test/src/nodeStressTest.ts + README.md:5-30
— an orchestrator driving N synthetic SharedString clients against a live
service, measuring end-to-end throughput and op-ack latency.

The synthetic editor submits VALID merge-tree wire ops without running a
full client replica: it tracks its own perspective's visible length from
the broadcast stream (+insert len, −remove span — its tracked length is a
lower bound on the true perspective length, so generated positions are
always resolvable), which is O(1) per op. Ops are real chanop envelopes,
so the TpuDocumentApplier can ride the same stream.

Two harnesses:
- ``run_inproc``: deli → scriptorium/scribe/broadcaster (+ optional
  TpuDocumentApplier) all in-process — the pipeline-throughput number.
- ``run_network``: clients on socket transports against a
  NetworkFrontEnd — the REAL p99 op-ack latency number.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Optional

from ..protocol.messages import MessageType
from .local_server import LocalServer
from .synthetic import CHANNEL_ID, DS_ID, SyntheticEditor  # noqa: F401
# SyntheticEditor lives in synthetic.py (import-light for load workers);
# re-exported here for the existing consumers


def _percentile(sorted_vals: list[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1, max(0, int(round(p * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


@dataclass
class LoadStats:
    ops_submitted: int = 0
    ops_acked: int = 0
    seconds: float = 0.0
    ack_latencies_ms: list[float] = field(default_factory=list)
    applier_ops: int = 0
    applier_escalations: int = 0
    # per-hop wire-trace latency (submit→deli, deli→ack), SURVEY §5.1
    hops: dict = field(default_factory=dict)

    @property
    def ops_per_sec(self) -> float:
        return self.ops_submitted / self.seconds if self.seconds else 0.0

    def latency_ms(self, p: float) -> float:
        return _percentile(sorted(self.ack_latencies_ms), p)

    def summary(self) -> dict:
        return {
            "ops": self.ops_submitted,
            "acked": self.ops_acked,
            "seconds": round(self.seconds, 3),
            "ops_per_sec": round(self.ops_per_sec, 1),
            "p50_ack_ms": round(self.latency_ms(0.50), 3),
            "p99_ack_ms": round(self.latency_ms(0.99), 3),
        }


def wire_applier(server: LocalServer, applier, tenant: str, docs: list[str]):
    """Subscribe a TpuDocumentApplier to the live broadcast of each doc
    (the scribe-position consumer of the sequenced stream). Op topics
    carry batches; the applier stages each batch in one call."""
    from .broadcaster import BroadcasterLambda

    op_t = MessageType.OPERATION

    def make_cb(doc):
        def cb(batch):
            if type(batch) is not list:  # array lane: bulk ingest
                box = batch.boxcar
                if box.ds_id == DS_ID and box.channel_id == CHANNEL_ID:
                    applier.ingest_array_batch(tenant, doc, batch)
                return
            pairs = []
            for msg in batch:
                if msg.type is not op_t:
                    continue
                env = msg.contents
                if type(env) is not dict or env.get("kind") != "chanop":
                    continue
                if env["address"] != DS_ID:
                    continue
                inner = env["contents"]
                if inner.get("address") != CHANNEL_ID or "attach" in inner:
                    continue
                pairs.append((msg, inner["contents"]))
            if pairs:
                applier.ingest_batch(tenant, doc, pairs)
        return cb

    for doc in docs:
        server.pubsub.subscribe(
            BroadcasterLambda.topic(tenant, doc), make_cb(doc))


def run_inproc(
    n_docs: int = 64,
    clients_per_doc: int = 2,
    ops_per_client: int = 50,
    seed: int = 0,
    applier=None,
    flush_every: int = 256,
    tenant: str = "bench",
    batch_size: int = 1,
    array_lane: bool = False,
    log=None,
) -> LoadStats:
    """Drive the full in-process pipeline at max rate; measure throughput.

    Every submitted op passes deli ticketing, scriptorium persistence,
    scribe protocol tracking, broadcast fan-out to every connected
    client, and (optionally) the TPU applier's device batch.

    ``batch_size``: ops each client submits per round as one boxcar (the
    outbound DeltaQueue flush / Kafka boxcar analog). ``ops_per_client``
    must be a multiple of it.

    ``array_lane``: submit ArrayBoxcars (the deli-tpu marshal,
    service/array_batch.py) — deli tickets with numpy, the applier
    bulk-loads chunks, subscribers consume batches without per-op
    message objects. Semantically equivalent to the dict lane
    (tests/test_array_lane.py pins the equivalence).
    """
    rng = random.Random(seed)
    server = LocalServer(log=log)
    docs = [f"doc{i}" for i in range(n_docs)]
    stats = LoadStats()

    if applier is not None:
        applier.set_replay_source(lambda t, d: [])
        wire_applier(server, applier, tenant, docs)

    sessions = []  # (conn, editor)
    submit_t = [0.0]  # the in-flight boxcar's submit timestamp
    for doc in docs:
        for _ in range(clients_per_doc):
            conn = server.connect(tenant, doc)
            editor = SyntheticEditor(rng)
            # track every broadcast op EXCEPT own (already tracked at submit)
            def on_ops(batch, editor=editor, me=conn.client_id):
                acked = 0
                for msg in batch:
                    if msg.client_id == me:
                        editor.ref_seq = msg.sequence_number
                        acked += 1
                    else:
                        editor.observe(msg)
                if acked:
                    # submit → own-broadcast latency for this boxcar (the
                    # in-proc ack time; ONE sample per boxcar — samples
                    # per op would be identical copies)
                    stats.ack_latencies_ms.append(
                        (time.perf_counter() - submit_t[0]) * 1e3)
                stats.ops_acked += acked
            conn.on_ops = on_ops
            if array_lane:
                # message LISTS (joins etc.) still route to on_ops above;
                # only SequencedArrayBatch objects land here
                def on_abatch(batch, editor=editor, me=conn.client_id):
                    if batch.boxcar.client_id == me:
                        editor.ref_seq = batch.last_seq
                        stats.ack_latencies_ms.append(
                            (time.perf_counter() - submit_t[0]) * 1e3)
                        stats.ops_acked += batch.n
                    else:
                        editor.observe_abatch(batch)
                conn.on_abatch = on_abatch
            sessions.append((conn, editor))

    assert ops_per_client % batch_size == 0
    rounds = ops_per_client // batch_size
    total = len(sessions) * ops_per_client
    since_flush = 0
    t0 = time.perf_counter()
    for i in range(rounds):
        for conn, editor in sessions:
            submit_t[0] = time.perf_counter()
            if array_lane:
                conn.submit_array(editor.next_boxcar(
                    batch_size, tenant, conn.document_id, conn.client_id))
            else:
                conn.submit(editor.next_ops(batch_size))
            stats.ops_submitted += batch_size
            since_flush += batch_size
            if applier is not None and since_flush >= flush_every:
                applier.flush()
                since_flush = 0
    if applier is not None:
        applier.flush()
        applier.finalize()
    stats.seconds = time.perf_counter() - t0

    if applier is not None:
        stats.applier_ops = applier.ops_applied
        stats.applier_escalations = applier.host_escalations
    assert stats.ops_submitted == total
    return stats


def run_network(
    port: int,
    n_docs: int = 2,
    clients_per_doc: int = 2,
    ops_per_client: int = 100,
    seed: int = 0,
    tenant: str = "bench",
    host: str = "127.0.0.1",
    timeout: float = 60.0,
    rate_hz: Optional[float] = None,
    doc_prefix: str = "netdoc",
) -> LoadStats:
    """Drive socket clients against a live front end; measure op-ack
    latency (submit → own op broadcast back) and throughput.

    ``rate_hz`` paces each SUBMISSION ROUND (one op per client) — without
    pacing the unbounded submit loop measures queueing depth, not service
    latency (the north-star p99 < 50 ms is an at-load number, not a
    saturation number)."""
    from ..driver.network import NetworkDocumentServiceFactory
    from ..protocol.messages import TraceHop
    from ..utils import TraceAggregator

    import threading

    rng = random.Random(seed)
    factory = NetworkDocumentServiceFactory(host, port)
    stats = LoadStats()
    traces = TraceAggregator()
    # acks arrive on per-connection reader threads; unsynchronized
    # read-modify-writes on the shared counters would drop increments
    stats_lock = threading.Lock()
    sessions = []

    for d in range(n_docs):
        doc = f"{doc_prefix}{d}"
        for _ in range(clients_per_doc):
            svc = factory.create_document_service(tenant, doc)
            conn = svc.connect_to_delta_stream()
            editor = SyntheticEditor(rng)
            pending: dict[int, float] = {}  # clientSeq → send time

            def on_op(msg, editor=editor, me=conn.client_id, pending=pending):
                if msg.client_id == me:
                    editor.ref_seq = msg.sequence_number
                    sent = pending.pop(msg.client_sequence_number, None)
                    now = time.time()
                    with stats_lock:
                        if sent is not None:
                            stats.ack_latencies_ms.append(
                                (time.perf_counter() - sent) * 1e3)
                        traces.record(msg, ack_time=now)
                        stats.ops_acked += 1
                else:
                    editor.observe(msg)
            conn.on_op = on_op
            sessions.append((conn, editor, pending))

    expected = len(sessions) * ops_per_client
    t0 = time.perf_counter()
    for i in range(ops_per_client):
        if rate_hz is not None:
            # absolute schedule so pacing error doesn't accumulate
            target = t0 + i / rate_hz
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
        for conn, editor, pending in sessions:
            with conn.lock:
                op = editor.next_op()
                # client-side trace stamp: deli appends its hop, and the
                # ack observer turns the pair into per-hop latency
                op.traces.append(
                    TraceHop(service="client", action="submit",
                             timestamp=time.time()))
                pending[op.client_sequence_number] = time.perf_counter()
                conn.submit([op])
            stats.ops_submitted += 1
    # wait for all acks
    deadline = time.time() + timeout
    while stats.ops_acked < expected and time.time() < deadline:
        time.sleep(0.002)
    stats.seconds = time.perf_counter() - t0
    stats.hops = traces.raw
    for conn, _, _ in sessions:
        conn.close()
    return stats


def _worker_main() -> None:
    """Subprocess load runner (ref: service-load-test nodeStressTest.ts —
    the orchestrator spawns N runner PROCESSES so client-side work never
    shares a GIL with the measurement). Prints one JSON result line."""
    import argparse
    import gc
    import json
    import sys

    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--docs", type=int, default=4)
    p.add_argument("--clients-per-doc", type=int, default=2)
    p.add_argument("--ops", type=int, default=200)
    p.add_argument("--rate", type=float, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--doc-prefix", default="netdoc")
    args = p.parse_args()

    gc.set_threshold(200000, 50, 50)
    gc.collect()
    gc.freeze()
    stats = run_network(
        args.port, n_docs=args.docs, clients_per_doc=args.clients_per_doc,
        ops_per_client=args.ops, seed=args.seed, host=args.host,
        rate_hz=args.rate, doc_prefix=args.doc_prefix)
    json.dump({
        "ops": stats.ops_submitted,
        "acked": stats.ops_acked,
        "seconds": stats.seconds,
        "lat_ms": stats.ack_latencies_ms,
        "hops": stats.hops,
    }, sys.stdout)
    print()


if __name__ == "__main__":
    _worker_main()
