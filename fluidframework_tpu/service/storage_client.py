"""RemoteStorage: the DocumentStorage surface over the storage process.

Ref: services-client/src/historian.ts:29 — every storage consumer (the
ordering service's summarizer, the drivers' snapshot boot) reaches
summaries through the storage service's REST surface, never its disk.
This client binds one (tenant, doc) to a storage_server.py process over
the shared framed-JSON transport; the ordering core hands these out via
``LocalServer.storage()`` when deployed with ``--storage-server``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..driver.network import _Transport


class StorageConnection:
    """One shared transport to the storage process (many docs ride it)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        import threading

        self._host, self._port, self._timeout = host, port, timeout
        self._t: Optional[_Transport] = None
        # front-end session threads and the orderer's ref-commit path
        # race the lazy connect; without the lock the loser's socket +
        # reader thread would leak
        self._lock = threading.Lock()

    def transport(self) -> _Transport:
        with self._lock:
            if self._t is None or self._t._closed:
                self._t = _Transport(self._host, self._port, self._timeout)
            return self._t

    def request(self, frame: dict) -> dict:
        return self.transport().request(frame)


class RemoteStorage:
    """DocumentStorage over the storage process, for one (tenant, doc).

    ``on_uploaded(version_id, record)`` fires after a summary upload —
    the ordering core uses it to mirror the version record into its db
    (scribe validation reads it there) and to announce the upload to an
    external scribe stage."""

    def __init__(self, conn: StorageConnection, tenant_id: str,
                 document_id: str,
                 on_uploaded: Optional[Callable] = None):
        self._conn = conn
        self._tenant = tenant_id
        self._doc = document_id
        self._on_uploaded = on_uploaded

    def _req(self, t: str, **kw) -> dict:
        return self._conn.request(
            {"t": t, "tenant": self._tenant, "doc": self._doc, **kw})

    # ------------------------------------------------- DocumentStorage api

    def get_versions(self, count: int = 1) -> list[dict]:
        return self._req("get_versions", count=count)["versions"]

    def get_snapshot_tree(self, version: Optional[dict] = None):
        return self._req("get_tree", version=version)["tree"]

    def read_blob(self, blob_id: str) -> bytes:
        return bytes.fromhex(self._req("read_blob", id=blob_id)["hex"])

    def write_blob(self, content: bytes) -> str:
        return self._req("write_blob", hex=content.hex())["id"]

    def upload_summary(self, summary: Any, parent: Optional[str]) -> str:
        from ..protocol.summary import (
            SummaryAttachment,
            SummaryBlob,
            SummaryHandle,
            SummaryTree,
            summary_to_wire,
        )

        if isinstance(summary, (SummaryTree, SummaryBlob, SummaryHandle,
                                SummaryAttachment)):
            summary = summary_to_wire(summary)
        out = self._req("upload_summary", summary=summary, parent=parent)
        if self._on_uploaded is not None:
            self._on_uploaded(out["id"], dict(out["record"]))
        return out["id"]

    # -------------------------------------------------- commit-graph extras

    def commit_ref(self, version_id: str) -> None:
        self._req("commit_ref", id=version_id)

    def get_ref(self) -> Optional[str]:
        return self._req("get_ref")["id"]

    def history(self, count: int = 50) -> list[dict]:
        return self._req("history", count=count)["commits"]

    def stats(self) -> dict:
        return self._conn.request({"t": "stats"})["stats"]
