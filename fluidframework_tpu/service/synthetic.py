"""Synthetic SharedString editor for load generation — import-light.

Extracted from load_gen.py so socket load WORKERS (load_async.py, one
process per CPU-starved core slice) import only the protocol layer:
load_gen pulls in LocalServer and, transitively, the JAX stack — ~2s of
single-core CPU per worker process, which on the 1-core bench host was
charged against the measured trial.

Ref: packages/test/service-load-test/src/nodeStressTest.ts (the
reference's synthetic client op source).
"""

from __future__ import annotations

import random

from ..protocol.messages import DocumentMessage, MessageType

DS_ID = "default"
CHANNEL_ID = "text"

_TEXT_POOL = "abcdefgh" * 4  # payload source: slicing beats per-char joins


class SyntheticEditor:
    """One synthetic client's op source for one document.

    Generation is deliberately cheap (single ``random()`` draws scaled to
    ranges, pooled payload text): at the north-star rate the generator
    runs inside the measured loop, so its cost is part of the headline.
    """

    def __init__(self, rng: random.Random, remove_fraction: float = 0.3,
                 annotate_fraction: float = 0.05, max_insert: int = 8):
        self.rng = rng
        self.length = 0  # lower bound on this perspective's visible length
        self.remove_fraction = remove_fraction
        self.annotate_fraction = annotate_fraction
        self.max_insert = max_insert
        self.client_seq = 0
        self.ref_seq = 0

    def observe(self, msg) -> None:
        """Track a broadcast sequenced message (anyone's, including own)."""
        self.ref_seq = msg.sequence_number
        if msg.type != MessageType.OPERATION:
            return
        env = msg.contents
        if type(env) is not dict or env.get("kind") != "chanop":
            return
        op = env["contents"]["contents"]
        self._track(op)

    def _track(self, op: dict) -> None:
        t = op["type"]
        if t == 0:
            self.length += len(op.get("text") or "￼")
        elif t == 1:
            self.length -= op["end"] - op["start"]
            if self.length < 0:
                self.length = 0

    def next_ops(self, count: int) -> list[DocumentMessage]:
        """Generate a submission batch (one outbound boxcar)."""
        rnd = self.rng.random
        rm, ann, mi = self.remove_fraction, self.annotate_fraction, self.max_insert
        ref_seq = self.ref_seq
        cseq = self.client_seq
        out = []
        for _ in range(count):
            r = rnd()
            length = self.length
            if length > 4 and r < rm:
                a = int(rnd() * (length - 1))
                b = a + 1 + int(rnd() * min(length - a - 1, mi - 1))
                op = {"type": 1, "start": a, "end": b}
                self.length = length - (b - a)
            elif length > 1 and r < rm + ann:
                a = int(rnd() * (length - 1))
                b = a + 1 + int(rnd() * min(length - a - 1, mi - 1))
                op = {"type": 2, "start": a, "end": b,
                      "props": {"k": int(rnd() * 4)}}
            else:
                n = 1 + int(rnd() * mi)
                off = int(rnd() * 8)
                op = {"type": 0, "pos": int(rnd() * (length + 1)),
                      "text": _TEXT_POOL[off:off + n]}
                self.length = length + n
            cseq += 1
            out.append(DocumentMessage(
                client_sequence_number=cseq,
                reference_sequence_number=ref_seq,
                type=MessageType.OPERATION,
                contents={"kind": "chanop", "address": DS_ID,
                          "contents": {"address": CHANNEL_ID, "contents": op}},
            ))
        self.client_seq = cseq
        return out

    def next_op(self) -> DocumentMessage:
        return self.next_ops(1)[0]

    def next_boxcar(self, count: int, tenant: str = "", doc: str = "",
                    client_id: str = ""):
        """Generate a submission batch as an ArrayBoxcar (the deli-tpu
        marshal lane): int arrays + one text blob, no per-op dicts. Same
        op mix and length-tracking contract as :meth:`next_ops`."""
        import numpy as np

        from .array_batch import ArrayBoxcar

        # build in python lists (numpy scalar writes cost ~5× a list
        # append), ONE array conversion per field at the end
        kind: list[int] = []
        a: list[int] = []
        b: list[int] = []
        text_off: list[int] = [0]
        texts: list[str] = []
        props = None
        rnd = self.rng.random
        rm, ann, mi = (self.remove_fraction, self.annotate_fraction,
                       self.max_insert)
        length = self.length
        off = 0
        for i in range(count):
            r = rnd()
            if length > 4 and r < rm:
                x = int(rnd() * (length - 1))
                y = x + 1 + int(rnd() * min(length - x - 1, mi - 1))
                kind.append(1)
                a.append(x)
                b.append(y)
                length -= y - x
            elif length > 1 and r < rm + ann:
                x = int(rnd() * (length - 1))
                y = x + 1 + int(rnd() * min(length - x - 1, mi - 1))
                kind.append(2)
                a.append(x)
                b.append(y)
                if props is None:
                    props = [None] * count
                props[i] = {"k": int(rnd() * 4)}
            else:
                n = 1 + int(rnd() * mi)
                o = int(rnd() * 8)
                kind.append(0)
                a.append(int(rnd() * (length + 1)))
                b.append(0)
                texts.append(_TEXT_POOL[o:o + n])
                off += n
                length += n
            text_off.append(off)
        base = self.client_seq
        self.client_seq = base + count
        self.length = length
        return ArrayBoxcar(
            tenant_id=tenant, document_id=doc, client_id=client_id,
            ds_id=DS_ID, channel_id=CHANNEL_ID,
            kind=np.asarray(kind, np.int8),
            a=np.asarray(a, np.int32), b=np.asarray(b, np.int32),
            cseq=np.arange(base + 1, base + count + 1, dtype=np.int32),
            rseq=np.full(count, self.ref_seq, np.int32),
            text="".join(texts),
            text_off=np.asarray(text_off, np.int32), props=props)

    def observe_abatch(self, batch) -> None:
        """Track another client's sequenced array batch (vectorized
        length deltas — the array-lane analog of :meth:`observe`)."""
        self.ref_seq = batch.last_seq
        box = batch.boxcar
        ins = int(box.text_off[-1])
        rem = int(((box.b - box.a) * (box.kind == 1)).sum())
        self.length = max(0, self.length + ins - rem)
