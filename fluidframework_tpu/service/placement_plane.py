"""Placement control plane: epoch-numbered routing + live doc migration.

Ref: memory-orderer/src/reservationManager.ts (lease reservations) and
the Kafka partition-reassignment protocol the reference inherits for
free — here an explicit subsystem over the flock-leased
``PlacementDir`` (service/placement.py):

- :class:`EpochTable` — a shard-dir routing table (``placement/
  table.json``) stamped with a monotone global epoch. Every ownership
  change (claim, release, migration transfer) bumps the epoch under one
  flock, so ANY two table states are ordered and a router can discard
  stale routes by comparing integers instead of re-reading leases.
- :class:`RoutingCache` — the gateway-side view: an in-memory dict on
  the hot path (no per-request lease reads), refreshed from the epoch
  table on miss and PATCHED by ``fplacement`` pushes from the cores on
  migration; an older epoch can never overwrite a newer route.
- :class:`MigrationEngine` — moves a live partition between cores
  without losing, duplicating or reordering a single op: seal → fence →
  checkpoint → handoff (atomic lease transfer, no unowned window) →
  epoch bump. In-flight submits bounce on the shed-retry lane (PR 7) and
  resubmit in client-sequence order against the new owner.

Fencing is layered: the seal refuses submits at the front door, the
lease-freshness clock refuses a stalled ex-owner, and deli's admission
refuses any record whose partition epoch is older than the table's
(``DeliLambda.epoch_fence``) — a doc mid-migration is sequenced by
exactly one core, provably.

The engine's ``fault_plane`` seam (class attribute, ``None`` by
default, same duck-typing as service/partitions.py) exposes the three
crash windows the chaos campaign kills: ``placement.pre_fence``,
``placement.pre_handoff``, ``placement.post_handoff``.
"""

from __future__ import annotations

import contextlib
import fcntl
import json
import os
import tempfile
import time
from typing import Callable, Optional

from ..obs import get_journal, tier_counters
from ..utils.affinity import blocking, holds_lock, loop_only
from .placement import PlacementDir

#: subdirectory of the shard dir holding the routing table
TABLE_DIRNAME = "placement"

#: core membership states in the table's ``cores`` section (elastic
#: membership, ref: consumer-group join/leave): ``active`` serves and
#: may receive rebalanced partitions, ``draining`` is being emptied by
#: live migration (``admin placement drain``), ``drained`` owns nothing
#: and is safe to decommission.
CORE_ACTIVE = "active"
CORE_DRAINING = "draining"
CORE_DRAINED = "drained"

_SHARED_COUNTERS = None


def placement_counters():
    """The module-held placement ``Counters`` for per-event seam call
    sites (deli's epoch fence, the front end's redirect bounces). Those
    sites must not mint a fresh ``tier_counters`` instance per event:
    the metrics registry tracks instances weakly, and a temporary dies
    before the next scrape ever sees its counts."""
    global _SHARED_COUNTERS
    if _SHARED_COUNTERS is None:
        _SHARED_COUNTERS = tier_counters("placement")
    return _SHARED_COUNTERS


def _flock(path: str):
    @contextlib.contextmanager
    def held():
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)
    return held()


class EpochTable:
    """Epoch-stamped doc→core routing table, one JSON file per shard dir.

    The table is a routing VIEW with total epoch order; the lease
    directory stays the liveness truth. A reader holding a stale table
    falls back to a lease read (``RoutingCache.refresh``), so a crash
    between a lease claim and the table write is merely a cache miss,
    never a wrong route that sticks.
    """

    def __init__(self, directory: str, counters=None, journal=None):
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, "table.json")
        self._lock_path = os.path.join(directory, "table.lock")
        self.counters = (counters if counters is not None
                         else tier_counters("placement"))
        # audit journal: disarmed singleton by default (free), or an
        # injected per-core instance (in-proc multi-core tests)
        self.journal = journal if journal is not None else get_journal()
        self._cache: Optional[dict] = None
        self._cache_stamp = None

    @classmethod
    def for_shard_dir(cls, shard_dir: str, counters=None) -> "EpochTable":
        return cls(os.path.join(shard_dir, TABLE_DIRNAME), counters=counters)

    # ------------------------------------------------------------- readers

    def read(self) -> dict:
        """Current table (mtime-cached): ``{"epoch": N, "parts":
        {"<k>": {"owner", "addr", "epoch"}}}``."""
        try:
            st = os.stat(self.path)
            stamp = (st.st_ino, st.st_mtime_ns, st.st_size)
        except OSError:
            return {"epoch": 0, "parts": {}}
        if self._cache is not None and stamp == self._cache_stamp:
            return self._cache
        rec = self._read_fresh()
        self._cache, self._cache_stamp = rec, stamp
        return rec

    def _read_fresh(self) -> dict:
        try:
            with open(self.path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return {"epoch": 0, "parts": {}}

    def global_epoch(self) -> int:
        return self.read()["epoch"]

    def epoch_of(self, k: int) -> int:
        part = self.read()["parts"].get(str(k))
        return part["epoch"] if part else 0

    def addr_of(self, k: int) -> Optional[str]:
        part = self.read()["parts"].get(str(k))
        return part["addr"] if part else None

    def part_epochs(self) -> dict[int, int]:
        """``{k: epoch}`` for every routed partition — the ShardHost
        refreshes its in-memory fence view from this once per poll."""
        return {int(k): p["epoch"]
                for k, p in self.read()["parts"].items()}

    def cores(self) -> dict:
        """Core membership: ``{owner: {"addr", "state"}}``. Registration
        is the ShardHost's per-poll ``record_core``; the rebalancer reads
        this to know which cores exist (cold joiners included — a fresh
        member owns nothing, so ``parts`` alone can't see it)."""
        return self.read().get("cores", {})

    def core_state(self, owner: str) -> Optional[str]:
        row = self.cores().get(owner)
        return row["state"] if row else None

    # ------------------------------------------------------------- writers

    def _write(self, rec: dict) -> None:
        d = os.path.dirname(self.path)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".table-")
        with os.fdopen(fd, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, self.path)

    @holds_lock("epoch_table_flock")
    def record_claim(self, k: int, owner: str, addr: str,
                     cause: Optional[str] = None) -> int:
        """Record that ``owner@addr`` now serves partition ``k`` (initial
        claim, takeover, or migration adoption). Returns the new epoch.
        ``cause`` links the journal's ``epoch.bump`` to the event that
        drove the ownership change (a migration adopt, a takeover)."""
        with _flock(self._lock_path):
            rec = self._read_fresh()
            rec["epoch"] += 1
            rec["parts"][str(k)] = {
                "owner": owner, "addr": addr, "epoch": rec["epoch"]}
            self._write(rec)
        self.counters.inc("placement.epoch.bumps")
        self.journal.emit("epoch.bump", cause=cause, epoch=rec["epoch"],
                          part=k, owner=owner, addr=addr, change="claim")
        return rec["epoch"]

    @holds_lock("epoch_table_flock")
    def record_release(self, k: int, owner: str,
                       cause: Optional[str] = None) -> Optional[int]:
        """Drop ``k``'s route if ``owner`` still holds it; the bump makes
        the removal itself ordered (a cached route older than the release
        epoch is discardable)."""
        with _flock(self._lock_path):
            rec = self._read_fresh()
            part = rec["parts"].get(str(k))
            if part is None or part["owner"] != owner:
                return None
            rec["epoch"] += 1
            del rec["parts"][str(k)]
            self._write(rec)
        self.counters.inc("placement.epoch.bumps")
        self.journal.emit("epoch.bump", cause=cause, epoch=rec["epoch"],
                          part=k, owner=owner, change="release")
        return rec["epoch"]

    @holds_lock("epoch_table_flock")
    def record_core(self, owner: str, addr: str,
                    host: Optional[str] = None) -> None:
        """Register ``owner@addr`` as a member (ShardHost calls this once
        per poll — cheap no-op when the row already matches). Membership
        is a capacity advertisement, not a route: nothing fences on it,
        so it does NOT bump the epoch. An existing draining/drained mark
        survives re-registration — the drain decision outlives the
        core's own heartbeat. ``host`` is the member's host-group id
        (multi-host fleets): the rebalancer's locality tiebreak and the
        gateways' same-host accounting read it back from the row."""
        row = self.cores().get(owner)
        if row is not None and row["addr"] == addr \
                and row.get("host") == host:
            return
        with _flock(self._lock_path):
            rec = self._read_fresh()
            cores = rec.setdefault("cores", {})
            prev = cores.get(owner)
            cores[owner] = {
                "addr": addr,
                "state": prev["state"] if prev else CORE_ACTIVE}
            if host is not None:
                cores[owner]["host"] = host
            self._write(rec)

    @holds_lock("epoch_table_flock")
    def set_core_state(self, owner: str, state: str,
                       cause: Optional[str] = None) -> bool:
        """Flip a member's state (``admin placement drain``, or the
        rebalancer marking a drained core). False for unknown owners —
        draining a core that never registered is an operator typo, not
        a pending instruction."""
        changed = False
        with _flock(self._lock_path):
            rec = self._read_fresh()
            row = rec.get("cores", {}).get(owner)
            if row is None:
                return False
            if row["state"] != state:
                row["state"] = state
                self._write(rec)
                changed = True
        if changed:
            self.journal.emit("core.state", cause=cause,
                             epoch=rec["epoch"], owner=owner, state=state)
        return True

    @holds_lock("epoch_table_flock")
    def remove_core(self, owner: str, cause: Optional[str] = None) -> None:
        """Forget a decommissioned member entirely."""
        removed = False
        with _flock(self._lock_path):
            rec = self._read_fresh()
            if rec.get("cores", {}).pop(owner, None) is not None:
                self._write(rec)
                removed = True
        if removed:
            self.journal.emit("core.state", cause=cause,
                              epoch=rec["epoch"], owner=owner,
                              state="removed")


class RoutingCache:
    """Gateway-side doc→core routing: dict lookup on the hot path.

    Replaces per-request ``PlacementDir.owner_of`` reads. Misses refresh
    from the epoch table (one mtime-cached file read), falling back to a
    lease read for partitions the table hasn't seen; ``fplacement``
    pushes from the cores patch entries the moment a migration commits.
    Epoch-stamped invalidation: an update only lands if its epoch is
    newer than the cached one, so a delayed push about yesterday's owner
    cannot clobber today's route.
    """

    def __init__(self, placement: PlacementDir, table: EpochTable,
                 counters=None):
        self.placement = placement
        self.table = table
        self.counters = (counters if counters is not None
                         else tier_counters("placement"))
        self.addrs: dict[int, Optional[str]] = {}
        self.epochs: dict[int, int] = {}

    def resolve(self, k: int) -> Optional[str]:
        addr = self.addrs.get(k)
        if addr is not None:
            self.counters.inc("placement.cache.hits")
            return addr
        return self.refresh(k)

    def refresh(self, k: int) -> Optional[str]:
        """Re-read ``k``'s route: epoch table first, lease directory as
        the liveness fallback (covers the claim→table-write crash gap and
        pre-epoch-table deployments)."""
        self.counters.inc("placement.cache.refreshes")
        part = self.table.read()["parts"].get(str(k))
        if part is not None and part["epoch"] >= self.epochs.get(k, 0):
            self._store(k, part["addr"], part["epoch"])
            return part["addr"]
        addr = self.placement.owner_of(k)
        if addr is not None:
            self._store(k, addr, self.epochs.get(k, 0))
        return addr

    def note_epoch(self, k: int, addr: Optional[str], epoch: int) -> bool:
        """Apply a pushed route (``fplacement``) iff it is newer than the
        cached epoch. Returns True when the route changed."""
        if epoch <= self.epochs.get(k, 0):
            return False
        self._store(k, addr, epoch)
        return True

    def invalidate(self, k: int) -> None:
        """Dial failure against the cached address: drop the route (the
        epoch stays, so only a NEWER route can repopulate via push)."""
        self.addrs.pop(k, None)
        self.counters.inc("placement.cache.invalidations")

    def _store(self, k: int, addr: Optional[str], epoch: int) -> None:
        if addr is None:
            self.addrs.pop(k, None)
        else:
            self.addrs[k] = addr
        self.epochs[k] = epoch


class MigrationEngine:
    """Live migration of one partition between two cores.

    Source-side protocol (:meth:`migrate`):

    1. **seal** — the source's LocalServer refuses new submits; the front
       end bounces them on the shed-retry lane (echoed op +
       ``retry_after_ms``), so drivers park and resubmit in cseq order.
    2. **fence** — record each live doc's deli sequence number; the
       ordering loop is single-threaded, so after the seal nothing new
       can be ticketed and these are exact.
    3. **checkpoint** — ``checkpoint_all`` + durable-log flush: the deli/
       scribe state the target resumes from (the same machinery
       partitions.py uses for crash recovery). The raw-log tail past the
       checkpoint replays idempotently on the target.
    4. **handoff** — the target adopts: atomic lease TRANSFER under the
       partition flock (owner rewritten in place — no unowned window a
       third core could steal), epoch-table claim, server rebuild.
    5. **flip** — the source pushes the new route (``fplacement``) and
       drops the partition's sessions; clients reconnect and land on the
       target via the refreshed routing cache.

    A source crash anywhere in this sequence is the chaos campaign's
    subject: before the fence the migration simply never happened (lease
    TTL takeover recovers); after the handoff the target already owns the
    log. The engine never holds both cores' state — the target side is
    :meth:`adopt`, reachable in-proc (tests, chaos) or over the admin
    plane (``admin_adopt_partition``).
    """

    #: chaos seam (duck-typed FaultPlane), None when disarmed
    fault_plane = None

    def __init__(self, host, counters=None, journal=None):
        # ``host`` is duck-typed (front_end.ShardHost): owner_id, address,
        # placement, table, servers, hb_times, claim_epochs, table_epochs,
        # migrating, _make_server(k)
        self.host = host
        self.counters = (counters if counters is not None
                         else tier_counters("placement"))
        self.journal = journal if journal is not None else get_journal()
        self._adopt_cause: Optional[str] = None
        self._adopt_log_blob: Optional[str] = None

    # -------------------------------------------------------------- source

    @loop_only("core")
    def migrate(self, k: int, target_addr: str,
                adopt: Optional[Callable[[int, str], dict]] = None,
                on_flip: Optional[Callable] = None,
                cause: Optional[str] = None) -> dict:
        """Move partition ``k`` from this host to ``target_addr``.

        ``adopt(k, from_owner)`` performs the target side; defaults to an
        ``admin_adopt_partition`` RPC against ``target_addr``. ``on_flip``
        (if given) runs after the epoch bump with ``(k, target_addr,
        epoch, server)`` — the front end uses it to push ``fplacement``
        and drop the partition's live sessions. ``cause`` roots the
        journal chain (the rebalance actuation or operator command that
        asked for the move); every phase then links to the previous
        one, and the cause id crosses to the target over the adopt RPC,
        so the fleet-merged journal shows one connected chain:
        cause → seal → fence → checkpoint → adopt → epoch bump → commit.
        """
        host = self.host
        server = host.servers.get(k)
        if server is None:
            raise RuntimeError(f"not the owner of partition {k}")
        if k in host.migrating:
            raise RuntimeError(f"partition {k} already migrating")
        host.migrating.add(k)
        jr = self.journal
        try:
            if self.fault_plane is not None:
                self.fault_plane("placement.pre_fence", k=k)
            # 1. seal: submits bounce from here on (front-end shed nacks)
            server.seal()
            seal_id = jr.emit("migration.seal", cause=cause, part=k,
                              target=target_addr)
            # 2. fence seqs: drain queued raw records first, then they are
            # exact — sealed + single-threaded means nothing is in flight
            server.drain()
            fences = server.doc_sequence_numbers()
            fence_id = jr.emit("migration.fence", cause=seal_id, part=k,
                               docs=len(fences))
            # 3. checkpoint + flush: the state the target resumes from
            server.checkpoint_all()
            flush = getattr(server.log, "flush", None)
            if flush is not None:
                flush()
            self.counters.inc("placement.migration.fences")
            ckpt_id = jr.emit("migration.checkpoint", cause=fence_id,
                              part=k)
            if self.fault_plane is not None:
                self.fault_plane("placement.pre_handoff", k=k)
            # stop heartbeating/serving k BEFORE the transfer: the lease
            # stays ours (fresh) until the target rewrites it in place
            host.hb_times.pop(k, None)
            host.servers.pop(k, None)
            server.revoke()
            # cross-host handoff: the flushed log dir lives in THIS host
            # group's disjoint working dir, so ship it through the shared
            # storage tier — the target then resumes from exactly the
            # checkpoint + idempotent tail a shared filesystem would give
            log_blob = self._ship_log(k, target_addr, cause=ckpt_id)
            # 4. handoff: the target transfers the lease + claims the epoch
            do_adopt = adopt if adopt is not None else self._rpc_adopt
            self._adopt_cause = ckpt_id
            self._adopt_log_blob = log_blob
            try:
                result = do_adopt(k, target_addr)
            except Exception as exc:
                jr.emit("migration.fail", cause=ckpt_id, part=k,
                        target=target_addr, error=str(exc))
                self._reclaim(k)
                raise
            finally:
                self._adopt_cause = None
                self._adopt_log_blob = None
            if self.fault_plane is not None:
                # the "source dies during target replay" window: the
                # target owns the lease + epoch; the source merely fails
                # to push the flip (clients discover via reconnect)
                self.fault_plane("placement.post_handoff", k=k)
            epoch = result["epoch"]
            self.counters.inc("placement.migration.committed")
            jr.emit("migration.commit",
                    cause=result.get("journal") or ckpt_id, part=k,
                    target=target_addr, epoch=epoch)
            # 5. flip: push the new route, drop the sealed sessions
            if on_flip is not None:
                on_flip(k, target_addr, epoch, server)
            return {"k": k, "target": target_addr, "epoch": epoch,
                    "fences": fences}
        finally:
            host.migrating.discard(k)

    def _reclaim(self, k: int) -> None:
        """Adoption failed before the lease moved: the lease is still
        ours, so rebuild the partition server and resume serving."""
        host = self.host
        self.counters.inc("placement.migration.failed")
        if host.placement.try_claim(k, host.owner_id, host.address):
            host.claim_epochs[k] = host.table.record_claim(
                k, host.owner_id, host.address)
            host.servers[k] = host._make_server(k)
            host.hb_times[k] = time.monotonic()

    def _host_of_addr(self, addr: str) -> Optional[str]:
        """The host-group id advertising ``addr`` in the table's cores
        section, or None (single-host fleet / unregistered core)."""
        for row in self.host.table.cores().values():
            if row.get("addr") == addr:
                return row.get("host")
        return None

    @loop_only("core")
    def _ship_log(self, k: int, target_addr: str,
                  cause: Optional[str] = None) -> Optional[str]:
        """When the target core lives in ANOTHER host group, upload the
        (sealed, flushed) ``log-<k>`` dir to the shared storage tier as
        one blob and return its id for the adopt frame. Same-host and
        single-host moves return None — the shared dir IS the transport
        there, exactly as before."""
        my_host = getattr(self.host, "host_id", None)
        if my_host is None:
            return None
        dst_host = self._host_of_addr(target_addr)
        if dst_host is None or dst_host == my_host:
            return None
        storage = getattr(self.host, "storage_server", None)
        if storage is None:
            raise RuntimeError(
                f"cross-host migration of partition {k} needs a storage "
                "tier to ship the durable log through")
        import io
        import tarfile

        log_dir = os.path.join(self.host.shard_dir, f"log-{k}")
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tf:
            tf.add(log_dir, arcname=".")
        s_host, s_port = storage
        reply = admin_rpc(s_host, int(s_port),
                          {"t": "write_blob", "hex": buf.getvalue().hex()})
        self.journal.emit("migration.ship", cause=cause, part=k,
                          src_host=my_host, dst_host=dst_host,
                          blob=reply["id"], bytes=buf.getbuffer().nbytes)
        return reply["id"]

    def _fetch_log(self, k: int, log_blob: str) -> None:
        """Target side of the ship: replace the local ``log-<k>`` dir
        with the shipped one (any content there is a dead generation —
        the partition's live log just arrived)."""
        import io
        import shutil
        import tarfile

        storage = getattr(self.host, "storage_server", None)
        if storage is None:
            raise RuntimeError(
                f"adopting partition {k} with a shipped log needs a "
                "storage tier")
        s_host, s_port = storage
        reply = admin_rpc(s_host, int(s_port),
                          {"t": "read_blob", "id": log_blob})
        log_dir = os.path.join(self.host.shard_dir, f"log-{k}")
        shutil.rmtree(log_dir, ignore_errors=True)
        os.makedirs(log_dir, exist_ok=True)
        buf = io.BytesIO(bytes.fromhex(reply["hex"]))
        with tarfile.open(fileobj=buf, mode="r:gz") as tf:
            try:
                tf.extractall(log_dir, filter="data")
            except TypeError:  # filter= needs py3.12; our own archive
                tf.extractall(log_dir)  # noqa: S202

    @loop_only("core")
    def _rpc_adopt(self, k: int, target_addr: str) -> dict:
        """Default target-side handoff: one blocking admin RPC against the
        target core (uniform deployments share the admin secret)."""
        host_s, _, port_s = target_addr.rpartition(":")
        frame = {"t": "admin_adopt_partition", "k": k,
                 "from_owner": self.host.owner_id}
        if self._adopt_cause:
            # the cause id crosses the wire so the TARGET core's journal
            # links its adopt entry back to the source's checkpoint —
            # the fleet merge stitches the chain across processes
            frame["journal_cause"] = self._adopt_cause
        if self._adopt_log_blob:
            frame["log_blob"] = self._adopt_log_blob
        secret = getattr(self.host, "admin_secret", None)
        if secret:
            frame["secret"] = secret
        return admin_rpc(host_s or "127.0.0.1", int(port_s), frame)

    # -------------------------------------------------------------- target

    @loop_only("core")
    def adopt(self, k: int, from_owner: str, cause: Optional[str] = None,
              log_blob: Optional[str] = None) -> dict:
        """Target side: take over ``k`` from ``from_owner`` and resume its
        pipeline from the shipped checkpoint + idempotent raw-log tail.
        ``log_blob`` (cross-host moves) names the storage-tier blob
        carrying the source's sealed log dir; it is materialized BEFORE
        the lease transfer so a fetch failure aborts the handoff while
        the source can still reclaim."""
        host = self.host
        if log_blob:
            self._fetch_log(k, log_blob)
        if not host.placement.transfer(k, from_owner, host.owner_id,
                                       host.address):
            if log_blob:
                import shutil

                shutil.rmtree(
                    os.path.join(host.shard_dir, f"log-{k}"),
                    ignore_errors=True)
            raise RuntimeError(
                f"partition {k} not transferable from {from_owner}")
        adopt_id = self.journal.emit("migration.adopt", cause=cause,
                                     part=k, from_owner=from_owner)
        epoch = host.table.record_claim(k, host.owner_id, host.address,
                                        cause=adopt_id)
        host.claim_epochs[k] = epoch
        host.table_epochs[k] = epoch
        server = host._make_server(k)
        host.servers[k] = server
        host.hb_times[k] = time.monotonic()
        self.counters.inc("placement.migration.adopted")
        return {"epoch": epoch, "journal": adopt_id}


@blocking("synchronous socket dial + rid round trip — the loopback "
          "migration/actuation seam (PR 10); never call on the loop "
          "unless the synchrony IS the design")
def admin_rpc(host: str, port: int, frame: dict,
              timeout: float = 30.0) -> dict:
    """One rid-matched admin RPC round trip (length-prefixed JSON — the
    same wire shape bench.py and the admin CLI use)."""
    import socket

    with socket.create_connection((host, port), timeout=timeout) as s:
        body = json.dumps(dict(frame, rid=1)).encode()
        s.sendall(len(body).to_bytes(4, "big") + body)

        def read_exactly(n):
            buf = b""
            while len(buf) < n:
                chunk = s.recv(n - len(buf))
                if not chunk:
                    raise ConnectionError("closed")
                buf += chunk
            return buf

        while True:
            n = int.from_bytes(read_exactly(4), "big")
            reply = json.loads(read_exactly(n).decode())
            if reply.get("rid") != 1:
                continue
            if reply.get("t") == "error":
                raise RuntimeError(reply.get("message"))
            return reply
