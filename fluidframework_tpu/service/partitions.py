"""Partition runtime: hosts, partitions, rebalance (the lambdas-driver).

Ref: server/routerlicious/packages/lambdas-driver — KafkaRunner starts a
PartitionManager (kafka-service/partitionManager.ts:22) which owns one
Partition per Kafka partition (partition.ts:24); documents hash onto
partitions; a consumer-group rebalance (partitionManager.ts:93-111)
checkpoints and closes the partitions that move away and recreates them
on their new host from the stored checkpoint. The document-router demuxes
each partition into per-document lambdas.

Here: a :class:`PartitionManager` spreads N partitions over registered
hosts and routes each ``(tenant, doc)`` to its partition's host. Each
:class:`Partition` lazily builds the per-document pipeline (LocalOrderer:
real deli/scribe/scriptorium/broadcaster over the shared log). Moving a
partition checkpoints every document pipeline it owns and closes it; the
next host resumes from those checkpoints, and deli's log-offset
idempotency absorbs replayed raw records. ``remove_host`` (crash
recovery) skips the graceful checkpoint — recovery leans entirely on the
last durable checkpoint + raw-log replay.
"""

from __future__ import annotations

import hashlib
from typing import Optional

from .local_orderer import LocalOrderer


def partition_of(tenant_id: str, document_id: str, n_partitions: int) -> int:
    key = f"{tenant_id}/{document_id}".encode()
    return int.from_bytes(hashlib.sha1(key).digest()[:4], "little") \
        % n_partitions


class Partition:
    """One partition's per-document pipelines on its current host."""

    #: chaos seam (fluidframework_tpu/chaos): a crash mid-checkpoint —
    #: some orderers checkpointed, the rest not — the partial-progress
    #: window a rebalance-during-crash exposes. None = disarmed.
    fault_plane = None

    def __init__(self, pid: int, log, db, pubsub, clock=None):
        self.pid = pid
        self._log = log
        self._db = db
        self._pubsub = pubsub
        self._clock = clock
        self.orderers: dict[str, LocalOrderer] = {}

    def orderer(self, tenant_id: str, document_id: str) -> LocalOrderer:
        key = f"{tenant_id}/{document_id}"
        o = self.orderers.get(key)
        if o is None:
            kw = {}
            if self._clock is not None:
                kw["clock"] = self._clock
            o = self.orderers[key] = LocalOrderer(
                tenant_id, document_id, self._log, self._db, self._pubsub,
                **kw)
        return o

    def checkpoint(self) -> None:
        """Checkpoint every doc pipeline. One raising orderer must not
        abort the rest — every doc that CAN shrink its replay window
        does; each failure is journaled and the first re-raises at the
        end so callers still see it."""
        first_err = None
        for key, o in self.orderers.items():
            if self.fault_plane is not None:
                # kill between one doc's checkpoint and the next: the
                # un-checkpointed docs recover by raw-log replay
                self.fault_plane("partition.checkpoint", pid=self.pid)
            try:
                o.checkpoint()
            except Exception as e:  # noqa: BLE001 — isolate per doc
                self._note_checkpoint_fail(key, e)
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err

    def _note_checkpoint_fail(self, key: str, err: Exception) -> None:
        from ..obs.journal import get_journal

        get_journal().emit("part.checkpoint_fail",
                           cause=f"{type(err).__name__}: {err}",
                           pid=self.pid, doc=key)

    def close(self, graceful: bool = True) -> None:
        """Graceful close checkpoints first (rebalance); a crash close
        (graceful=False) just detaches — recovery is checkpoint+replay.
        A failing checkpoint never strands the remaining docs: every
        orderer still checkpoints (best effort) AND closes, then the
        first checkpoint error re-raises."""
        first_err = None
        for key, o in self.orderers.items():
            if graceful:
                try:
                    o.checkpoint()
                except Exception as e:  # noqa: BLE001 — isolate per doc
                    self._note_checkpoint_fail(key, e)
                    if first_err is None:
                        first_err = e
            o.close()
        self.orderers.clear()
        if first_err is not None:
            raise first_err


class PartitionHost:
    """One process/VM's share of the partition space (KafkaRunner role)."""

    def __init__(self, host_id: str, log, db, pubsub, clock=None):
        self.host_id = host_id
        self._log = log
        self._db = db
        self._pubsub = pubsub
        self._clock = clock
        self.partitions: dict[int, Partition] = {}

    def assign(self, pid: int) -> Partition:
        if pid not in self.partitions:
            self.partitions[pid] = Partition(
                pid, self._log, self._db, self._pubsub, self._clock)
        return self.partitions[pid]

    def release(self, pid: int, graceful: bool = True) -> None:
        part = self.partitions.pop(pid, None)
        if part is not None:
            part.close(graceful)


class PartitionManager:
    """Spreads partitions over hosts; routes and rebalances.

    Ref: partitionManager.ts:22 (ownership), :93-111 (rebalance). The
    assignment is deterministic round-robin over the sorted host list so
    every participant computes the same map.
    """

    def __init__(self, n_partitions: int, log, db, pubsub, clock=None):
        self.n_partitions = n_partitions
        self._log = log
        self._db = db
        self._pubsub = pubsub
        self._clock = clock
        self.hosts: dict[str, PartitionHost] = {}
        self.assignment: dict[int, str] = {}  # pid → host_id

    # ---------------------------------------------------------- membership

    def add_host(self, host_id: str) -> PartitionHost:
        host = PartitionHost(host_id, self._log, self._db, self._pubsub,
                             self._clock)
        self.hosts[host_id] = host
        self._rebalance(graceful=True)
        return host

    def remove_host(self, host_id: str, crashed: bool = False) -> None:
        host = self.hosts.pop(host_id, None)
        if host is not None:
            for pid in list(host.partitions):
                host.release(pid, graceful=not crashed)
        self._rebalance(graceful=not crashed)

    def _rebalance(self, graceful: bool) -> None:
        if not self.hosts:
            self.assignment.clear()
            return
        order = sorted(self.hosts)
        want = {pid: order[pid % len(order)]
                for pid in range(self.n_partitions)}
        for pid, new_host in want.items():
            old_host = self.assignment.get(pid)
            if old_host == new_host:
                continue
            if old_host in self.hosts:
                # the moving partition checkpoints + closes on its old
                # host; the new host resumes lazily from the checkpoint
                self.hosts[old_host].release(pid, graceful)
            self.assignment[pid] = new_host
        self.rebalances = getattr(self, "rebalances", 0) + 1

    # ------------------------------------------------------------- routing

    def host_of(self, tenant_id: str, document_id: str) -> PartitionHost:
        pid = partition_of(tenant_id, document_id, self.n_partitions)
        return self.hosts[self.assignment[pid]]

    def order(self, raw) -> None:
        """Route a raw record to the owning partition's document pipeline
        (the front door's connection.order())."""
        host = self.host_of(raw.tenant_id, raw.document_id)
        pid = partition_of(raw.tenant_id, raw.document_id,
                           self.n_partitions)
        host.assign(pid).orderer(raw.tenant_id, raw.document_id).order(raw)

    def checkpoint_all(self) -> None:
        for host in self.hosts.values():
            for part in host.partitions.values():
                part.checkpoint()
