"""Fleet topology spec: a whole deployment — and its restart — as ONE
declarative object.

Ref: Routerlicious ships as a helm chart — alfred/deli/scribe replica
counts, kafka topics, and redis endpoints live in one values file, and
"restart the cluster" means re-applying that file (SURVEY §5). Our
deployment knowledge had instead spread across four construction paths
that each re-derived it by hand: ``front_end`` main()'s flag soup,
bench harnesses re-assembling argv per core, gateways wired by
positional ports, and in-process tests building ShardHosts directly.
A cold restart therefore had no single artifact to restart FROM — the
operator (or bench) had to replay the original command lines from
memory.

:class:`TopologySpec` is that artifact: partitions, cores (with their
preferred claims and ports), gateway relay tiers, the shard dir, and
the boot-admission budget, JSON round-trippable via ``save``/``load``.
Every construction path now converges on :func:`build_core` — the one
function that turns (spec, core_index) into a serving
``NetworkFrontEnd`` — so ``front_end --topology spec.json
--core-index 2`` and an in-process test fleet are the same code.
:class:`Fleet` drives the whole object: start every core (and gateway)
from the spec, SIGKILL the lot mid-traffic, and restart from the same
spec — the cold-start storm bench (``bench.py net_cold_storm``) and
the cold-start chaos drill are its two callers.

Counters (tier "frontend", locked in fluidlint's registry):

    topology.fleet.starts       fleets started from a spec
    topology.fleet.restarts     fleets RE-started from the same spec
    topology.fleet.kills        whole-fleet kill -9s issued
    topology.fleet.host_kills   single host-group kill -9s (kill_host)
    topology.fleet.host_starts  single host-group respawns (start_host)
    topology.core.spawns        cores constructed via build_core
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import time
from typing import Optional


@dataclasses.dataclass
class CoreSpec:
    """One ordering core: which partitions it prefers to claim and
    where it listens. ``port=0`` lets the OS pick (the Fleet records
    the bound port from the core's LISTENING line / front object)."""

    name: str
    prefer: list = dataclasses.field(default_factory=list)
    port: int = 0
    # multi-host fleets: which host group (TopologySpec.hosts key) runs
    # this core; None = the placement host
    host: Optional[str] = None


@dataclasses.dataclass
class GatewaySpec:
    """One gateway tier node. ``upstream`` chains relay tiers: None
    routes shard-aware against the epoch table (leaf-of-cores tier),
    an int splices through that gateway index (deeper fan-out tiers
    speak the muxed link protocol upward)."""

    name: str
    port: int = 0
    upstream: Optional[int] = None
    # multi-host fleets: which host group runs this gateway
    host: Optional[str] = None


@dataclasses.dataclass
class TopologySpec:
    """The whole fleet as data. See the module docstring."""

    shard_dir: str
    n_partitions: int
    cores: list = dataclasses.field(default_factory=list)
    gateways: list = dataclasses.field(default_factory=list)
    host: str = "127.0.0.1"
    lease_ttl: Optional[float] = None
    admin_secret: Optional[str] = None
    summarize_every: Optional[int] = None
    storage_server: Optional[str] = None  # "host:port" or "port"
    # when set, the Fleet RUNS a storage server over this dir and wires
    # every core to it — summaries must outlive core processes or a
    # cold restart has no snapshot to lazy-boot from
    storage_dir: Optional[str] = None
    # boot-storm admission (service/rehydrate.py): each core's
    # rehydration executor budget; rate <= 0 disarms (unbounded boots)
    boot_rate: float = 200.0
    boot_burst: int = 32
    # self-driving placement: kwargs for enable_rebalancer, or None
    rebalance: Optional[dict] = None
    # live health plane: kwargs for enable_health (canary prober +
    # streaming doctor on every core), or None = unarmed
    health: Optional[dict] = None
    # ---- multi-host fleets ----------------------------------------
    # host groups: {host_id: address}. Empty = classic single-host.
    # Each non-placement group runs in a DISJOINT working dir
    # (``host_dir``) with its own process group — no flock, no file
    # sharing with the placement host; its cores reach the lease/epoch
    # plane only through the table door (``table_server``).
    hosts: dict = dataclasses.field(default_factory=dict)
    # which host group owns the shard dir, the storage tier, and the
    # table door; None defaults to the lexicographically first host id
    placement_host: Optional[str] = None
    # "host:port" of the admin_table_* door — resolved by the Fleet
    # once the storage process binds (the door rides its socket)
    table_server: Optional[str] = None
    # ShardHost claim policy: None/"any" (historical — claim stale
    # leases anywhere) or "prefer" (pinned: multi-host fleets without
    # log replication can't resume a foreign group's log by takeover)
    claim_policy: Optional[str] = None
    # forward-compat (rolling upgrade): top-level keys this build does
    # not know survive load→save round-trips via this bag — a newer
    # spec rewritten by an older core keeps the newer fields
    extras: dict = dataclasses.field(default_factory=dict)

    # ---- JSON round-trip ------------------------------------------

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["cores"] = [dataclasses.asdict(c) if not isinstance(c, dict)
                      else c for c in self.cores]
        d["gateways"] = [dataclasses.asdict(g) if not isinstance(g, dict)
                         else g for g in self.gateways]
        # unknown-key passthrough: flatten the bag back to the top
        # level (known fields win on collision — ours are typed)
        extras = d.pop("extras", None) or {}
        return {**extras, **d}

    @classmethod
    def from_dict(cls, d: dict) -> "TopologySpec":
        known = {f.name for f in dataclasses.fields(cls)} - {"extras"}
        extras = {k: v for k, v in d.items() if k not in known}
        d = {k: v for k, v in d.items() if k in known}
        d["cores"] = [CoreSpec(**c) for c in d.get("cores", [])]
        d["gateways"] = [GatewaySpec(**g) for g in d.get("gateways", [])]
        return cls(**d, extras=extras)

    def save(self, path: str) -> str:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path: str) -> "TopologySpec":
        with open(path, encoding="utf-8") as f:
            return cls.from_dict(json.load(f))

    # ---- derived views --------------------------------------------

    def storage_addr(self) -> Optional[tuple]:
        if not self.storage_server:
            return None
        host, _, port = self.storage_server.rpartition(":")
        return (host or "127.0.0.1", int(port))

    def table_addr(self) -> Optional[tuple]:
        if not self.table_server:
            return None
        host, _, port = self.table_server.rpartition(":")
        return (host or "127.0.0.1", int(port))

    def core_name(self, i: int) -> str:
        return self.cores[i].name or f"core{i}"

    # ---- host groups ----------------------------------------------

    def placement_host_id(self) -> Optional[str]:
        """The host group owning the shard dir / storage / table door
        (None for classic single-host specs)."""
        if self.placement_host:
            return self.placement_host
        return min(self.hosts) if self.hosts else None

    def host_addr(self, hid: Optional[str]) -> str:
        """A host group's network address (``spec.host`` for None or
        unknown ids — the single-host fallback)."""
        if hid is None:
            return self.host
        return self.hosts.get(hid, self.host)

    def host_is_remote(self, hid: Optional[str]) -> bool:
        return bool(self.hosts) and hid is not None \
            and hid != self.placement_host_id()

    def host_dir(self, hid: Optional[str]) -> str:
        """The group's working dir: the shard dir for the placement
        host, a DISJOINT sibling for every other group — remote cores
        never open (or flock) a placement-host path; simulated machines
        on one box share nothing but sockets."""
        if not self.host_is_remote(hid):
            return self.shard_dir
        return f"{self.shard_dir.rstrip(os.sep)}-host-{hid}"

    def host_spec_path(self, hid: Optional[str]) -> str:
        return os.path.join(self.host_dir(hid), "topology.json")

    def core_host(self, i: int) -> Optional[str]:
        """Which host group core ``i`` runs in (None single-host)."""
        if not self.hosts:
            return None
        return self.cores[i].host or self.placement_host_id()

    def core_is_remote(self, i: int) -> bool:
        return self.host_is_remote(self.core_host(i))

    def core_dir(self, i: int) -> str:
        return self.host_dir(self.core_host(i))

    def core_host_addr(self, i: int) -> str:
        return self.host_addr(self.core_host(i))

    def gateway_host(self, i: int) -> Optional[str]:
        if not self.hosts:
            return None
        return self.gateways[i].host or self.placement_host_id()

    def spec_path(self) -> str:
        """Canonical on-disk home: the spec lives beside the state it
        describes, so a restart needs only the shard dir."""
        return os.path.join(self.shard_dir, "topology.json")

    def core_argv(self, i: int, spec_path: str,
                  python: str = sys.executable) -> list:
        return [python, "-m", "fluidframework_tpu.service.front_end",
                "--topology", spec_path, "--core-index", str(i)]

    def gateway_argv(self, i: int, core_ports: dict,
                     gateway_ports: dict,
                     python: str = sys.executable) -> list:
        g = self.gateways[i]
        ghid = self.gateway_host(i)
        argv = [python, "-m", "fluidframework_tpu.service.gateway",
                "--host", self.host_addr(ghid), "--port", str(g.port)]
        if g.upstream is not None:
            up = gateway_ports[g.upstream]
            up_addr = self.host_addr(self.gateway_host(g.upstream))
            argv += ["--upstream-gateway", f"{up_addr}:{up}"]
        elif self.host_is_remote(ghid):
            # remote host group: route from the table door over the
            # wire — this gateway has no placement dir to read
            if not self.table_server:
                raise RuntimeError(
                    f"gateway {g.name} is in remote host group "
                    f"{ghid!r} but the spec has no table_server")
            argv += ["--table-server", self.table_server,
                     "--shards", str(self.n_partitions)]
        else:
            argv += ["--shard-dir", self.shard_dir,
                     "--shards", str(self.n_partitions)]
        if ghid is not None and g.upstream is None:
            argv += ["--host-id", ghid]
        return argv


def build_core(spec: TopologySpec, core_index: int, *,
               port: Optional[int] = None, arm_journal: bool = True):
    """THE core construction path: (spec, index) → an un-started
    ``NetworkFrontEnd``. ``front_end --topology`` (subprocess mode)
    and :class:`Fleet` in-process mode both land here, so a restarted
    fleet is byte-for-byte the construction the first start ran.

    ``arm_journal=False`` skips arming the process-singleton audit
    journal — required in-process, where many cores share one process
    and tests inject private Journal instances instead.
    """
    from ..obs import get_journal
    from .front_end import NetworkFrontEnd, ShardHost
    from .rehydrate import boot_counters

    core = spec.cores[core_index]
    core_dir = spec.core_dir(core_index)
    table_client = None
    if spec.core_is_remote(core_index):
        # remote host group: the lease/epoch plane is the placement
        # host's table door, reached over the wire — this process
        # neither sees nor flocks any placement-host file
        from .placement import DEFAULT_TTL_S
        from .table_client import RemoteTableClient

        taddr = spec.table_addr()
        if taddr is None:
            raise RuntimeError(
                f"core {spec.core_name(core_index)} is in remote host "
                f"group {spec.core_host(core_index)!r} but the spec "
                "has no table_server (start the fleet's storage "
                "process with --table-dir first)")
        table_client = RemoteTableClient(
            f"{taddr[0]}:{taddr[1]}", spec.n_partitions,
            ttl_s=(spec.lease_ttl if spec.lease_ttl is not None
                   else DEFAULT_TTL_S))
    host = ShardHost(core_dir, spec.n_partitions,
                     prefer=core.prefer,
                     storage_server=spec.storage_addr(),
                     ttl_s=spec.lease_ttl,
                     table_client=table_client,
                     host_id=spec.core_host(core_index),
                     claim_policy=spec.claim_policy)
    if arm_journal:
        from ..obs import arm_journal as _arm

        # journal file named by the core's STABLE role so a restarted
        # core continues its id space; anonymous cores fall back to
        # their (fresh) owner id — unique but not restart-stable
        name = spec.cores[core_index].name or host.owner_id
        table = host.table
        jr = _arm(os.path.join(core_dir, "journal",
                               f"{name}.jsonl"),
                  core=name,
                  epoch_fn=lambda: table.read().get("epoch", 0))
    else:
        jr = get_journal()
    jr.emit("core.recover" if jr.seq else "core.start",
            owner=host.owner_id, shards=spec.n_partitions,
            prefer=list(core.prefer))
    front = NetworkFrontEnd(
        host=spec.core_host_addr(core_index),
        port=core.port if port is None else port,
        shard_host=host, admin_secret=spec.admin_secret)
    if spec.summarize_every is not None:
        front.enable_summarizer(spec.summarize_every)
    if spec.rebalance is not None:
        front.enable_rebalancer(**spec.rebalance)
    if spec.health is not None:
        front.enable_health(**spec.health)
    if spec.boot_rate and spec.boot_rate > 0:
        front.enable_boot_admission(spec.boot_rate, spec.boot_burst)
    boot_counters().inc("topology.core.spawns")
    return front


def default_spec(shard_dir: str, n_cores: int, n_partitions: int,
                 **kw) -> TopologySpec:
    """The common shape: partitions dealt round-robin across cores,
    OS-assigned ports, no gateways, a fleet-run storage tier under the
    shard dir (durable summaries are what make a cold boot
    O(snapshot+tail) instead of O(log))."""
    cores = [CoreSpec(name=f"core{i}",
                      prefer=[k for k in range(n_partitions)
                              if k % n_cores == i])
             for i in range(n_cores)]
    kw.setdefault("storage_dir", os.path.join(shard_dir, "storage"))
    return TopologySpec(shard_dir=shard_dir, n_partitions=n_partitions,
                        cores=cores, **kw)


def multihost_spec(shard_dir: str, n_hosts: int, cores_per_host: int,
                   n_partitions: int, gateway_per_host: bool = True,
                   **kw) -> TopologySpec:
    """The common multi-host shape: ``n_hosts`` simulated host groups
    (``h0`` is the placement host — shard dir, storage tier, table
    door), ``cores_per_host`` cores each with partitions dealt
    round-robin across ALL cores, one shard-aware gateway per host, and
    ``claim_policy="prefer"`` (partitions are pinned — without log
    replication a foreign group's log cannot be resumed by takeover;
    cross-host MIGRATION ships the log through storage instead)."""
    n_cores = n_hosts * cores_per_host
    cores = []
    for i in range(n_cores):
        cores.append(CoreSpec(
            name=f"core{i}",
            prefer=[k for k in range(n_partitions)
                    if k % n_cores == i],
            host=f"h{i // cores_per_host}"))
    gateways = []
    if gateway_per_host:
        gateways = [GatewaySpec(name=f"gw-h{h}", host=f"h{h}")
                    for h in range(n_hosts)]
    kw.setdefault("storage_dir", os.path.join(shard_dir, "storage"))
    kw.setdefault("claim_policy", "prefer")
    return TopologySpec(
        shard_dir=shard_dir, n_partitions=n_partitions, cores=cores,
        gateways=gateways,
        hosts={f"h{h}": "127.0.0.1" for h in range(n_hosts)},
        placement_host="h0", **kw)


class Fleet:
    """Drive a TopologySpec: start, kill -9, restart — the whole fleet
    as one object.

    Two modes share the spec and the construction path:

    * ``subprocess=True`` — each core is ``front_end --topology
      spec.json --core-index i`` (plus gateway processes); ``kill()``
      is a real SIGKILL. The storm bench and chaos drill mode.
    * in-process (default) — each core is ``build_core(...).
      start_background()`` on its own loop thread; ``kill()`` abandons
      the fronts without checkpoint or close (stop() tears down the
      loop and sockets but never flushes pipeline state — the same
      on-disk picture a SIGKILL leaves). The net_smoke gate and unit
      tests run this mode.

    ``restart()`` = ``kill()`` scars healed only by the recovery path:
    a fresh Fleet state is rebuilt from the SAME spec, so anything the
    spec fails to capture shows up as a restart that comes up wrong.
    """

    def __init__(self, spec: TopologySpec, subprocess: bool = False,
                 env: Optional[dict] = None):
        self.spec = spec
        self.subprocess = subprocess
        self.env = env
        self.procs: dict[int, "subprocess.Popen"] = {}
        self.gw_procs: dict[int, "subprocess.Popen"] = {}
        self.fronts: dict[int, object] = {}  # in-proc NetworkFrontEnds
        self.core_ports: dict[int, int] = {}
        self.gw_ports: dict[int, int] = {}
        self.storage_proc = None   # subprocess mode
        self.storage_runner = None  # in-proc mode
        self._generation = 0
        # multi-host: host group id → that group's live Popens (cores +
        # gateways), the unit kill_host()/start_host() operate on
        self.host_procs: dict = {}

    # ---- lifecycle ------------------------------------------------

    def start(self) -> "Fleet":
        from .rehydrate import boot_counters

        os.makedirs(self.spec.shard_dir, exist_ok=True)
        # epoch floor: claims recorded AFTER this instant bump past it,
        # which is how wait_claimed tells this generation's ownership
        # from a dead generation's leftover rows (same addrs when the
        # spec pins ports)
        from .placement_plane import EpochTable

        self._epoch_floor = EpochTable.for_shard_dir(
            self.spec.shard_dir).read().get("epoch", 0)
        counters = boot_counters()
        if self._generation == 0:
            counters.inc("topology.fleet.starts")
        else:
            counters.inc("topology.fleet.restarts")
        self._generation += 1
        if self.subprocess:
            self._start_subprocess()
        else:
            self._start_inproc()
        return self

    def _start_inproc(self) -> None:
        spec = self.spec
        if spec.storage_dir:
            door = None
            if spec.hosts:
                from .placement import DEFAULT_TTL_S
                from .table_client import TableDoorService

                door = TableDoorService(
                    spec.shard_dir, spec.n_partitions,
                    ttl_s=(spec.lease_ttl if spec.lease_ttl is not None
                           else DEFAULT_TTL_S))
            placement_addr = spec.host_addr(spec.placement_host_id())
            self.storage_runner = _StorageRunner(
                spec.storage_dir, placement_addr, table_door=door)
            port = self.storage_runner.start()
            spec.storage_server = f"{placement_addr}:{port}"
            if door is not None:
                spec.table_server = spec.storage_server
        for hid in spec.hosts:
            os.makedirs(spec.host_dir(hid), exist_ok=True)
        for i in range(len(spec.cores)):
            front = build_core(spec, i, arm_journal=False)
            front.start_background()
            self.fronts[i] = front
            self.core_ports[i] = front.port
        # in-process mode serves cores directly; gateway tiers are a
        # subprocess-mode concern (their loops want own processes)

    def _start_subprocess(self) -> None:
        env = dict(os.environ)
        if self.env:
            env.update(self.env)
        self._env_cache = env
        spec = self.spec
        if spec.storage_dir:
            placement_addr = spec.host_addr(spec.placement_host_id())
            argv = [sys.executable, "-m",
                    "fluidframework_tpu.service.storage_server",
                    "--dir", spec.storage_dir,
                    "--host", placement_addr]
            if spec.hosts:
                # the table door rides the storage socket: one extra
                # frame family, zero extra processes
                argv += ["--table-dir", spec.shard_dir,
                         "--shards", str(spec.n_partitions)]
                if spec.lease_ttl is not None:
                    argv += ["--lease-ttl", str(spec.lease_ttl)]
            self.storage_proc = subprocess.Popen(
                argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env)
            port = _read_listening(self.storage_proc, "storage")
            spec.storage_server = f"{placement_addr}:{port}"
            if spec.hosts:
                spec.table_server = spec.storage_server
        # saved AFTER the storage tier binds: the spec file each core
        # loads carries the resolved storage + table-door addresses
        spec.save(spec.spec_path())
        # each remote host group gets a COPY of the spec in its own
        # disjoint dir — its processes never read a placement-host path
        for hid in spec.hosts:
            if spec.host_is_remote(hid):
                os.makedirs(spec.host_dir(hid), exist_ok=True)
                spec.save(spec.host_spec_path(hid))
        for i in range(len(spec.cores)):
            self._spawn_core(i, env)
        for i, p in self.procs.items():
            self.core_ports[i] = _read_listening(p, spec.core_name(i))
        # gateways after cores: a shard-aware gateway routes from the
        # epoch table the cores have begun writing; relay tiers after
        # their upstream so the splice target exists
        order = [i for i, g in enumerate(spec.gateways)
                 if g.upstream is None]
        order += [i for i, g in enumerate(spec.gateways)
                  if g.upstream is not None]
        for i in order:
            self._spawn_gateway(i, env)
            self.gw_ports[i] = _read_listening(
                self.gw_procs[i], spec.gateways[i].name)

    def _spawn_core(self, i: int, env: dict) -> None:
        spec = self.spec
        hid = spec.core_host(i)
        spec_path = (spec.host_spec_path(hid) if spec.host_is_remote(hid)
                     else spec.spec_path())
        p = subprocess.Popen(
            spec.core_argv(i, spec_path), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=env,
            start_new_session=bool(spec.hosts))
        self.procs[i] = p
        if hid is not None:
            self.host_procs.setdefault(hid, []).append(p)

    def _spawn_gateway(self, i: int, env: dict) -> None:
        spec = self.spec
        p = subprocess.Popen(
            spec.gateway_argv(i, self.core_ports, self.gw_ports),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, start_new_session=bool(spec.hosts))
        self.gw_procs[i] = p
        hid = spec.gateway_host(i)
        if hid is not None:
            self.host_procs.setdefault(hid, []).append(p)

    def kill(self) -> "Fleet":
        """kill -9 the whole fleet: no checkpoint, no close, no
        goodbye — the cold-start bench's opening move."""
        from .rehydrate import boot_counters

        boot_counters().inc("topology.fleet.kills")
        victims = (list(self.gw_procs.values())
                   + list(self.procs.values()))
        if self.storage_proc is not None:
            victims.append(self.storage_proc)
        for p in victims:
            try:
                p.send_signal(signal.SIGKILL)
            except OSError:
                pass
        for p in victims:
            try:
                p.wait(timeout=10)
            except Exception:
                pass
        for front in self.fronts.values():
            # abandon: stop() kills the loop + sockets but flushes
            # NOTHING (no checkpoint_all, no orderer close) — the
            # on-disk state is what the last 2 s ticker left, exactly
            # a SIGKILL's aftermath
            front.stop()
        if self.storage_runner is not None:
            self.storage_runner.stop()
        self.procs.clear()
        self.gw_procs.clear()
        self.fronts.clear()
        self.core_ports.clear()
        self.gw_ports.clear()
        self.host_procs.clear()
        self.storage_proc = None
        self.storage_runner = None
        return self

    def kill_host(self, hid: str) -> "Fleet":
        """kill -9 ONE host group (its separate process group simulates
        a machine dying): every core and gateway of ``hid``, nothing
        else. The placement host's storage/table door stays up unless
        ``hid`` IS the placement host."""
        from .rehydrate import boot_counters

        boot_counters().inc("topology.fleet.host_kills")
        victims = list(self.host_procs.pop(hid, []))
        if (hid == self.spec.placement_host_id()
                and self.storage_proc is not None):
            victims.append(self.storage_proc)
            self.storage_proc = None
        for p in victims:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except (OSError, ProcessLookupError):
                try:
                    p.send_signal(signal.SIGKILL)
                except OSError:
                    pass
        for p in victims:
            try:
                p.wait(timeout=10)
            except Exception:
                pass
        dead = set(victims)
        for i in [i for i, p in self.procs.items() if p in dead]:
            self.procs.pop(i)
            self.core_ports.pop(i, None)
        for i in [i for i, p in self.gw_procs.items() if p in dead]:
            self.gw_procs.pop(i)
            self.gw_ports.pop(i, None)
        return self

    def start_host(self, hid: str) -> "Fleet":
        """Respawn ONE host group from its spec copy — the recovery
        half of :meth:`kill_host` (subprocess mode only)."""
        from .rehydrate import boot_counters

        boot_counters().inc("topology.fleet.host_starts")
        env = getattr(self, "_env_cache", None)
        if env is None:
            env = dict(os.environ)
            if self.env:
                env.update(self.env)
        spec = self.spec
        mine = [i for i in range(len(spec.cores))
                if spec.core_host(i) == hid]
        for i in mine:
            self._spawn_core(i, env)
        for i in mine:
            self.core_ports[i] = _read_listening(self.procs[i],
                                                 spec.core_name(i))
        gws = [i for i, g in enumerate(spec.gateways)
               if spec.gateway_host(i) == hid]
        for i in sorted(gws, key=lambda i:
                        spec.gateways[i].upstream is not None):
            self._spawn_gateway(i, env)
            self.gw_ports[i] = _read_listening(
                self.gw_procs[i], spec.gateways[i].name)
        return self

    def restart(self) -> "Fleet":
        """Restart from the spec — the artifact IS the runbook."""
        if self.procs or self.fronts:
            self.kill()
        return self.start()

    def checkpoint_all(self) -> None:
        """Checkpoint + flush every in-proc core. The 2s checkpoint
        ticker lives in serve_forever (subprocess cores get it for
        free); an in-proc fleet must ask explicitly before a kill is
        expected to be recoverable from the checkpoint."""
        for front in self.fronts.values():
            for server in front._all_servers():
                server.checkpoint_all()
            front._flush_logs()

    def stop(self) -> None:
        """Graceful-ish teardown for harness cleanup (not part of the
        crash story): terminate subprocesses, stop in-proc loops."""
        victims = (list(self.gw_procs.values())
                   + list(self.procs.values()))
        if self.storage_proc is not None:
            victims.append(self.storage_proc)
        for p in victims:
            try:
                p.terminate()
            except OSError:
                pass
        for p in victims:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()
        for front in self.fronts.values():
            front.stop()
        if self.storage_runner is not None:
            self.storage_runner.stop()
        self.procs.clear()
        self.gw_procs.clear()
        self.fronts.clear()
        self.storage_proc = None
        self.storage_runner = None

    # ---- addressing -----------------------------------------------

    def core_addr(self, i: int) -> tuple:
        return (self.spec.core_host_addr(i), self.core_ports[i])

    def client_addr(self) -> tuple:
        """Where clients dial: the deepest gateway tier if one exists,
        else the first core."""
        if self.gw_ports:
            leaf = max(self.gw_ports)
            return (self.spec.host_addr(self.spec.gateway_host(leaf)),
                    self.gw_ports[leaf])
        return self.core_addr(0)

    def gateway_addr(self, i: int) -> tuple:
        return (self.spec.host_addr(self.spec.gateway_host(i)),
                self.gw_ports[i])

    def wait_claimed(self, timeout: float = 30.0,
                     parts: Optional[set] = None) -> None:
        """Block until every partition (or just ``parts``) is routed to
        one of THIS generation's cores in the epoch table — 'the fleet
        is up'. (After a restart the table still carries the dead
        generation's rows, so mere presence of an owner proves
        nothing.)"""
        from .placement_plane import EpochTable

        table = EpochTable.for_shard_dir(self.spec.shard_dir)
        want = {f"{self.spec.core_host_addr(i)}:{p}"
                for i, p in self.core_ports.items()}
        floor = getattr(self, "_epoch_floor", 0)
        need = (set(range(self.spec.n_partitions)) if parts is None
                else {int(k) for k in parts})
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            rows = table.read().get("parts", {})
            have = {int(k): p for k, p in rows.items() if int(k) in need}
            if (len(have) == len(need)
                    and all(p.get("addr") in want
                            and p.get("epoch", 0) > floor
                            for p in have.values())):
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"fleet: partitions unclaimed after {timeout}s")

    def wait_healthy(self, host_id: Optional[str] = None,
                     timeout: float = 60.0) -> dict:
        """Block until the probe-backed live health plane answers
        ``ok`` — the rolling-upgrade go/no-go gate (requires
        ``spec.health``; an unarmed fleet answers ``unknown`` forever).

        With ``host_id`` only that host group's cores must be healthy
        (the host just respawned; the rest of the fleet is a later
        upgrade step); without it every core must be. Generation-gated
        like :meth:`wait_claimed` (the epoch floor first — a dead
        generation's core can still answer on a recycled port), and
        PROBE-backed: a core counts healthy only once a canary has
        walked its doors successfully this generation, not merely once
        its engine boots with nothing evaluated yet.

        Returns {core_name: health dict}; raises TimeoutError with the
        failing verdicts otherwise."""
        from .placement_plane import admin_rpc

        deadline = time.monotonic() + timeout
        if host_id is None:
            targets = sorted(self.core_ports)
            parts = None
        else:
            targets = [i for i in sorted(self.core_ports)
                       if self.spec.core_host(i) == host_id]
            parts = {k for i in targets
                     for k in self.spec.cores[i].prefer} or None
        self.wait_claimed(
            timeout=max(0.1, deadline - time.monotonic()), parts=parts)
        last: dict = {}
        while time.monotonic() < deadline:
            verdicts = {}
            ok = True
            for i in targets:
                frame = {"t": "admin_health"}
                if self.spec.admin_secret:
                    frame["secret"] = self.spec.admin_secret
                try:
                    reply = admin_rpc(*self.core_addr(i), frame,
                                      timeout=5.0)
                    h = reply.get("health") or {}
                except (OSError, ValueError, RuntimeError) as e:
                    h = {"verdict": "unreachable", "error": str(e)}
                verdicts[self.spec.core_name(i)] = h
                doors = ((h.get("probes") or {}).get("doors") or {})
                probed = any(d.get("probes", 0) and d.get("ok")
                             for d in doors.values())
                if h.get("verdict") != "ok" or not probed:
                    ok = False
            last = verdicts
            if ok:
                return verdicts
            time.sleep(0.2)
        summary = {name: h.get("verdict") for name, h in last.items()}
        raise TimeoutError(
            f"fleet: not healthy after {timeout}s: {summary}")


class _StorageRunner:
    """In-process storage tier: StorageServer on its own loop thread
    (it has no background mode of its own — subprocess deployments run
    it as a process)."""

    def __init__(self, directory: str, host: str, table_door=None):
        from .storage_server import StorageServer

        self.srv = StorageServer(directory, host=host, port=0,
                                 table_door=table_door)
        self.loop = None
        self.thread = None

    def start(self) -> int:
        import asyncio
        import threading

        ready = threading.Event()

        def run():
            loop = asyncio.new_event_loop()
            self.loop = loop
            asyncio.set_event_loop(loop)

            async def bind():
                s = await asyncio.start_server(
                    self.srv._handle_conn, self.srv.host, self.srv.port,
                    backlog=256)
                self.srv.port = s.sockets[0].getsockname()[1]

            loop.run_until_complete(bind())
            ready.set()
            loop.run_forever()

        self.thread = threading.Thread(target=run, daemon=True,
                                       name="fluid-storage")
        self.thread.start()
        ready.wait(timeout=10)
        return self.srv.port

    def stop(self) -> None:
        if self.loop is not None:
            self.loop.call_soon_threadsafe(self.loop.stop)
            if self.thread is not None:
                self.thread.join(timeout=5)
            self.loop = None


def _read_listening(proc, name: str, timeout: float = 60.0) -> int:
    """Parse the LISTENING readiness line a core/gateway prints; fail
    loudly with the process's output if it died instead."""
    deadline = time.monotonic() + timeout
    lines = []
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                break
            continue
        lines.append(line.rstrip())
        if line.startswith("LISTENING"):
            return int(line.rsplit(":", 1)[1])
    tail = "\n".join(lines[-20:])
    raise RuntimeError(f"{name} never reported LISTENING "
                       f"(rc={proc.poll()}):\n{tail}")
