"""Broadcaster: fan sequenced ops out to every connected front end.

Ref: lambdas/src/broadcaster/lambda.ts:29-80 — batches sequenced ops per
"tenant/doc" topic and publishes to all front-end instances (Redis pub/sub
in production; in-proc PubSub here, memory-orderer/src/pubsub.ts:39).
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Callable

from ..protocol.messages import SequencedDocumentMessage, TraceHop
from ..utils.telemetry import HOP_FANOUT, HOP_SERVICE_ACTION
from .core import QueuedMessage


class PubSub:
    """Topic → subscriber callbacks (ref: memory-orderer pubsub.ts)."""

    def __init__(self):
        self._subs: dict[str, list[Callable]] = defaultdict(list)

    def subscribe(self, topic: str, cb: Callable) -> None:
        self._subs[topic].append(cb)

    def unsubscribe(self, topic: str, cb: Callable) -> None:
        if cb in self._subs.get(topic, []):
            self._subs[topic].remove(cb)

    def publish(self, topic: str, *args) -> None:
        for cb in list(self._subs.get(topic, [])):
            cb(*args)


class BroadcasterLambda:
    """Relays sequenced messages to the doc's pub/sub topic in batches.

    The op-topic contract is ``callback(list[SequencedDocumentMessage])``
    — the reference broadcaster likewise accumulates per-doc batches
    before publishing (lambda.ts:29-80), which is what keeps fan-out cost
    per-batch instead of per-op at high throughput.
    """

    #: chaos seam (fluidframework_tpu/chaos): dropped / repeated
    #: broadcast faults. Class-level because orderers construct their
    #: broadcaster lazily; None = disarmed, one branch per batch.
    fault_plane = None

    def __init__(self, pubsub: PubSub):
        self._pubsub = pubsub

    @staticmethod
    def topic(tenant_id: str, document_id: str) -> str:
        return f"{tenant_id}/{document_id}"

    def handler(self, message: QueuedMessage) -> None:
        envelope = message.value  # {..., "message"|"boxcar"|"abatch"}
        batch = envelope.get("abatch")  # array lane: published AS-IS —
        # array-aware subscribers consume it raw, legacy ones receive
        # its lazily-materialized messages (local_server._deliver_ops)
        if batch is None:
            batch = envelope.get("boxcar")
        if batch is None:
            batch = [envelope["message"]]
        self._stamp_fanout(batch)
        topic = self.topic(envelope["tenant_id"], envelope["document_id"])
        if self.fault_plane is not None:
            directive = self.fault_plane("broadcast.publish", topic=topic)
            if directive == "drop":
                # a lost pub/sub delivery: clients recover through the
                # delta-storage gap repair when the next op arrives (or
                # the settle-phase catch-up)
                return
            if directive == "dup":
                # a repeated delivery (pub/sub redelivers after a
                # timeout): clients dedupe by sequence number
                self._pubsub.publish(topic, batch)
        self._pubsub.publish(topic, batch)

    @staticmethod
    def _stamp_fanout(batch) -> None:
        """Stamp broadcast/fanout on SAMPLED traffic only.

        Array batches carry the accumulated hoptail on the boxcar
        (appended in place — the egress encode packs it); rec batches
        carry per-message TraceHop lists, stamped only where a hop
        list already exists (the client's sampling decision rides the
        presence of traces). Unsampled traffic takes one branch here.
        """
        hops = getattr(getattr(batch, "boxcar", None), "hops", None)
        if hops is not None:
            hops.append((HOP_FANOUT, time.time()))
            return
        if isinstance(batch, list):
            svc, act = HOP_SERVICE_ACTION[HOP_FANOUT]
            for msg in batch:
                traces = getattr(msg, "traces", None)
                if traces:
                    traces.append(
                        TraceHop(service=svc, action=act,
                                 timestamp=time.time()))
