"""Legacy JSON durable-record codec — the storage tier's compat shim.

Every record shape the durable log ever persisted before the columnar
segment store (PR 6) decodes through here: tag-wrapped JSON structures
(``_wrap``/``_unwrap``) and the 0xFF binary kinds whose header is a JSON
list. New code paths append columnar segment blocks (protocol/binwire
``encode_seg_block``) and never call this module; the hot storage modules
(``durable_log``, ``segment_store``, ``native/oplog``) are fluidlint-banned
from ``json.dumps``/``json.loads`` — this shim is the ONE exempted home,
and callers count every trip through it under the
``storage.log.legacy_json`` deprecation counter.
"""

from __future__ import annotations

import json
from typing import Any

from ..protocol.serialization import message_from_dict, message_to_dict

_TAG_MSG = "_msg"  # a wrapped protocol message
_TAG_ESC = "_esc"  # an escaped user dict that contained a tag key


def _wrap(value: Any) -> Any:
    """Recursively tag protocol messages / escape colliding user dicts."""
    if isinstance(value, dict):
        out = {k: _wrap(v) for k, v in value.items()}
        if _TAG_MSG in out or _TAG_ESC in out:
            return {_TAG_ESC: out}
        return out
    if isinstance(value, (list, tuple)):
        return [_wrap(v) for v in value]
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return {_TAG_MSG: message_to_dict(value)}


def _unwrap(value: Any) -> Any:
    if isinstance(value, dict):
        if _TAG_MSG in value and len(value) == 1:
            return message_from_dict(value[_TAG_MSG])
        if _TAG_ESC in value and len(value) == 1:
            return {k: _unwrap(v) for k, v in value[_TAG_ESC].items()}
        return {k: _unwrap(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_unwrap(v) for v in value]
    return value


def encode_json_value(value: Any) -> bytes:
    """The frozen legacy record encoding (tag-wrapped compact JSON)."""
    return json.dumps(_wrap(value), separators=(",", ":")).encode()


def decode_json_value(data: bytes) -> Any:
    return _unwrap(json.loads(data.decode()))


def abox_header_bytes(box) -> bytes:
    """JSON header of the legacy 0xFF boxcar record kinds (1/2)."""
    return json.dumps(
        [box.tenant_id, box.document_id, box.client_id, box.ds_id,
         box.channel_id, box.timestamp, int(box.n), box.props],
        separators=(",", ":")).encode()


def abox_header_from(data: bytes) -> list:
    return json.loads(data.decode())
