"""Scriptorium: durable sequenced-op store for backfill.

Ref: lambdas/src/scriptorium/lambda.ts:16-48 — inserts each sequenced op
into the per-document ``deltas`` collection, the source for the REST
delta-backfill path new/reconnecting clients use to catch up
(alfred /deltas → DeltaManager.getDeltas, deltaManager.ts:647).
"""

from __future__ import annotations

from ..protocol.messages import SequencedDocumentMessage
from .core import InMemoryDb, QueuedMessage


class LogTruncatedError(RuntimeError):
    """The requested range starts below the retention base: the caller's
    head predates the truncated prefix and it must reload from the
    latest acked summary instead of backfilling op-by-op."""

    def __init__(self, base: int, snapshot_seq=None):
        super().__init__(
            f"op log truncated below seq {base}: reload from the latest "
            "acked summary")
        self.base = base
        # capture seq of the acked summary that heals this hole: retention
        # clamps its trim to this, so it is always ≥ base when set
        self.snapshot_seq = snapshot_seq


class ScriptoriumLambda:
    """Stores each doc's sequenced stream as ONE db document holding the
    seq-ordered list (``log[i]`` is seq ``i+1+base`` — the sequencer
    assigns dense seqs from 1, so list position IS the index, offset by
    the truncation ``base``). Appends are O(batch) and range reads are
    slices; the round-2 per-op keyed upserts were a measurable slice of
    the service hot path.

    Retention: once a summary is ACKED at seq N, ops ≤ N are only needed
    by replicas that already hold them — new boots use the summary + the
    tail. ``truncate_below`` drops the covered prefix (keeping a safety
    margin for in-flight backfills); a client disconnected past the
    retained window must reload from the summary, the same contract as
    the reference's deli ClearCache + summary-based catch-up."""

    def __init__(self, db: InMemoryDb):
        self._db = db

    @staticmethod
    def collection(tenant_id: str, document_id: str) -> str:
        return f"deltas/{tenant_id}/{document_id}"

    def _doc(self, name: str) -> dict:
        col = self._db.collection(name)
        doc = col.get("log")
        if doc is None:
            doc = col["log"] = {"_id": "log", "messages": [], "base": 0}
        return doc

    def _log(self, name: str) -> list:
        return self._doc(name)["messages"]

    def handler(self, message: QueuedMessage) -> None:
        envelope = message.value
        name = self.collection(envelope["tenant_id"], envelope["document_id"])
        doc = self._doc(name)
        log = doc["messages"]
        # dense invariant: log[i] holds seq base+i+1, so the last stored
        # seq is positional (entries may be per-op messages OR a shared
        # SequencedArrayBatch object occupying its n positions)
        last = doc.get("base", 0) + len(log)
        abatch = envelope.get("abatch")
        if abatch is not None:
            first, n = abatch.base_seq, abatch.n
            if not log and last == 0 and first > 1:
                # fork adoption: a forked doc's deltas topic begins at its
                # fork base + 1, not 1 — the topic's first record defines
                # the base (normal docs always open at seq 1), otherwise a
                # durable-log replay would rebuild the tail at positions
                # that violate the dense invariant
                last = doc["base"] = first - 1
            if first == last + 1:  # hot path: ONE list-repeat, no per-op
                log.extend([abatch] * n)
            elif first + n - 1 > last:
                log.extend([abatch] * (first + n - 1 - last))
            return
        batch = envelope.get("boxcar")
        if batch is None:
            batch = [envelope["message"]]
        first = batch[0].sequence_number
        if not log and last == 0 and first > 1:
            # fork adoption (see the abatch branch above)
            last = doc["base"] = first - 1
        if first == last + 1:  # the hot path: append in arrival order
            log.extend(batch)
            return
        # replay overlap (deli crash-replay re-emits ticketed seqs at new
        # offsets): keep only the unseen tail — idempotent by seq
        for msg in batch:
            if msg.sequence_number > last:
                log.append(msg)
                last = msg.sequence_number

    def close(self) -> None:
        pass

    def truncate_below(self, tenant_id: str, document_id: str,
                       seq: int) -> int:
        """Drop retained ops with sequence_number ≤ seq; returns how many
        were dropped. Callers pass (acked summary seq − retention).

        The base RAISES even past the held range (or on an empty store):
        a checkpoint restore declares the prefix gone BEFORE the durable
        deltas-topic replay re-delivers it, and the append path then
        drops everything at or below the declared base."""
        doc = self._doc(self.collection(tenant_id, document_id))
        base = doc.get("base", 0)
        if seq <= base:
            return 0
        drop = min(seq - base, len(doc["messages"]))
        del doc["messages"][:drop]
        doc["base"] = seq
        return drop

    def retained_base(self, tenant_id: str, document_id: str) -> int:
        """Seqs ≤ base are no longer served (summary-covered)."""
        return self._doc(self.collection(tenant_id, document_id)) \
            .get("base", 0)

    def head_seq(self, tenant_id: str, document_id: str) -> int:
        """Highest stored seq (== base on an empty/trimmed store)."""
        doc = self._doc(self.collection(tenant_id, document_id))
        return doc.get("base", 0) + len(doc["messages"])

    def get_deltas(
        self, tenant_id: str, document_id: str, from_seq: int, to_seq: int
    ) -> list[SequencedDocumentMessage]:
        """Ops with from_seq < seq < to_seq (exclusive bounds, matching the
        reference's /deltas REST contract). A request reaching below the
        retention base raises :class:`LogTruncatedError` — silently
        omitting the dropped prefix would stall the caller forever on a
        gap that can never fill."""
        doc = self._doc(self.collection(tenant_id, document_id))
        base = doc.get("base", 0)
        if from_seq < base:
            raise LogTruncatedError(base)
        log = doc["messages"]
        lo = max(from_seq - base, 0)
        hi = min(to_seq - 1 - base, len(log))
        if hi <= lo:
            return []
        out = []
        i = lo
        while i < hi:
            entry = log[i]
            if isinstance(entry, SequencedDocumentMessage):
                out.append(entry)
                i += 1
                continue
            # a SequencedArrayBatch occupies its n seq positions: slice
            # ONE cached messages() list across the whole in-range run
            # instead of materializing position by position
            start = base + i + 1 - entry.base_seq
            stop = min(entry.n, start + (hi - i))
            out.extend(entry.messages()[start:stop])
            i += stop - start
        return out
