"""Scriptorium: durable sequenced-op store for backfill.

Ref: lambdas/src/scriptorium/lambda.ts:16-48 — inserts each sequenced op
into the per-document ``deltas`` collection, the source for the REST
delta-backfill path new/reconnecting clients use to catch up
(alfred /deltas → DeltaManager.getDeltas, deltaManager.ts:647).
"""

from __future__ import annotations

from ..protocol.messages import SequencedDocumentMessage
from .core import InMemoryDb, QueuedMessage


class ScriptoriumLambda:
    def __init__(self, db: InMemoryDb):
        self._db = db

    @staticmethod
    def collection(tenant_id: str, document_id: str) -> str:
        return f"deltas/{tenant_id}/{document_id}"

    def handler(self, message: QueuedMessage) -> None:
        envelope = message.value
        msg: SequencedDocumentMessage = envelope["message"]
        name = self.collection(envelope["tenant_id"], envelope["document_id"])
        # idempotent on replay: keyed by sequence number
        self._db.upsert(name, str(msg.sequence_number), {"message": msg})

    def close(self) -> None:
        pass

    def get_deltas(
        self, tenant_id: str, document_id: str, from_seq: int, to_seq: int
    ) -> list[SequencedDocumentMessage]:
        """Ops with from_seq < seq < to_seq (exclusive bounds, matching the
        reference's /deltas REST contract)."""
        name = self.collection(tenant_id, document_id)
        docs = self._db.find_range(
            name, lambda d: d["message"].sequence_number, from_seq + 1, to_seq
        )
        return [d["message"] for d in docs]
