"""Scriptorium: durable sequenced-op store for backfill.

Ref: lambdas/src/scriptorium/lambda.ts:16-48 — inserts each sequenced op
into the per-document ``deltas`` collection, the source for the REST
delta-backfill path new/reconnecting clients use to catch up
(alfred /deltas → DeltaManager.getDeltas, deltaManager.ts:647).
"""

from __future__ import annotations

from ..protocol.messages import SequencedDocumentMessage
from .core import InMemoryDb, QueuedMessage


class ScriptoriumLambda:
    """Stores each doc's sequenced stream as ONE db document holding the
    seq-ordered list (``log[i]`` is seq ``i+1`` — the sequencer assigns
    dense seqs from 1, so the list IS the index). Appends are O(batch)
    and range reads are slices; the round-2 per-op keyed upserts were a
    measurable slice of the service hot path."""

    def __init__(self, db: InMemoryDb):
        self._db = db

    @staticmethod
    def collection(tenant_id: str, document_id: str) -> str:
        return f"deltas/{tenant_id}/{document_id}"

    def _log(self, name: str) -> list:
        col = self._db.collection(name)
        doc = col.get("log")
        if doc is None:
            doc = col["log"] = {"_id": "log", "messages": []}
        return doc["messages"]

    def handler(self, message: QueuedMessage) -> None:
        envelope = message.value
        name = self.collection(envelope["tenant_id"], envelope["document_id"])
        batch = envelope.get("boxcar")
        if batch is None:
            batch = [envelope["message"]]
        log = self._log(name)
        last = log[-1].sequence_number if log else 0
        first = batch[0].sequence_number
        if first == last + 1:  # the hot path: append in arrival order
            log.extend(batch)
            return
        # replay overlap (deli crash-replay re-emits ticketed seqs at new
        # offsets): keep only the unseen tail — idempotent by seq
        for msg in batch:
            if msg.sequence_number > last:
                log.append(msg)
                last = msg.sequence_number

    def close(self) -> None:
        pass

    def get_deltas(
        self, tenant_id: str, document_id: str, from_seq: int, to_seq: int
    ) -> list[SequencedDocumentMessage]:
        """Ops with from_seq < seq < to_seq (exclusive bounds, matching the
        reference's /deltas REST contract)."""
        log = self._log(self.collection(tenant_id, document_id))
        lo = max(from_seq, 0)
        hi = min(to_seq - 1, len(log))
        return log[lo:hi] if hi > lo else []
