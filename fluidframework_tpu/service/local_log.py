"""Ordered log: topics + subscriber fan-out, with pluggable storage.

Ref: memory-orderer/src/localKafka.ts — an append-only per-partition
message list with monotonically increasing offsets, drained synchronously
into subscribed lambdas. Deterministic drain order (topic registration
order, then offset order) is what makes multi-client interleaving tests
reproducible (the OpProcessingController property, SURVEY §4).

``OrderedLogBase`` owns the subtle parts once — subscriber positions,
fixed-point drain, single-step delivery — over three storage primitives:
``_store`` / ``_load`` / ``_stored_length``. ``LocalLog`` keeps records
in memory; ``service.durable_log.DurableLog`` persists them through the
native C++ op log (the librdkafka-role component, SURVEY §2.9).
"""

from __future__ import annotations

from typing import Any, Callable

from .core import QueuedMessage

Handler = Callable[[QueuedMessage], None]


class OrderedLogBase:
    #: chaos seam (duck-typed; see fluidframework_tpu/chaos): when armed,
    #: append() consults it for torn-write / duplicate-delivery /
    #: replay-from-older-offset faults. None = disarmed, one branch.
    fault_plane = None

    def __init__(self):
        self._subs: dict[str, list[tuple[Handler, list[int]]]] = {}
        self._order: list[str] = []
        # topics that MAY have undelivered records (ordered set): drain is
        # O(pending work), not O(topics) — at thousands of docs the
        # scan-everything loop was the service hot spot
        self._dirty: dict[str, None] = {}

    # ------------------------------------------------- storage primitives

    def _store(self, topic: str, value: Any) -> int:
        """Append; returns the record's offset."""
        raise NotImplementedError

    def _load(self, topic: str, offset: int) -> Any:
        raise NotImplementedError

    def _stored_length(self, topic: str) -> int:
        raise NotImplementedError

    def _torn_append(self, topic: str, value: Any) -> int:
        """Chaos-plane torn-write semantics: the write never reached the
        medium (power cut mid append) — the producer believes it wrote,
        consumers never see it; recovery is the client resubmit path.
        Storage backends with a physical torn-tail representation
        (DurableLog's segment streams) override this to actually leave
        ragged bytes on disk and exercise the recovery scan."""
        return self._stored_length(topic)

    # ----------------------------------------------------------- topic api

    def create_topic(self, topic: str) -> None:
        if topic not in self._subs:
            self._subs[topic] = []
            self._order.append(topic)

    def append(self, topic: str, value: Any, partition: int = 0) -> int:
        self.create_topic(topic)
        if self.fault_plane is not None:
            directive = self.fault_plane("log.append", topic=topic,
                                         record=value)
            if directive == "torn":
                self._dirty[topic] = None
                return self._torn_append(topic, value)
            if directive == "dup":
                # the record lands twice (producer retry after a lost
                # ack) — consumers must dedupe (deli by clientSeq,
                # scriptorium by idempotent upsert, clients by seq)
                self._store(topic, value)
            elif directive == "rewind":
                # replay-from-older-offset: store normally, then drag
                # every subscriber back one record — redelivery of an
                # already-consumed window
                offset = self._store(topic, value)
                self._dirty[topic] = None
                self.rewind_subscribers(topic, 1)
                return offset
        offset = self._store(topic, value)
        self._dirty[topic] = None
        return offset

    def rewind_subscribers(self, topic: str, n: int = 1) -> None:
        """Move every subscriber position on ``topic`` back ``n``
        records: the next drain redelivers them (the at-least-once
        delivery mode every consumer must already tolerate)."""
        for _, pos in self._subs.get(topic, ()):
            pos[0] = max(0, pos[0] - n)
        if self._subs.get(topic):
            self._dirty[topic] = None

    def subscribe(self, topic: str, handler: Handler, from_offset: int = 0) -> None:
        self.create_topic(topic)
        self._subs[topic].append((handler, [from_offset]))
        self._dirty[topic] = None  # may need catch-up delivery

    def unsubscribe(self, topic: str, handler: Handler) -> None:
        subs = self._subs.get(topic, [])
        self._subs[topic] = [(h, p) for h, p in subs if h is not handler]

    def length(self, topic: str) -> int:
        return self._stored_length(topic)

    def first_offset_covering(self, topic: str, seq: int) -> int:
        """Lowest record offset that may hold sequence numbers ≥ ``seq``
        — where a lazy cold boot tails in. Storage without a seq index
        returns 0: the subscribers' own idempotent skip absorbs the
        prefix (correct, just not lazy)."""
        return 0

    def read(self, topic: str, offset: int) -> Any:
        return self._load(topic, offset)

    # ------------------------------------------------------------ delivery

    def drain(self) -> int:
        """Deliver pending messages to all subscribers until quiescent.

        Handlers may append more messages (deli → deltas topic); the loop
        runs to a fixed point. Returns the number of deliveries made.
        """
        delivered = 0
        while self._dirty:
            topic = next(iter(self._dirty))
            del self._dirty[topic]
            # handlers may subscribe/unsubscribe and append (re-dirtying
            # this or other topics); the outer loop reaches the fixed point
            try:
                for handler, pos in list(self._subs.get(topic, [])):
                    # snapshot the length once per handler pass: for the
                    # durable log it is a ctypes call, and re-querying
                    # per record made it ~4 calls/record on the hot
                    # path. Records a handler appends to THIS topic
                    # re-dirty it, so the fixed-point loop still
                    # delivers them.
                    n = self._stored_length(topic)
                    while pos[0] < n:
                        msg = QueuedMessage(
                            offset=pos[0], topic=topic, partition=0,
                            value=self._load(topic, pos[0]))
                        pos[0] += 1
                        handler(msg)
                        delivered += 1
            except Exception:
                # a raising handler must not strand the topic's remaining
                # records: re-dirty so the next drain() retries
                self._dirty[topic] = None
                raise
        return delivered

    def step(self, topic: str) -> bool:
        """Deliver exactly ONE pending message on ``topic`` to each lagging
        subscriber — the deterministic single-step used by interleaving
        tests. Returns False when the topic is fully drained."""
        n = self._stored_length(topic)
        any_delivered = False
        for handler, pos in self._subs.get(topic, []):
            if pos[0] < n:
                msg = QueuedMessage(offset=pos[0], topic=topic, partition=0,
                                    value=self._load(topic, pos[0]))
                pos[0] += 1
                handler(msg)
                any_delivered = True
        return any_delivered


class LocalLog(OrderedLogBase):
    """In-memory ordered log (the LocalKafka analog)."""

    def __init__(self):
        super().__init__()
        self._topics: dict[str, list[Any]] = {}

    def _store(self, topic: str, value: Any) -> int:
        records = self._topics.setdefault(topic, [])
        records.append(value)
        return len(records) - 1

    def _load(self, topic: str, offset: int) -> Any:
        return self._topics[topic][offset]

    def _stored_length(self, topic: str) -> int:
        return len(self._topics.get(topic, []))
