"""In-memory ordered log: the LocalKafka analog.

Ref: memory-orderer/src/localKafka.ts — an append-only per-partition
message list with monotonically increasing offsets, drained synchronously
into subscribed lambdas. Deterministic drain order (topic registration
order, then offset order) is what makes multi-client interleaving tests
reproducible (the OpProcessingController property, SURVEY §4).

The production analog is the C++ sharded op log (SURVEY §2.9); both sides
present the same (append → offset, subscribe → in-order handler calls)
contract, so every lambda runs unchanged over either.
"""

from __future__ import annotations

from typing import Any, Callable

from .core import QueuedMessage


class LocalLog:
    """Named topics of ordered partitions with subscriber fan-out."""

    def __init__(self):
        self._topics: dict[str, list[QueuedMessage]] = {}
        # subscriber positions: (topic, id) -> next offset to deliver
        self._subs: dict[str, list[tuple[Callable[[QueuedMessage], None], list[int]]]] = {}
        self._order: list[str] = []

    def create_topic(self, topic: str) -> None:
        if topic not in self._topics:
            self._topics[topic] = []
            self._subs[topic] = []
            self._order.append(topic)

    def append(self, topic: str, value: Any, partition: int = 0) -> int:
        self.create_topic(topic)
        log = self._topics[topic]
        offset = len(log)
        log.append(QueuedMessage(offset=offset, topic=topic, partition=partition, value=value))
        return offset

    def subscribe(
        self,
        topic: str,
        handler: Callable[[QueuedMessage], None],
        from_offset: int = 0,
    ) -> None:
        self.create_topic(topic)
        self._subs[topic].append((handler, [from_offset]))

    def unsubscribe(self, topic: str, handler: Callable[[QueuedMessage], None]) -> None:
        subs = self._subs.get(topic, [])
        self._subs[topic] = [(h, p) for h, p in subs if h is not handler]

    def drain(self) -> int:
        """Deliver pending messages to all subscribers until quiescent.

        Handlers may append more messages (deli → deltas topic); the loop
        runs to a fixed point. Returns the number of deliveries made.
        """
        delivered = 0
        progressed = True
        while progressed:
            progressed = False
            for topic in self._order:
                log = self._topics[topic]
                for handler, pos in self._subs[topic]:
                    while pos[0] < len(log):
                        msg = log[pos[0]]
                        pos[0] += 1
                        handler(msg)
                        delivered += 1
                        progressed = True
        return delivered

    def step(self, topic: str) -> bool:
        """Deliver exactly ONE pending message on ``topic`` to each lagging
        subscriber — the deterministic single-step used by interleaving
        tests. Returns False when the topic is fully drained."""
        log = self._topics.get(topic, [])
        any_delivered = False
        for handler, pos in self._subs.get(topic, []):
            if pos[0] < len(log):
                msg = log[pos[0]]
                pos[0] += 1
                handler(msg)
                any_delivered = True
        return any_delivered

    def length(self, topic: str) -> int:
        return len(self._topics.get(topic, []))
