"""Per-tenant admission control: token buckets + SLO-burn shedding.

Ref: Routerlicious gets overload protection for free from Kafka
backpressure plus Alfred's per-tenant throttler
(server/routerlicious/packages/lambdas — throttling middleware); our
socket tier has no broker between the front door and deli, so the
admission decision lives here, right where boxcars enter the event
loop (service/front_end.py calls :meth:`AdmissionController.check`
once per submit boxcar, never per op).

Two independent signals gate a boxcar:

1. **Token bucket** (per tenant, from ``TenantManager.set_rate``;
   tenants without a configured rate are unlimited). A depleted bucket
   alone does NOT shed — while the SLOs are healthy the boxcar is
   admitted anyway and only ``net.admission.delayed`` counts it
   (accounting, not refusal), so a modest burst above budget costs
   nothing when the service has headroom.
2. **SLO burn** (``SloEngine.shed_signal``). Only when some SLO is
   ``violated`` do depleted tenants shed: every op of the boxcar is
   nacked through the shared nack door with ``retry_after_ms`` and
   ``net.admission.shed{tenant,reason="rate"}`` counts the ops.

Shedding is boxcar-granular and must preserve deli's clientSeq
continuity (deli nacks any cseq gap, deli.py): once a connection has
shed cseq N, every later boxcar whose first cseq is ABOVE the lowest
shed cseq is shed too (``reason="ordering"``) until the client rewinds
— the driver resubmits held ops first, so one round trip restores the
stream. The resume watermark rides the ServerConnection itself
(``_shed_resume``), dying with the connection.

All state mutates on the front end's event-loop thread only.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..obs import get_registry
from ..obs.probe import CANARY_TENANT
from ..utils.affinity import loop_only

#: Bounds for the retry_after_ms hint handed to shed clients.
RETRY_AFTER_MIN_MS = 25
RETRY_AFTER_MAX_MS = 1000


class TokenBucket:
    """Classic token bucket; ``now`` injected for frozen-clock tests."""

    __slots__ = ("rate", "burst", "tokens", "t_last")

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = self.burst
        self.t_last: Optional[float] = None

    def take(self, n: float, now: float) -> float:
        """Refill to ``now`` and try to take ``n`` tokens.

        Returns 0.0 on success, else the seconds until ``n`` tokens
        would be affordable (tokens untouched on failure). A boxcar
        larger than ``burst`` is admitted once the bucket is FULL, with
        the balance going negative (the refill pays the debt) — the
        driver coalesces its whole shed backlog into one resubmit, and
        refusing any boxcar over ``burst`` outright would livelock that
        retry forever."""
        if self.t_last is not None and now > self.t_last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self.t_last) * self.rate)
        self.t_last = now
        if self.tokens >= n or self.tokens >= self.burst:
            self.tokens -= n
            return 0.0
        return (n - self.tokens) / self.rate

    def drain(self) -> None:
        """Empty the bucket (soft-admit accounting: the over-budget
        boxcar was let through, so its cost is still charged)."""
        self.tokens = 0.0


def retry_after_ms(wait_s: float) -> int:
    return max(RETRY_AFTER_MIN_MS,
               min(RETRY_AFTER_MAX_MS, int(wait_s * 1000.0)))


class AdmissionController:
    """The front end's per-tenant admission gate (see module doc)."""

    def __init__(self, rate_for: Callable, registry=None):
        #: tenant -> (ops_per_s, burst) | None; re-read per boxcar so
        #: runtime rate changes take effect without a restart
        self._rate_for = rate_for
        self._reg = registry if registry is not None else get_registry()
        self._buckets: dict[str, TokenBucket] = {}
        #: attached SloEngine (or anything with .shed_signal); None
        #: means token depletion can only ever soft-admit
        self.engine = None
        #: master switch for the control arm of the overload bench
        self.shedding = True

    # ------------------------------------------------------------------ gate

    def _bucket(self, tenant: str) -> Optional[TokenBucket]:
        spec = self._rate_for(tenant)
        if spec is None:
            self._buckets.pop(tenant, None)
            return None
        b = self._buckets.get(tenant)
        if b is None or (b.rate, b.burst) != spec:
            b = TokenBucket(*spec)
            self._buckets[tenant] = b
        return b

    def shed_active(self) -> bool:
        eng = self.engine
        return (self.shedding and eng is not None
                and bool(eng.shed_signal))

    @loop_only("core")
    def check(self, conn, n: int, first_cseq: int,
              now: Optional[float] = None) -> float:
        """Admission verdict for a boxcar of ``n`` ops starting at
        ``first_cseq`` on ``conn``. Returns 0.0 to admit, else the
        retry-after in seconds — the caller sheds the WHOLE boxcar."""
        tenant = conn.tenant_id
        if tenant == CANARY_TENANT:
            # the canary prober (obs/probe.py) is synthetic blackbox
            # traffic: it must measure the door, never consume a
            # tenant's tokens nor be shed by someone else's burn —
            # defense in depth behind the front end's own skip
            return 0.0
        resume = getattr(conn, "_shed_resume", None)
        if resume is not None:
            if first_cseq > resume:
                # ops behind an outstanding shed: admitting them would
                # gap the clientSeq stream at deli, so they shed too —
                # and they ride the SAME backoff as the rate shed that
                # opened the watermark. A come-back-now hint here made
                # the driver fire subset retries mid-nack-wave; each
                # re-shed multiplied the nack traffic until the wire
                # backed up (the noisy-neighbor seed-7 wedge).
                self._reg.inc("net.admission.shed", n, tenant=tenant,
                              reason="ordering")
                return getattr(conn, "_shed_wait_s",
                               RETRY_AFTER_MIN_MS / 1000.0)
            conn._shed_resume = None
        b = self._bucket(tenant)
        if b is None:
            return 0.0
        now = time.monotonic() if now is None else now
        wait = b.take(n, now)
        if wait <= 0.0:
            return 0.0
        if not self.shed_active():
            # over budget but SLOs healthy: admit (headroom exists),
            # charge the bucket, and account the overage
            b.drain()
            self._reg.inc("net.admission.delayed", n, tenant=tenant)
            return 0.0
        conn._shed_resume = (first_cseq if resume is None
                             else min(resume, first_cseq))
        conn._shed_wait_s = wait
        self._reg.inc("net.admission.shed", n, tenant=tenant,
                      reason="rate")
        return wait
