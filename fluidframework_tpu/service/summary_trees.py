"""Summary-tree ⇄ content-addressed store codec, shared by every
storage backend.

Ref: server/routerlicious/packages/services-client/src/gitManager.ts:13 —
the reference stores summaries as git objects (blobs + tree nodes) and
both the in-proc test storage and the historian-backed production
storage share that shape. Here the same upload/materialize walk is one
module used by the in-proc LocalStorage (driver/local.py) and the
standalone storage process (service/storage_server.py).

Stored tree-node format: ``{"t": "tree", "e": {name: {"k", "id"}}}``;
refs are ``{"k": "tree"|"blob", "id": <content id>}``. A
``SummaryHandle`` resolves against the PARENT version's tree and
re-uploads nothing (protocol-definitions summary.ts incremental
contract).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Optional

from ..protocol.summary import (
    SummaryAttachment,
    SummaryBlob,
    SummaryHandle,
    SummaryTree,
)


def upload_summary_obj(blobs, obj, parent_root: Optional[dict],
                       stats: Optional[dict] = None) -> dict:
    """Recursively store a summary object; returns its ``{"k","id"}``
    ref. ``blobs`` needs ``put(bytes) -> id`` and ``get(id) -> bytes``;
    ``stats`` (optional) accumulates blobs_written / trees_written /
    handles_reused."""
    if stats is None:
        stats = {}
    if isinstance(obj, SummaryBlob):
        stats["blobs_written"] = stats.get("blobs_written", 0) + 1
        return {"k": "blob", "id": blobs.put(obj.content)}
    if isinstance(obj, SummaryAttachment):
        return {"k": "blob", "id": obj.id}
    if isinstance(obj, SummaryHandle):
        if parent_root is None:
            raise ValueError(
                f"summary handle {obj.handle!r} with no parent version")
        ref = resolve_handle_path(blobs.get, parent_root, obj.handle)
        stats["handles_reused"] = stats.get("handles_reused", 0) + 1
        return ref
    if isinstance(obj, SummaryTree):
        entries = {
            name: upload_summary_obj(blobs, child, parent_root, stats)
            for name, child in obj.tree.items()
        }
        node = json.dumps({"t": "tree", "e": entries},
                          sort_keys=True).encode()
        stats["trees_written"] = stats.get("trees_written", 0) + 1
        return {"k": "tree", "id": blobs.put(node)}
    raise TypeError(f"not a summary object: {obj!r}")


def resolve_handle_path(get: Callable[[str], bytes], root_ref: dict,
                        path: str) -> dict:
    """Walk stored tree nodes to the subtree ref a handle names. Parent
    trees were themselves uploaded with handles resolved, so the walk
    always lands on a concrete content id."""
    ref = root_ref
    for segment in path.strip("/").split("/"):
        if ref["k"] != "tree":
            raise KeyError(f"handle path {path!r}: {segment!r} is a blob")
        node = json.loads(get(ref["id"]).decode())
        if segment not in node["e"]:
            raise KeyError(f"handle path {path!r}: no entry {segment!r}")
        ref = node["e"][segment]
    return ref


def materialize_tree(get: Callable[[str], bytes], ref: dict) -> Any:
    """Expand a stored ref into the plain nested summary dict containers
    boot from."""
    if ref["k"] == "blob":
        return json.loads(get(ref["id"]).decode())
    node = json.loads(get(ref["id"]).decode())
    return {name: materialize_tree(get, child)
            for name, child in node["e"].items()}


def materialize_snapcols(get: Callable[[str], bytes], root: dict) -> dict:
    """Expand a columnar ``{"t": "snapcols"}`` version root into the
    classic nested boot dict: pull the content-addressed chunks, decode
    the columns, and rebuild the single-data-store container shape the
    loader already understands. This is the LEGACY-COMPAT read path —
    fast boots splice the framed chunk bytes straight off the wire and
    never come through here."""
    from ..protocol import snapcols

    chunks = [get(h) for h in root["chunks"]]
    mergetree = snapcols.decode_snapshot_chunks(
        chunks, root["min_seq"], root["tree_seq"])
    return {
        "protocol": root["protocol"],
        "runtime": {
            "dataStores": {
                root["ds"]: {
                    "pkg": root["pkg"],
                    "snapshot": {
                        "channels": {
                            root["channel"]: {
                                "type": "shared-string",
                                "snapshot": {
                                    "mergetree": mergetree,
                                    "intervals": {},
                                },
                            }
                        }
                    },
                }
            }
        },
        "sequence_number": root["sequence_number"],
    }
