"""Gateway: a horizontally-scalable front-end instance.

Ref: the reference runs N Alfred instances behind Redis-backed socket.io
(services/src/socketIoRedisPublisher.ts) — each Alfred terminates client
sockets and the pub/sub layer fans sequenced batches to every instance
once. Here each gateway process serves the standard client wire protocol
(driver/network.py speaks to it unchanged) and muxes all its sessions
over ONE upstream backbone connection to the core ordering process
(front_end.py's f* frames):

    clients ⇄ gateway (this module) ⇄ core NetworkFrontEnd + pipeline

What scales: socket termination, frame parsing, and broadcast fan-out
encode move to the gateways; the core sends each doc's batch ONCE per
gateway as raw bytes that the gateway re-frames once and relays to every
local subscriber. Submit frames pass through without re-encoding the op
payloads.

Relay tree (ISSUE 12): a gateway's upstream may itself be another
gateway (``--upstream-gateway H:P``) — every gateway SERVES the same
f* backbone protocol it dials, so tiers stack:

    clients ⇄ leaf gateways ⇄ mid gateways ⇄ core

A downstream gateway is an ordinary client socket here whose first
``fconnect`` marks it a LINK: it gets ONE topic registration per doc
(however many clients ride behind it), and upstream fan-out bytes relay
to it VERBATIM — the topic-slice splice happens once per tier, the
payload encode zero times (``fanout.relay.splices`` vs
``fanout.relay.encodes``). The core's per-doc cost is per CHILD, not
per client: 10× the readers behind a deeper tree is ~flat bytes/op at
the core.

Deployment: ``python -m fluidframework_tpu.service.gateway
--core-host H --core-port P [--port N]``; add another tier with
``--upstream-gateway H:P`` (aliases the core address and keeps the
asyncio relay, which speaks the backbone protocol on both sides).

When to use it (measured honestly): on a single host the extra hop LOSES
— the core's one-encode batch cache makes direct fan-out writes cheap,
so bench.py keeps the direct topology. Gateways are the cross-HOST
scale-out story: socket termination under TLS/compression, thousands of
clients per doc, or a core that is NIC-bound — the same conditions that
motivate the reference's multi-Alfred deployment.
"""

from __future__ import annotations

import argparse
import asyncio
import itertools
import json
import socket as _socket
import time
from typing import Optional

from ..obs import get_recorder, tier_counters
from ..utils.affinity import loop_only
from ..protocol import binwire
from ..utils.telemetry import HOP_RELAY
from .front_end import (_BULK_FRAMES, _encode_frame, _frame_buffered,
                        _read_body)


class _CoreError(RuntimeError):
    """An upstream error reply, with its machine-readable fields kept —
    a relayed ``boot_pending`` (cold-start admission parking) must reach
    the leaf client's retry lane intact through any number of tiers."""

    def __init__(self, reply: dict):
        super().__init__(f"core error: {reply.get('message')}")
        self.code = reply.get("code")
        self.retry_after_ms = reply.get("retryAfterMs")


def _error_frame(frame: dict, e: BaseException) -> dict:
    err = {"t": "error", "rid": frame.get("rid"), "message": str(e)}
    code = getattr(e, "code", None)
    if code:
        err["code"] = code
        retry = getattr(e, "retry_after_ms", None)
        if retry is not None:
            err["retryAfterMs"] = retry
    return err


class _GatewaySession:
    """One client connection terminated at this gateway.

    A downstream GATEWAY arrives on the same listener; its first
    ``fconnect`` flips ``is_link`` and the session becomes a relay-tree
    edge: many muxed sessions (``dsids``/``fsids``) over one socket,
    one topic registration per doc however many of them share it
    (``ftopic_refs``)."""

    def __init__(self, gw: "Gateway", writer: asyncio.StreamWriter):
        self.gw = gw
        self.writer = writer
        self.sid: Optional[int] = None
        self.topic: Optional[str] = None
        self.binary = False  # client negotiated binwire ops push
        self.up: Optional[_Upstream] = None  # owning core's backbone
        # While a connect awaits the core's auth verdict, broadcasts are
        # held here instead of the socket; flushed on success, dropped on
        # refusal. None = no gate (normal delivery).
        self._gate_buffer: Optional[list[bytes]] = None
        # relay-tree link state (this "client" is a downstream gateway)
        self.is_link = False
        self.dsids: dict[int, int] = {}  # downstream sid → parent sid
        self.fsids: dict[int, int] = {}  # parent sid → downstream sid
        self.fups: dict[int, _Upstream] = {}  # parent sid → owning core
        self.ftopic_names: dict[int, str] = {}  # parent sid → topic
        self.ftopic_refs: dict[str, int] = {}  # topic → live muxed sids

    def push_raw(self, raw: bytes) -> None:
        if self._gate_buffer is not None:
            self._gate_buffer.append(raw)
            return
        try:
            if not self.writer.is_closing():
                self.writer.write(raw)
        except RuntimeError:
            pass

    def push(self, obj: dict) -> None:
        self.push_raw(_encode_frame(obj))

    async def handle(self, frame: dict) -> None:
        t = frame.get("t")
        gw = self.gw
        if t == "connect":
            # A re-connect on a live session must first release the old
            # registration, else the prior sid's core-side connection and
            # topic refcount leak until the socket closes.
            if self.sid is not None:
                self.detach()
            self.sid = next(gw.sid_counter)
            self.binary = bool(frame.get("bin"))
            self.topic = f"{frame['tenant']}/{frame['doc']}"
            # Register NOW (the core broadcasts this client's own join
            # synchronously with the fconnect — miss it and the client
            # never activates) but GATE delivery behind the core's auth
            # verdict: buffered frames flush only on success, and a
            # refusal unregisters + drops the buffer, so a rejected
            # (tokenless) client never receives a byte of the doc's live
            # stream even while authorized clients keep the topic open.
            self._gate_buffer = []
            gw.sessions[self.sid] = self
            gw.topic_sessions.setdefault(self.topic, set()).add(self)
            try:
                # route to the doc's owning core (sharded mode resolves
                # the partition lease; classic mode returns THE core).
                # The gateway ALWAYS asks for binary fops — it relays
                # them to binary clients by byte-slicing and re-encodes
                # JSON locally for legacy clients, keeping the expensive
                # per-op encode off the core either way.
                self.up = await gw.upstream_for(frame["tenant"],
                                                frame["doc"])
                self.up.sessions.add(self.sid)
                reply = await gw.upstream_request({
                    "t": "fconnect", "sid": self.sid,
                    "tenant": frame["tenant"], "doc": frame["doc"],
                    "details": frame.get("details"),
                    "token": frame.get("token"), "bin": 1,
                    "readonly": frame.get("readonly")}, self.up)
            except BaseException:
                self._gate_buffer = None
                self.detach()
                gw.note_route_failure(frame["tenant"], frame["doc"])
                raise
            self._gate_buffer, buffered = None, self._gate_buffer
            self.push({"t": "connected", "rid": frame.get("rid"),
                       "clientId": reply["clientId"], "seq": reply["seq"],
                       "mode": reply.get("mode", "write"),
                       "maxMessageSize": reply.get("maxMessageSize")})
            for raw in buffered:
                self.push_raw(raw)
        elif t == "submit":
            if self.up is None:
                raise RuntimeError("submit before connect")
            # ops pass through verbatim — no payload re-encode
            gw.upstream_send({"t": "fsubmit", "sid": self.sid,
                              "ops": frame["ops"]}, self.up)
        elif t == "signal":
            if self.up is None:
                raise RuntimeError("signal before connect")
            gw.upstream_send({"t": "fsignal", "sid": self.sid,
                              "content": frame["content"],
                              "type": frame.get("type", "signal")},
                             self.up)
        elif t == "disconnect":
            self.detach()
        elif t == "ping":
            # answered HERE, not relayed: the probe checks this hop's
            # liveness, and the upstream has its own reader watchdog
            self.push({"t": "pong"})
        elif t == "gateway_counters":
            # THIS tier's relay counters (splices / encodes / upstream
            # frames+bytes) — answered locally, unlike admin_counters
            # which relays to the core. The read-storm bench asserts
            # the zero-re-encode contract through this door.
            self.push({"t": "gateway_counters", "rid": frame.get("rid"),
                       "counters": gw.counters.snapshot()})
        elif t == "fconnect":
            # a downstream GATEWAY muxing a session through this tier
            await self._handle_fconnect(frame)
        elif t == "fsubmit":
            psid = self.dsids.get(frame["sid"])
            if psid is None:
                raise RuntimeError("fsubmit on unknown downstream sid")
            gw.upstream_send({"t": "fsubmit", "sid": psid,
                              "ops": frame["ops"]}, self.fups[psid])
        elif t == "fsignal":
            psid = self.dsids.get(frame["sid"])
            if psid is None:
                raise RuntimeError("fsignal on unknown downstream sid")
            gw.upstream_send({"t": "fsignal", "sid": psid,
                              "content": frame["content"],
                              "type": frame.get("type", "signal")},
                             self.fups[psid])
        elif t == "fdisconnect":
            psid = self.dsids.pop(frame["sid"], None)
            if psid is not None:
                self._release_link_sid(psid)
        elif t in ("get_deltas", "get_versions", "get_tree", "read_blob",
                   "write_blob", "upload_summary"):
            up = await gw.upstream_for(frame["tenant"], frame["doc"])
            reply = await gw.upstream_request(
                {k: v for k, v in frame.items() if k != "rid"}, up)
            reply["rid"] = frame.get("rid")
            self.push(reply)
        elif t in ("get_deltas_cols", "get_snapshot_cols"):
            await self._relay_bulk(frame)
        else:
            self.push({"t": "error", "rid": frame.get("rid"),
                       "message": f"unknown frame type {t!r}"})

    async def _handle_fconnect(self, frame: dict) -> None:
        """Open a muxed downstream session through this tier: allocate a
        parent-side sid, register the LINK on the doc topic (once per
        topic — fan-out to the whole downstream subtree is one frame),
        and splice the fconnect upstream.

        No gate buffer on links: the frames that reach a downstream
        gateway before ITS client's auth verdict land in that client's
        own gate buffer, so an unauthorized client still never sees a
        byte — the gate lives at the tree's leaves."""
        gw = self.gw
        dsid = frame["sid"]
        if not self.is_link:
            self.is_link = True
            self.binary = True  # links always speak binwire
            gw.links.add(self)
        stale = self.dsids.pop(dsid, None)
        if stale is not None:
            # downstream reused a sid before its fdisconnect drained
            self._release_link_sid(stale)
        tenant, doc = frame["tenant"], frame["doc"]
        topic = f"{tenant}/{doc}"
        psid = next(gw.sid_counter)
        # register BEFORE the upstream fconnect for the same reason the
        # client path does: the join broadcast is synchronous with it
        gw.sessions[psid] = self
        self.dsids[dsid] = psid
        self.fsids[psid] = dsid
        self.ftopic_names[psid] = topic
        self.ftopic_refs[topic] = self.ftopic_refs.get(topic, 0) + 1
        if self.ftopic_refs[topic] == 1:
            gw.topic_sessions.setdefault(topic, set()).add(self)
        try:
            up = await gw.upstream_for(tenant, doc)
            up.sessions.add(psid)
            self.fups[psid] = up
            reply = await gw.upstream_request({
                "t": "fconnect", "sid": psid, "tenant": tenant,
                "doc": doc, "details": frame.get("details"),
                "token": frame.get("token"), "bin": 1,
                "readonly": frame.get("readonly")}, up)
        except BaseException:
            self.dsids.pop(dsid, None)
            self._release_link_sid(psid, fdisconnect=False)
            gw.note_route_failure(tenant, doc)
            raise
        self.push({"t": "fconnected", "rid": frame.get("rid"),
                   "sid": dsid, "clientId": reply["clientId"],
                   "seq": reply["seq"],
                   "mode": reply.get("mode", "write"),
                   "maxMessageSize": reply.get("maxMessageSize")})

    def _release_link_sid(self, psid: int, fdisconnect: bool = True
                          ) -> None:
        gw = self.gw
        gw.sessions.pop(psid, None)
        self.fsids.pop(psid, None)
        topic = self.ftopic_names.pop(psid, None)
        if topic is not None and topic in self.ftopic_refs:
            self.ftopic_refs[topic] -= 1
            if not self.ftopic_refs[topic]:
                del self.ftopic_refs[topic]
                peers = gw.topic_sessions.get(topic)
                if peers is not None:
                    peers.discard(self)
                    if not peers:
                        gw.topic_sessions.pop(topic, None)
        up = self.fups.pop(psid, None)
        if up is not None:
            up.sessions.discard(psid)
            if fdisconnect and not up.writer.is_closing():
                gw.upstream_send({"t": "fdisconnect", "sid": psid}, up)

    async def _relay_bulk(self, frame: dict) -> None:
        """Columnar bulk RPCs (snapshot chunks, delta blocks) stream
        multi-frame responses, and snapshot chunk pushes carry rid 0 —
        they can't be demuxed on the shared backbone. Relay them over a
        DEDICATED upstream connection per request instead: every frame
        passes through verbatim (chunk bytes splice down the tree with
        zero re-encode) until the JSON terminal, which carries the
        caller's rid unchanged. Stacks through gateway tiers: the
        parent tier sees an ordinary client-protocol bulk RPC."""
        gw = self.gw
        up = await gw.upstream_for(frame["tenant"], frame["doc"])
        host, _, port = up.address.rpartition(":")
        reader, writer = await asyncio.open_connection(
            host or "127.0.0.1", int(port))
        try:
            writer.write(_encode_frame(frame))
            await writer.drain()
            while True:
                body = await _read_body(reader)
                if body is None:
                    raise ConnectionError("core closed during bulk relay")
                if binwire.is_binary(body):
                    self.push_raw(binwire.frame(body))
                    continue
                self.push(json.loads(body.decode()))  # rid-tagged terminal
                break
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def detach(self) -> None:
        if self.is_link:
            # the downstream gateway's socket is gone: release every
            # muxed session it held (core-side fdisconnects drain the
            # quorum exactly as if each client had left)
            for psid in list(self.dsids.values()):
                self._release_link_sid(psid)
            self.dsids.clear()
            self.gw.links.discard(self)
            self.is_link = False
        if self.sid is not None:
            self.gw.sessions.pop(self.sid, None)
            if self.topic is not None:
                peers = self.gw.topic_sessions.get(self.topic)
                if peers is not None:
                    peers.discard(self)
                    if not peers:  # prune emptied topics
                        self.gw.topic_sessions.pop(self.topic, None)
            if self.up is not None:
                self.up.sessions.discard(self.sid)
                if not self.up.writer.is_closing():
                    self.gw.upstream_send(
                        {"t": "fdisconnect", "sid": self.sid}, self.up)
                self.up = None
            self.sid = None


class _Upstream:
    """One backbone connection to one core process."""

    def __init__(self, gw: "Gateway", address: str,
                 writer: asyncio.StreamWriter):
        self.gw = gw
        self.address = address
        self.writer = writer
        self.sessions: set[int] = set()  # sids registered on this core
        self.pending_rids: set[int] = set()  # in-flight requests HERE


class Gateway:
    """``shard_dir``/``shards`` switch on sharded-core routing: each doc
    routes to the core holding its partition's lease (placement.py); a
    core's death kills only ITS sessions, and the next resolution picks
    up the takeover owner. Without them the gateway runs the classic
    single-core topology."""

    def __init__(self, core_host: str, core_port: int,
                 host: str = "127.0.0.1", port: int = 0,
                 shard_dir: Optional[str] = None, shards: int = 0,
                 table_server: Optional[str] = None,
                 host_id: Optional[str] = None):
        self.core_host, self.core_port = core_host, core_port
        self.host, self.port = host, port
        self.sessions: dict[int, _GatewaySession] = {}
        self.topic_sessions: dict[str, set[_GatewaySession]] = {}
        self.sid_counter = itertools.count(1)
        self._rid_counter = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self.placement = None
        self.routing = None
        # multi-host: which host group this gateway runs in; with a
        # table set, every route resolution is classified same-host vs
        # cross-host (fanout.upstream.same_host / .cross_host) — the
        # weak-scaling bench's locality hit rate
        self.host_id = host_id
        self._table = None
        self._addr_hosts: dict = {}
        if shard_dir is not None:
            import os

            from .placement import PlacementDir
            from .placement_plane import EpochTable, RoutingCache

            self.placement = PlacementDir(
                os.path.join(shard_dir, "placement"), shards)
            # hot-path routing: in-memory dict, epoch-table refresh on
            # miss, lease read only as the liveness fallback — replaces
            # the old per-connect owner_of poll (placement_plane)
            self._table = EpochTable.for_shard_dir(shard_dir)
            self.routing = RoutingCache(self.placement, self._table)
        elif table_server:
            # remote host group: no placement dir to read — the same
            # RoutingCache machinery runs over RPC proxies against the
            # placement host's table door (table_client.py); epoch-gated
            # fplacement pushes are the cache-coherence protocol either
            # way
            from .placement_plane import RoutingCache
            from .table_client import RemoteTableClient

            client = RemoteTableClient(table_server, shards)
            self.placement = client.leases
            self._table = client.table
            self.routing = RoutingCache(self.placement, self._table)
        self._upstreams: dict[str, _Upstream] = {}
        self._upstream_dials: dict[str, "asyncio.Future"] = {}
        self._up_default: Optional[_Upstream] = None
        # relay-tree: downstream gateway link sessions (fplacement
        # pushes forward to every one of them)
        self.links: set[_GatewaySession] = set()
        # splice-vs-encode accounting for the fan-out tier
        # (fanout.relay.splices should dwarf fanout.relay.encodes on an
        # all-binary tree — the acceptance gate asserts encodes == 0)
        self.counters = tier_counters("fanout")

    # ----------------------------------------------------------- upstream

    async def _open_upstream(self, address: str) -> _Upstream:
        while True:
            up = self._upstreams.get(address)
            if up is not None and not up.writer.is_closing():
                return up
            dial = self._upstream_dials.get(address)
            if dial is None:
                break
            # another session is already dialing this core: share its
            # connection. Two concurrent dials would open TWO backbone
            # connections to one core — the core tracks its per-topic
            # fan-out subscription per connection, so every broadcast
            # would reach this gateway (and its clients) TWICE.
            up = await asyncio.shield(dial)
            if up is not None and not up.writer.is_closing():
                return up
        fut = asyncio.get_running_loop().create_future()
        self._upstream_dials[address] = fut
        try:
            host, _, port = address.rpartition(":")
            reader, writer = await asyncio.open_connection(
                host or "127.0.0.1", int(port))
            sock = writer.get_extra_info("socket")
            if sock is not None:
                sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            up = _Upstream(self, address, writer)
            self._upstreams[address] = up
            # keep a strong ref on the upstream: the loop's refs are
            # weak, and a gc'd reader task silently freezes every
            # session on this core (acks stop; clients stall until
            # reconnect)
            up.reader_task = asyncio.get_running_loop().create_task(
                self._upstream_loop(reader, up))
            fut.set_result(up)
            return up
        finally:
            del self._upstream_dials[address]
            if not fut.done():
                # dial failed: waiters retry (and dial themselves);
                # the failure propagates to THIS caller via the raise
                fut.set_result(None)

    async def _connect_upstream(self) -> None:
        if self.placement is None:
            self._up_default = await self._open_upstream(
                f"{self.core_host}:{self.core_port}")

    async def upstream_for(self, tenant: str, doc: str) -> _Upstream:
        """The backbone connection of the core owning this doc."""
        if self.placement is None:
            if self._up_default is None or \
                    self._up_default.writer.is_closing():
                self._up_default = None
                await self._connect_upstream()
            return self._up_default
        from .stage_runner import doc_partition

        k = doc_partition(tenant, doc, self.placement.n)
        deadline = asyncio.get_running_loop().time() + 15.0
        while True:
            addr = self.routing.resolve(k)
            if addr is not None:
                if self.host_id is not None:
                    self.counters.inc(
                        "fanout.upstream.same_host"
                        if self._host_of_addr(addr) == self.host_id
                        else "fanout.upstream.cross_host")
                try:
                    return await self._open_upstream(addr)
                except OSError:
                    # owner died between route and dial: drop the route
                    # so the retry re-reads table + lease
                    self.routing.invalidate(k)
            if asyncio.get_running_loop().time() > deadline:
                raise ConnectionError(
                    f"no live core owns partition {k}")
            await asyncio.sleep(0.2)

    def _host_of_addr(self, addr: str):
        """Which host group advertises ``addr`` in the table's cores
        rows (lazily cached — membership changes re-resolve on miss)."""
        h = self._addr_hosts.get(addr)
        if h is None and self._table is not None:
            for row in self._table.cores().values():
                a = row.get("addr")
                if a:
                    self._addr_hosts[a] = row.get("host") or ""
            h = self._addr_hosts.get(addr)
        return h or None

    def note_route_failure(self, tenant: str, doc: str) -> None:
        """A core refused the doc (``not the owner`` after a migration
        this gateway missed): drop the cached route so the client's
        reconnect resolves fresh instead of looping on the old owner."""
        if self.routing is None:
            return
        from .stage_runner import doc_partition

        self.routing.invalidate(doc_partition(tenant, doc,
                                              self.placement.n))

    def upstream_send(self, obj: dict, up: Optional[_Upstream] = None
                      ) -> None:
        (up or self._up_default).writer.write(_encode_frame(obj))

    def upstream_send_raw(self, raw: bytes,
                          up: Optional[_Upstream] = None) -> None:
        (up or self._up_default).writer.write(raw)

    async def upstream_request(self, obj: dict,
                               up: Optional[_Upstream] = None) -> dict:
        rid = next(self._rid_counter)
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        target = up or self._up_default
        if target is None:
            raise ConnectionError("no live core connection")
        target.pending_rids.add(rid)
        try:
            self.upstream_send(dict(obj, rid=rid), target)
            reply = await fut
        finally:
            target.pending_rids.discard(rid)
            self._pending.pop(rid, None)
        if reply.get("t") == "error":
            raise _CoreError(reply)
        return reply

    async def _upstream_loop(self, reader: asyncio.StreamReader,
                             up: _Upstream) -> None:
        try:
            while True:
                body = await _read_body(reader)
                if body is None:
                    break
                self.counters.inc("fanout.upstream.frames")
                self.counters.inc("fanout.upstream.bytes", len(body) + 4)
                if binwire.is_binary(body):
                    self._dispatch_upstream_binary(body)
                else:
                    self._dispatch_upstream(json.loads(body.decode()))
        finally:
            # this upstream is gone: only ITS clients are dead. In
            # sharded mode the takeover core will serve them on
            # reconnect. A relay LINK's writer closing kills the whole
            # downstream gateway socket — crash-equivalent on purpose:
            # the downstream tier's own upstream-loss teardown then
            # closes ITS clients, whose drivers reconnect and gap-repair
            # through the driver catch-up fetch.
            self._upstreams.pop(up.address, None)
            if self._up_default is up:
                self._up_default = None
            for sid in list(up.sessions):
                session = self.sessions.get(sid)
                if session is not None:
                    try:
                        session.writer.close()
                    except Exception:
                        pass
            # fail exactly THIS core's in-flight requests — a request
            # pending on a live core must keep waiting for its reply
            for rid in list(up.pending_rids):
                fut = self._pending.pop(rid, None)
                if fut is not None and not fut.done():
                    fut.set_exception(
                        ConnectionError("core disconnected"))

    @loop_only("gateway")
    def _dispatch_upstream_binary(self, body: bytes) -> None:
        """Relay a binary fops batch or fpresence flush: downstream
        gateway LINKS get the backbone bytes VERBATIM (topic intact —
        their own dispatch splices again), binary clients get the
        topic-stripped slice, and only a legacy JSON client costs a
        re-encode (lazy, once per frame however many legacy clients).
        The op/signal payloads are never decoded on the binary path —
        that's the relay-tree invariant the smoke gate counter-asserts:
        ``fanout.relay.encodes`` stays 0 above the first tier."""
        if body[1] == binwire.FT_FPRESENCE:
            topic, client_body = binwire.fpresence_strip_topic(body)
        else:
            topic, client_body = binwire.fops_strip_topic(body)
        self.counters.inc("fanout.relay.splices")
        raw = fraw = json_raw = None
        for session in self.topic_sessions.get(topic, ()):
            if session.is_link:
                if fraw is None:
                    fraw = binwire.frame(body)
                session.push_raw(fraw)
            elif session.binary:
                if raw is None:
                    raw = binwire.frame(client_body)
                session.push_raw(raw)
            else:
                if json_raw is None:
                    json_raw = self._legacy_json(body, client_body)
                    self.counters.inc("fanout.relay.encodes")
                session.push_raw(json_raw)

    def _legacy_json(self, body: bytes, client_body: bytes) -> bytes:
        """Materialize the JSON wire form of a binary fan-out frame for
        a legacy client (possibly several frames concatenated — the
        stream is length-prefixed, one write carries them all)."""
        from ..protocol.serialization import message_to_dict

        if body[1] == binwire.FT_FPRESENCE:
            return b"".join(
                _encode_frame({"t": "signal",
                               "signal": message_to_dict(s)})
                for s in binwire.decode_presence(client_body))
        _, msgs = binwire.decode_ops(client_body)
        return _encode_frame(
            {"t": "ops", "msgs": [message_to_dict(m) for m in msgs]})

    @loop_only("gateway")
    def _dispatch_upstream(self, frame: dict) -> None:
        rid = frame.get("rid")
        if rid is not None:
            fut = self._pending.pop(rid, None)
            if fut is not None and not fut.done():
                fut.set_result(frame)
            return
        t = frame.get("t")
        if t == "fops":
            # ONE re-encode for all local subscribers of the doc;
            # downstream links get the backbone frame verbatim
            raw = fraw = None
            for session in self.topic_sessions.get(frame["topic"], ()):
                if session.is_link:
                    if fraw is None:
                        fraw = _encode_frame(frame)
                    session.push_raw(fraw)
                else:
                    if raw is None:
                        raw = _encode_frame({"t": "ops",
                                             "msgs": frame["msgs"]})
                    session.push_raw(raw)
        elif t == "fnack":
            session = self.sessions.get(frame["sid"])
            if session is not None:
                if session.is_link:
                    dsid = session.fsids.get(frame["sid"])
                    if dsid is not None:
                        session.push({"t": "fnack", "sid": dsid,
                                      "nack": frame["nack"]})
                else:
                    session.push({"t": "nack", "nack": frame["nack"]})
        elif t == "fsignal":
            raw = fraw = None
            for session in self.topic_sessions.get(frame["topic"], ()):
                if session.is_link:
                    if fraw is None:
                        fraw = _encode_frame(frame)
                    session.push_raw(fraw)
                else:
                    if raw is None:
                        raw = _encode_frame({"t": "signal",
                                             "signal": frame["signal"]})
                    session.push_raw(raw)
        elif t == "fplacement":
            # routing flip push: the core committed a migration; patch
            # the cache in-memory (epoch-gated — a late push about an
            # older epoch is ignored) so the reconnects triggered by the
            # fdropped/teardown that follows resolve straight to the
            # new owner without a table read. Relay tiers forward the
            # push verbatim so the WHOLE tree learns the flip at once.
            if self.routing is not None:
                self.routing.note_epoch(int(frame["k"]), frame["addr"],
                                        int(frame["epoch"]))
            raw = None
            for session in list(self.links):
                if raw is None:
                    raw = _encode_frame(frame)
                session.push_raw(raw)
        elif t == "fdropped":
            # the core revoked this client's partition (lease moved):
            # close just that client; its auto-reconnect re-resolves the
            # owner and lands on the takeover core. For a muxed session
            # on a relay LINK, the drop forwards downstream and releases
            # only that sid — the link (its other docs) stays up.
            session = self.sessions.get(frame["sid"])
            if session is None:
                pass
            elif session.is_link:
                psid = frame["sid"]
                dsid = session.fsids.get(psid)
                if dsid is not None:
                    session.dsids.pop(dsid, None)
                session._release_link_sid(psid, fdisconnect=False)
                if dsid is not None:
                    session.push({"t": "fdropped", "sid": dsid})
            else:
                try:
                    session.writer.close()
                except Exception:
                    pass

    # ------------------------------------------------------------- clients

    async def _handle_client(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        sock = writer.get_extra_info("socket")
        if sock is not None:
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        session = _GatewaySession(self, writer)
        recorder = get_recorder()
        conn_id = f"gw-{id(session) & 0xFFFFFF:06x}"
        try:
            while True:
                body = await _read_body(reader)
                if body is None:
                    break
                # drain-batched serving (same shape as the core's
                # _handle_conn): relay every frame already buffered on
                # this socket, then drain the writer once per wave — a
                # client's coalesced submit burst costs one drain, not
                # one per frame
                n = 0
                deferred: list = []
                while body is not None:
                    n += 1
                    recorder.frame(conn_id, "in", body)
                    if binwire.is_binary(body):
                        # hot path: rewrite submit → fsubmit by
                        # prepending the sid — op payloads are relayed,
                        # never decoded here
                        ft = body[1] if len(body) >= 2 else 0
                        if (ft in (binwire.FT_SUBMIT,
                                   binwire.FT_COLS_SUBMIT)
                                and session.sid is not None
                                and session.up is not None):
                            if (ft == binwire.FT_COLS_SUBMIT
                                    and body[-1]):
                                # sampled frame (hoptail count > 0):
                                # append gateway/relay in place —
                                # unsampled frames cost one byte read
                                body = binwire.append_hop(
                                    body, HOP_RELAY, time.time())
                            self.upstream_send_raw(binwire.frame(
                                binwire.submit_to_fsubmit(body,
                                                          session.sid)),
                                session.up)
                        elif (ft in (binwire.FT_FSUBMIT,
                                     binwire.FT_COLS_FSUBMIT)
                                and session.is_link):
                            # relay-tree write path: re-address the
                            # muxed sid to this tier's sid, payload
                            # bytes untouched
                            psid = session.dsids.get(
                                binwire.fsubmit_sid(body))
                            up = session.fups.get(psid)
                            if up is not None:
                                if (ft == binwire.FT_COLS_FSUBMIT
                                        and body[-1]):
                                    body = binwire.append_hop(
                                        body, HOP_RELAY, time.time())
                                self.upstream_send_raw(binwire.frame(
                                    binwire.fsubmit_rewrite_sid(body,
                                                                psid)),
                                    up)
                        else:
                            session.push(
                                {"t": "error",
                                 "message": "unexpected binary frame"})
                    else:
                        frame = json.loads(body.decode())
                        if frame.get("t") in _BULK_FRAMES:
                            # lane priority (mirrors the core's
                            # _handle_conn): bulk backfill relays run
                            # after the wave's interactive frames
                            deferred.append(frame)
                        else:
                            try:
                                await session.handle(frame)
                            except (RuntimeError, ConnectionError) as e:
                                # a core error reply (auth refusal,
                                # storage failure) answers THIS request
                                # — it must not kill the socket
                                session.push(_error_frame(frame, e))
                    body = None
                    if n < 64 and _frame_buffered(reader):
                        body = await _read_body(reader)
                for frame in deferred:
                    try:
                        await session.handle(frame)
                    except (RuntimeError, ConnectionError) as e:
                        session.push(_error_frame(frame, e))
                await writer.drain()
        except (ValueError, json.JSONDecodeError):
            pass
        except (ConnectionResetError, BrokenPipeError):
            pass
        except Exception as e:  # noqa: BLE001 — unhandled tier failure:
            # dump the flight recorder so the frames preceding the
            # escape are preserved for post-mortem
            try:
                recorder.dump("gateway_unhandled",
                              conn=conn_id, error=str(e))
            except Exception:
                pass
        finally:
            session.detach()
            try:
                writer.close()
            except Exception:
                pass

    async def _start(self) -> None:
        await self._connect_upstream()
        server = await asyncio.start_server(
            self._handle_client, self.host, self.port, backlog=1024)
        self.port = server.sockets[0].getsockname()[1]

    def serve_forever(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        loop.run_until_complete(self._start())
        print(f"LISTENING {self.host}:{self.port}", flush=True)
        loop.run_forever()


def main() -> None:
    import gc

    p = argparse.ArgumentParser(description="Fluid TPU gateway front end")
    p.add_argument("--core-host", default="127.0.0.1")
    p.add_argument("--core-port", type=int, default=0,
                   help="single-core topology (omit with --shard-dir)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--shard-dir", default=None,
                   help="sharded-core deployment dir (placement leases); "
                        "docs route to their partition's owning core")
    p.add_argument("--shards", type=int, default=0,
                   help="number of doc partitions in the sharded core")
    p.add_argument("--table-server", default=None, metavar="HOST:PORT",
                   help="remote-host deployment: route from the "
                        "placement host's table door (admin_table_*) "
                        "instead of a local --shard-dir")
    p.add_argument("--host-id", default=None,
                   help="this gateway's host group id (multi-host "
                        "fleets): routes are counted same- vs "
                        "cross-host for the locality hit rate")
    p.add_argument("--upstream-gateway", default=None, metavar="HOST:PORT",
                   help="relay-tree mode: dial a PARENT GATEWAY as the "
                        "upstream instead of a core — fan-out bytes "
                        "splice through each tier with zero re-encode")
    p.add_argument("--python", action="store_true",
                   help="force the asyncio relay (compat path: serves "
                        "JSON-ops legacy clients the native loop refuses)")
    args = p.parse_args()
    if args.upstream_gateway:
        # an upstream gateway speaks the same backbone protocol a core
        # does; the asyncio relay (which SERVES that protocol to the
        # next tier down) is what stacks, so skip the native loop
        host, _, port = args.upstream_gateway.rpartition(":")
        args.core_host, args.core_port = host or "127.0.0.1", int(port)
        args.python = True
    if args.shard_dir is None and args.table_server is None \
            and not args.core_port:
        p.error("--core-port is required without --shard-dir / "
                "--table-server (or --upstream-gateway)")
    if not args.python and args.shard_dir is None \
            and args.table_server is None:
        # default: the C++ epoll relay (native/gateway.cpp) — zero
        # Python on the hot path (VERDICT r4 #3, SURVEY §2.9). Falls
        # back to asyncio if the toolchain can't build it.
        try:
            from ..native.build import NativeUnavailable
            from ..native.gateway import NativeGateway

            try:
                ng = NativeGateway(args.core_host, args.core_port,
                                   host=args.host, port=args.port)
            except NativeUnavailable:
                ng = None
        except Exception:
            ng = None
        if ng is not None:
            print(f"LISTENING {args.host}:{ng.port}", flush=True)
            raise SystemExit(0 if ng.run() == 0 else 1)
    # relay path allocates acyclic graphs only; cycle-collector pauses
    # would land directly on forwarded-frame latency (see front_end main)
    gc.freeze()
    gc.disable()
    Gateway(args.core_host, args.core_port,
            host=args.host, port=args.port,
            shard_dir=args.shard_dir, shards=args.shards,
            table_server=args.table_server,
            host_id=args.host_id).serve_forever()


if __name__ == "__main__":
    main()
