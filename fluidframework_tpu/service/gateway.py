"""Gateway: a horizontally-scalable front-end instance.

Ref: the reference runs N Alfred instances behind Redis-backed socket.io
(services/src/socketIoRedisPublisher.ts) — each Alfred terminates client
sockets and the pub/sub layer fans sequenced batches to every instance
once. Here each gateway process serves the standard client wire protocol
(driver/network.py speaks to it unchanged) and muxes all its sessions
over ONE upstream backbone connection to the core ordering process
(front_end.py's f* frames):

    clients ⇄ gateway (this module) ⇄ core NetworkFrontEnd + pipeline

What scales: socket termination, frame parsing, and broadcast fan-out
encode move to the gateways; the core sends each doc's batch ONCE per
gateway as raw bytes that the gateway re-frames once and relays to every
local subscriber. Submit frames pass through without re-encoding the op
payloads.

Deployment: ``python -m fluidframework_tpu.service.gateway
--core-host H --core-port P [--port N]``.

When to use it (measured honestly): on a single host the extra hop LOSES
— the core's one-encode batch cache makes direct fan-out writes cheap,
so bench.py keeps the direct topology. Gateways are the cross-HOST
scale-out story: socket termination under TLS/compression, thousands of
clients per doc, or a core that is NIC-bound — the same conditions that
motivate the reference's multi-Alfred deployment.
"""

from __future__ import annotations

import argparse
import asyncio
import itertools
import json
import socket as _socket
import time
from typing import Optional

from ..obs import get_recorder
from ..protocol import binwire
from ..utils.telemetry import HOP_RELAY
from .front_end import (_BULK_FRAMES, _encode_frame, _frame_buffered,
                        _read_body)


class _GatewaySession:
    """One client connection terminated at this gateway."""

    def __init__(self, gw: "Gateway", writer: asyncio.StreamWriter):
        self.gw = gw
        self.writer = writer
        self.sid: Optional[int] = None
        self.topic: Optional[str] = None
        self.binary = False  # client negotiated binwire ops push
        self.up: Optional[_Upstream] = None  # owning core's backbone
        # While a connect awaits the core's auth verdict, broadcasts are
        # held here instead of the socket; flushed on success, dropped on
        # refusal. None = no gate (normal delivery).
        self._gate_buffer: Optional[list[bytes]] = None

    def push_raw(self, raw: bytes) -> None:
        if self._gate_buffer is not None:
            self._gate_buffer.append(raw)
            return
        try:
            if not self.writer.is_closing():
                self.writer.write(raw)
        except RuntimeError:
            pass

    def push(self, obj: dict) -> None:
        self.push_raw(_encode_frame(obj))

    async def handle(self, frame: dict) -> None:
        t = frame.get("t")
        gw = self.gw
        if t == "connect":
            # A re-connect on a live session must first release the old
            # registration, else the prior sid's core-side connection and
            # topic refcount leak until the socket closes.
            if self.sid is not None:
                self.detach()
            self.sid = next(gw.sid_counter)
            self.binary = bool(frame.get("bin"))
            self.topic = f"{frame['tenant']}/{frame['doc']}"
            # Register NOW (the core broadcasts this client's own join
            # synchronously with the fconnect — miss it and the client
            # never activates) but GATE delivery behind the core's auth
            # verdict: buffered frames flush only on success, and a
            # refusal unregisters + drops the buffer, so a rejected
            # (tokenless) client never receives a byte of the doc's live
            # stream even while authorized clients keep the topic open.
            self._gate_buffer = []
            gw.sessions[self.sid] = self
            gw.topic_sessions.setdefault(self.topic, set()).add(self)
            try:
                # route to the doc's owning core (sharded mode resolves
                # the partition lease; classic mode returns THE core).
                # The gateway ALWAYS asks for binary fops — it relays
                # them to binary clients by byte-slicing and re-encodes
                # JSON locally for legacy clients, keeping the expensive
                # per-op encode off the core either way.
                self.up = await gw.upstream_for(frame["tenant"],
                                                frame["doc"])
                self.up.sessions.add(self.sid)
                reply = await gw.upstream_request({
                    "t": "fconnect", "sid": self.sid,
                    "tenant": frame["tenant"], "doc": frame["doc"],
                    "details": frame.get("details"),
                    "token": frame.get("token"), "bin": 1}, self.up)
            except BaseException:
                self._gate_buffer = None
                self.detach()
                gw.note_route_failure(frame["tenant"], frame["doc"])
                raise
            self._gate_buffer, buffered = None, self._gate_buffer
            self.push({"t": "connected", "rid": frame.get("rid"),
                       "clientId": reply["clientId"], "seq": reply["seq"],
                       "mode": reply.get("mode", "write"),
                       "maxMessageSize": reply.get("maxMessageSize")})
            for raw in buffered:
                self.push_raw(raw)
        elif t == "submit":
            if self.up is None:
                raise RuntimeError("submit before connect")
            # ops pass through verbatim — no payload re-encode
            gw.upstream_send({"t": "fsubmit", "sid": self.sid,
                              "ops": frame["ops"]}, self.up)
        elif t == "signal":
            if self.up is None:
                raise RuntimeError("signal before connect")
            gw.upstream_send({"t": "fsignal", "sid": self.sid,
                              "content": frame["content"],
                              "type": frame.get("type", "signal")},
                             self.up)
        elif t == "disconnect":
            self.detach()
        elif t == "ping":
            # answered HERE, not relayed: the probe checks this hop's
            # liveness, and the upstream has its own reader watchdog
            self.push({"t": "pong"})
        elif t in ("get_deltas", "get_versions", "get_tree", "read_blob",
                   "write_blob", "upload_summary"):
            up = await gw.upstream_for(frame["tenant"], frame["doc"])
            reply = await gw.upstream_request(
                {k: v for k, v in frame.items() if k != "rid"}, up)
            reply["rid"] = frame.get("rid")
            self.push(reply)
        else:
            self.push({"t": "error", "rid": frame.get("rid"),
                       "message": f"unknown frame type {t!r}"})

    def detach(self) -> None:
        if self.sid is not None:
            self.gw.sessions.pop(self.sid, None)
            if self.topic is not None:
                peers = self.gw.topic_sessions.get(self.topic)
                if peers is not None:
                    peers.discard(self)
                    if not peers:  # prune emptied topics
                        self.gw.topic_sessions.pop(self.topic, None)
            if self.up is not None:
                self.up.sessions.discard(self.sid)
                if not self.up.writer.is_closing():
                    self.gw.upstream_send(
                        {"t": "fdisconnect", "sid": self.sid}, self.up)
                self.up = None
            self.sid = None


class _Upstream:
    """One backbone connection to one core process."""

    def __init__(self, gw: "Gateway", address: str,
                 writer: asyncio.StreamWriter):
        self.gw = gw
        self.address = address
        self.writer = writer
        self.sessions: set[int] = set()  # sids registered on this core
        self.pending_rids: set[int] = set()  # in-flight requests HERE


class Gateway:
    """``shard_dir``/``shards`` switch on sharded-core routing: each doc
    routes to the core holding its partition's lease (placement.py); a
    core's death kills only ITS sessions, and the next resolution picks
    up the takeover owner. Without them the gateway runs the classic
    single-core topology."""

    def __init__(self, core_host: str, core_port: int,
                 host: str = "127.0.0.1", port: int = 0,
                 shard_dir: Optional[str] = None, shards: int = 0):
        self.core_host, self.core_port = core_host, core_port
        self.host, self.port = host, port
        self.sessions: dict[int, _GatewaySession] = {}
        self.topic_sessions: dict[str, set[_GatewaySession]] = {}
        self.sid_counter = itertools.count(1)
        self._rid_counter = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self.placement = None
        self.routing = None
        if shard_dir is not None:
            import os

            from .placement import PlacementDir
            from .placement_plane import EpochTable, RoutingCache

            self.placement = PlacementDir(
                os.path.join(shard_dir, "placement"), shards)
            # hot-path routing: in-memory dict, epoch-table refresh on
            # miss, lease read only as the liveness fallback — replaces
            # the old per-connect owner_of poll (placement_plane)
            self.routing = RoutingCache(
                self.placement, EpochTable.for_shard_dir(shard_dir))
        self._upstreams: dict[str, _Upstream] = {}
        self._upstream_dials: dict[str, "asyncio.Future"] = {}
        self._up_default: Optional[_Upstream] = None

    # ----------------------------------------------------------- upstream

    async def _open_upstream(self, address: str) -> _Upstream:
        while True:
            up = self._upstreams.get(address)
            if up is not None and not up.writer.is_closing():
                return up
            dial = self._upstream_dials.get(address)
            if dial is None:
                break
            # another session is already dialing this core: share its
            # connection. Two concurrent dials would open TWO backbone
            # connections to one core — the core tracks its per-topic
            # fan-out subscription per connection, so every broadcast
            # would reach this gateway (and its clients) TWICE.
            up = await asyncio.shield(dial)
            if up is not None and not up.writer.is_closing():
                return up
        fut = asyncio.get_running_loop().create_future()
        self._upstream_dials[address] = fut
        try:
            host, _, port = address.rpartition(":")
            reader, writer = await asyncio.open_connection(
                host or "127.0.0.1", int(port))
            sock = writer.get_extra_info("socket")
            if sock is not None:
                sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            up = _Upstream(self, address, writer)
            self._upstreams[address] = up
            # keep a strong ref on the upstream: the loop's refs are
            # weak, and a gc'd reader task silently freezes every
            # session on this core (acks stop; clients stall until
            # reconnect)
            up.reader_task = asyncio.get_running_loop().create_task(
                self._upstream_loop(reader, up))
            fut.set_result(up)
            return up
        finally:
            del self._upstream_dials[address]
            if not fut.done():
                # dial failed: waiters retry (and dial themselves);
                # the failure propagates to THIS caller via the raise
                fut.set_result(None)

    async def _connect_upstream(self) -> None:
        if self.placement is None:
            self._up_default = await self._open_upstream(
                f"{self.core_host}:{self.core_port}")

    async def upstream_for(self, tenant: str, doc: str) -> _Upstream:
        """The backbone connection of the core owning this doc."""
        if self.placement is None:
            if self._up_default is None or \
                    self._up_default.writer.is_closing():
                self._up_default = None
                await self._connect_upstream()
            return self._up_default
        from .stage_runner import doc_partition

        k = doc_partition(tenant, doc, self.placement.n)
        deadline = asyncio.get_running_loop().time() + 15.0
        while True:
            addr = self.routing.resolve(k)
            if addr is not None:
                try:
                    return await self._open_upstream(addr)
                except OSError:
                    # owner died between route and dial: drop the route
                    # so the retry re-reads table + lease
                    self.routing.invalidate(k)
            if asyncio.get_running_loop().time() > deadline:
                raise ConnectionError(
                    f"no live core owns partition {k}")
            await asyncio.sleep(0.2)

    def note_route_failure(self, tenant: str, doc: str) -> None:
        """A core refused the doc (``not the owner`` after a migration
        this gateway missed): drop the cached route so the client's
        reconnect resolves fresh instead of looping on the old owner."""
        if self.routing is None:
            return
        from .stage_runner import doc_partition

        self.routing.invalidate(doc_partition(tenant, doc,
                                              self.placement.n))

    def upstream_send(self, obj: dict, up: Optional[_Upstream] = None
                      ) -> None:
        (up or self._up_default).writer.write(_encode_frame(obj))

    def upstream_send_raw(self, raw: bytes,
                          up: Optional[_Upstream] = None) -> None:
        (up or self._up_default).writer.write(raw)

    async def upstream_request(self, obj: dict,
                               up: Optional[_Upstream] = None) -> dict:
        rid = next(self._rid_counter)
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        target = up or self._up_default
        if target is None:
            raise ConnectionError("no live core connection")
        target.pending_rids.add(rid)
        try:
            self.upstream_send(dict(obj, rid=rid), target)
            reply = await fut
        finally:
            target.pending_rids.discard(rid)
            self._pending.pop(rid, None)
        if reply.get("t") == "error":
            raise RuntimeError(f"core error: {reply.get('message')}")
        return reply

    async def _upstream_loop(self, reader: asyncio.StreamReader,
                             up: _Upstream) -> None:
        try:
            while True:
                body = await _read_body(reader)
                if body is None:
                    break
                if binwire.is_binary(body):
                    self._dispatch_upstream_binary(body)
                else:
                    self._dispatch_upstream(json.loads(body.decode()))
        finally:
            # this core is gone: only ITS clients are dead. In sharded
            # mode the takeover core will serve them on reconnect.
            self._upstreams.pop(up.address, None)
            if self._up_default is up:
                self._up_default = None
            for sid in list(up.sessions):
                session = self.sessions.get(sid)
                if session is not None:
                    try:
                        session.writer.close()
                    except Exception:
                        pass
            # fail exactly THIS core's in-flight requests — a request
            # pending on a live core must keep waiting for its reply
            for rid in list(up.pending_rids):
                fut = self._pending.pop(rid, None)
                if fut is not None and not fut.done():
                    fut.set_exception(
                        ConnectionError("core disconnected"))

    def _dispatch_upstream_binary(self, body: bytes) -> None:
        """Relay a binary fops batch: byte-slice for binary clients (no
        decode), one lazy JSON re-encode for any legacy client."""
        topic, client_body = binwire.fops_strip_topic(body)
        raw = binwire.frame(client_body)
        json_raw = None
        for session in self.topic_sessions.get(topic, ()):
            if session.binary:
                session.push_raw(raw)
            else:
                if json_raw is None:
                    from ..protocol.serialization import message_to_dict

                    _, msgs = binwire.decode_ops(client_body)
                    json_raw = _encode_frame(
                        {"t": "ops",
                         "msgs": [message_to_dict(m) for m in msgs]})
                session.push_raw(json_raw)

    def _dispatch_upstream(self, frame: dict) -> None:
        rid = frame.get("rid")
        if rid is not None:
            fut = self._pending.pop(rid, None)
            if fut is not None and not fut.done():
                fut.set_result(frame)
            return
        t = frame.get("t")
        if t == "fops":
            # ONE re-encode for all local subscribers of the doc
            raw = _encode_frame({"t": "ops", "msgs": frame["msgs"]})
            for session in self.topic_sessions.get(frame["topic"], ()):
                session.push_raw(raw)
        elif t == "fnack":
            session = self.sessions.get(frame["sid"])
            if session is not None:
                session.push({"t": "nack", "nack": frame["nack"]})
        elif t == "fsignal":
            raw = _encode_frame({"t": "signal", "signal": frame["signal"]})
            for session in self.topic_sessions.get(frame["topic"], ()):
                session.push_raw(raw)
        elif t == "fplacement":
            # routing flip push: the core committed a migration; patch
            # the cache in-memory (epoch-gated — a late push about an
            # older epoch is ignored) so the reconnects triggered by the
            # fdropped/teardown that follows resolve straight to the
            # new owner without a table read
            if self.routing is not None:
                self.routing.note_epoch(int(frame["k"]), frame["addr"],
                                        int(frame["epoch"]))
        elif t == "fdropped":
            # the core revoked this client's partition (lease moved):
            # close just that client; its auto-reconnect re-resolves the
            # owner and lands on the takeover core
            session = self.sessions.get(frame["sid"])
            if session is not None:
                try:
                    session.writer.close()
                except Exception:
                    pass

    # ------------------------------------------------------------- clients

    async def _handle_client(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        sock = writer.get_extra_info("socket")
        if sock is not None:
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        session = _GatewaySession(self, writer)
        recorder = get_recorder()
        conn_id = f"gw-{id(session) & 0xFFFFFF:06x}"
        try:
            while True:
                body = await _read_body(reader)
                if body is None:
                    break
                # drain-batched serving (same shape as the core's
                # _handle_conn): relay every frame already buffered on
                # this socket, then drain the writer once per wave — a
                # client's coalesced submit burst costs one drain, not
                # one per frame
                n = 0
                deferred: list = []
                while body is not None:
                    n += 1
                    recorder.frame(conn_id, "in", body)
                    if binwire.is_binary(body):
                        # hot path: rewrite submit → fsubmit by
                        # prepending the sid — op payloads are relayed,
                        # never decoded here
                        if (len(body) >= 2
                                and body[1] in (binwire.FT_SUBMIT,
                                                binwire.FT_COLS_SUBMIT)
                                and session.sid is not None
                                and session.up is not None):
                            if (body[1] == binwire.FT_COLS_SUBMIT
                                    and body[-1]):
                                # sampled frame (hoptail count > 0):
                                # append gateway/relay in place —
                                # unsampled frames cost one byte read
                                body = binwire.append_hop(
                                    body, HOP_RELAY, time.time())
                            self.upstream_send_raw(binwire.frame(
                                binwire.submit_to_fsubmit(body,
                                                          session.sid)),
                                session.up)
                        else:
                            session.push(
                                {"t": "error",
                                 "message": "unexpected binary frame"})
                    else:
                        frame = json.loads(body.decode())
                        if frame.get("t") in _BULK_FRAMES:
                            # lane priority (mirrors the core's
                            # _handle_conn): bulk backfill relays run
                            # after the wave's interactive frames
                            deferred.append(frame)
                        else:
                            try:
                                await session.handle(frame)
                            except (RuntimeError, ConnectionError) as e:
                                # a core error reply (auth refusal,
                                # storage failure) answers THIS request
                                # — it must not kill the socket
                                session.push(
                                    {"t": "error",
                                     "rid": frame.get("rid"),
                                     "message": str(e)})
                    body = None
                    if n < 64 and _frame_buffered(reader):
                        body = await _read_body(reader)
                for frame in deferred:
                    try:
                        await session.handle(frame)
                    except (RuntimeError, ConnectionError) as e:
                        session.push({"t": "error",
                                      "rid": frame.get("rid"),
                                      "message": str(e)})
                await writer.drain()
        except (ValueError, json.JSONDecodeError):
            pass
        except (ConnectionResetError, BrokenPipeError):
            pass
        except Exception as e:  # noqa: BLE001 — unhandled tier failure:
            # dump the flight recorder so the frames preceding the
            # escape are preserved for post-mortem
            try:
                recorder.dump("gateway_unhandled",
                              conn=conn_id, error=str(e))
            except Exception:
                pass
        finally:
            session.detach()
            try:
                writer.close()
            except Exception:
                pass

    async def _start(self) -> None:
        await self._connect_upstream()
        server = await asyncio.start_server(
            self._handle_client, self.host, self.port, backlog=1024)
        self.port = server.sockets[0].getsockname()[1]

    def serve_forever(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        loop.run_until_complete(self._start())
        print(f"LISTENING {self.host}:{self.port}", flush=True)
        loop.run_forever()


def main() -> None:
    import gc

    p = argparse.ArgumentParser(description="Fluid TPU gateway front end")
    p.add_argument("--core-host", default="127.0.0.1")
    p.add_argument("--core-port", type=int, default=0,
                   help="single-core topology (omit with --shard-dir)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--shard-dir", default=None,
                   help="sharded-core deployment dir (placement leases); "
                        "docs route to their partition's owning core")
    p.add_argument("--shards", type=int, default=0,
                   help="number of doc partitions in the sharded core")
    p.add_argument("--python", action="store_true",
                   help="force the asyncio relay (compat path: serves "
                        "JSON-ops legacy clients the native loop refuses)")
    args = p.parse_args()
    if args.shard_dir is None and not args.core_port:
        p.error("--core-port is required without --shard-dir")
    if not args.python and args.shard_dir is None:
        # default: the C++ epoll relay (native/gateway.cpp) — zero
        # Python on the hot path (VERDICT r4 #3, SURVEY §2.9). Falls
        # back to asyncio if the toolchain can't build it.
        try:
            from ..native.build import NativeUnavailable
            from ..native.gateway import NativeGateway

            try:
                ng = NativeGateway(args.core_host, args.core_port,
                                   host=args.host, port=args.port)
            except NativeUnavailable:
                ng = None
        except Exception:
            ng = None
        if ng is not None:
            print(f"LISTENING {args.host}:{ng.port}", flush=True)
            raise SystemExit(0 if ng.run() == 0 else 1)
    # relay path allocates acyclic graphs only; cycle-collector pauses
    # would land directly on forwarded-frame latency (see front_end main)
    gc.freeze()
    gc.disable()
    Gateway(args.core_host, args.core_port,
            host=args.host, port=args.port,
            shard_dir=args.shard_dir, shards=args.shards).serve_forever()


if __name__ == "__main__":
    main()
