"""Cold-start rehydration plane: bounded lazy doc boots for a cold core.

After a full-cluster crash a core inherits a partition space of maybe
10k docs. Two rules keep recovery O(what's asked for):

- **Lazy**: claiming a partition builds NO per-doc pipeline. The first
  route to a doc boots it from the latest acked summary + the durable
  log tail (local_orderer's lazy plan); docs nobody asks for cost
  nothing. ``boot.part.lazy`` / ``boot.part.full_replay`` witness that
  the whole-log-replay count is zero.
- **Bounded**: a boot *storm* (thousands of first-routes at once) must
  not hold connects hostage behind pipeline construction. The
  :class:`RehydrationExecutor` is a token bucket (the PR 7 admission
  primitive) on boots per core: excess first-routes park with
  :class:`BootPending` — surfaced as a ``boot_pending`` nack the driver
  retries on the shed-retry lane — while warm docs' acks stay flat.
"""

from __future__ import annotations

import os
import time
from typing import Callable

from ..obs.metrics import tier_counters
from .admission import TokenBucket, retry_after_ms

_counters = None


def boot_counters():
    """The boot-plane counter sheet. One frontend-tier instance per
    process (tier_snapshot("frontend") folds it into admin_boot_status
    next to the front end's own sheet)."""
    global _counters
    if _counters is None:
        _counters = tier_counters("frontend")
    return _counters


class BootPending(RuntimeError):
    """First route to a cold doc parked by the rehydration executor: the
    caller retries after ``retry_after_ms`` instead of timing out a
    connect held hostage by a boot storm."""

    def __init__(self, retry_after: int):
        super().__init__(
            f"doc boot parked by cold-start admission; retry in "
            f"{retry_after}ms")
        self.retry_after_ms = retry_after


class RehydrationExecutor:
    """Per-core cap on doc-boot admissions (rate + burst).

    Boots run ON the core's event loop (pipeline construction is
    single-threaded by design), so the bucket bounds how much of each
    loop interval the storm may consume: between admitted boots the
    loop keeps serving warm-doc submits and acks. Parked first-routes
    carry a jittered retry-after, the same contract as overload
    shedding.
    """

    def __init__(self, boots_per_s: float = 200.0, burst: int = 32,
                 clock: Callable[[], float] = time.monotonic):
        self.bucket = TokenBucket(rate=boots_per_s, burst=burst)
        self._clock = clock
        self.booted = 0
        self.parked = 0
        # chaos seam: die (kill -9-shaped, no cleanup) after admitting N
        # boots — the drill's crash-mid-rehydration window. Env-armed so
        # subprocess cores can be told to crash from the outside.
        crash = os.environ.get("FLUID_CHAOS_BOOT_CRASH")
        self.crash_after = int(crash) if crash else None

    def admit(self, tenant_id: str, document_id: str) -> None:
        """Take a boot slot or raise :class:`BootPending`."""
        wait = self.bucket.take(1.0, self._clock())
        if wait > 0.0:
            self.parked += 1
            boot_counters().inc("boot.part.parked")
            raise BootPending(retry_after_ms(wait))
        self.booted += 1
        if self.crash_after is not None and self.booted >= self.crash_after:
            os._exit(9)  # the crash seam: mid-storm, boots in flight

    def status(self) -> dict:
        """Operator view (admin placement boot)."""
        return {
            "booted": self.booted,
            "parked": self.parked,
            "rate": self.bucket.rate,
            "burst": self.bucket.burst,
            "tokens": round(self.bucket.tokens, 3),
        }
