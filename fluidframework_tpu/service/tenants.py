"""Tenancy + token auth at the front door (the riddler role).

Ref: server/routerlicious/packages/routerlicious/src/riddler
(tenantManager.ts — tenant registry + per-tenant shared secret) and
protocol-definitions/src/tokens.ts (ITokenClaims: tenantId, documentId,
scopes, user, exp — a JWT signed with the tenant secret).

Tokens here are the same shape, HMAC-SHA256-signed compact JWS
(header.payload.signature, base64url) produced with the standard library
— no external JWT dependency. An empty registry means OPEN access (the
tinylicious/dev mode); registering any tenant turns enforcement on for
that tenant id.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from typing import Optional

SCOPE_READ = "doc:read"
SCOPE_WRITE = "doc:write"
DEFAULT_SCOPES = (SCOPE_READ, SCOPE_WRITE)


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


class AuthError(Exception):
    """Token rejected: the front door refuses the connection."""


def sign_token(tenant_id: str, document_id: str, secret: str,
               user: Optional[dict] = None,
               scopes: tuple = DEFAULT_SCOPES,
               lifetime_s: float = 3600.0) -> str:
    """Client-side token mint (the reference's TokenProvider role)."""
    header = {"alg": "HS256", "typ": "JWT"}
    claims = {
        "tenantId": tenant_id,
        "documentId": document_id,
        "scopes": list(scopes),
        "user": user or {"id": "anonymous"},
        "iat": int(time.time()),
        "exp": int(time.time() + lifetime_s),
    }
    signing_input = (_b64(json.dumps(header, separators=(",", ":")).encode())
                     + "."
                     + _b64(json.dumps(claims, separators=(",", ":")).encode()))
    sig = hmac.new(secret.encode(), signing_input.encode(),
                   hashlib.sha256).digest()
    return f"{signing_input}.{_b64(sig)}"


class TenantManager:
    """Tenant registry + token validation (riddler's tenantManager)."""

    def __init__(self):
        self._secrets: dict[str, str] = {}
        # admission budgets, tenant -> (ops_per_s, burst). Deliberately
        # separate from _secrets: a rate cap must not flip `enforcing`
        # (auth) on, and the shard-host tenants.json sync persists
        # secrets only.
        self._rates: dict[str, tuple[float, float]] = {}

    def register(self, tenant_id: str, secret: str) -> None:
        self._secrets[tenant_id] = secret

    def set_rate(self, tenant_id: str, ops_per_s: float,
                 burst: Optional[float] = None) -> None:
        """Cap a tenant's admission rate; ``ops_per_s <= 0`` clears it.

        Tenants without a rate stay unlimited (the default), so
        configuring one noisy tenant never touches the rest."""
        if ops_per_s <= 0:
            self._rates.pop(tenant_id, None)
            return
        self._rates[tenant_id] = (
            float(ops_per_s),
            float(burst) if burst is not None else max(float(ops_per_s), 1.0))

    def rate_for(self, tenant_id: str) -> Optional[tuple[float, float]]:
        """(ops_per_s, burst) for the tenant, or None = unlimited."""
        return self._rates.get(tenant_id)

    def remove(self, tenant_id: str) -> bool:
        """Deregister a tenant; its tokens stop validating immediately."""
        return self._secrets.pop(tenant_id, None) is not None

    def list_tenants(self) -> list[str]:
        return sorted(self._secrets)

    def replace_all(self, secrets: dict) -> None:
        """Swap the whole registry in place (shared-registry reload:
        every server holding this instance sees the change at once)."""
        self._secrets.clear()
        self._secrets.update(secrets)

    @property
    def enforcing(self) -> bool:
        return bool(self._secrets)

    def validate(self, token: Optional[str], tenant_id: str,
                 document_id: str,
                 required_scope: str = SCOPE_WRITE) -> dict:
        """Return the verified claims, or raise AuthError.

        Unregistered tenants are refused outright once ANY tenant is
        registered (an open tenant next to secured ones would be a
        bypass); with an empty registry everything is open (dev mode).
        """
        if not self.enforcing:
            return {"tenantId": tenant_id, "documentId": document_id,
                    "scopes": list(DEFAULT_SCOPES)}
        secret = self._secrets.get(tenant_id)
        if secret is None:
            raise AuthError(f"unknown tenant {tenant_id!r}")
        if not token:
            raise AuthError("missing token")
        try:
            signing_input, _, sig_part = token.rpartition(".")
            want = hmac.new(secret.encode(), signing_input.encode(),
                            hashlib.sha256).digest()
            if not hmac.compare_digest(want, _unb64(sig_part)):
                raise AuthError("bad signature")
            claims = json.loads(_unb64(signing_input.split(".")[1]))
        except AuthError:
            raise
        except Exception as e:  # malformed structure/base64/json
            raise AuthError(f"malformed token: {e}") from None
        if claims.get("tenantId") != tenant_id:
            raise AuthError("token tenant mismatch")
        if claims.get("documentId") != document_id:
            raise AuthError("token document mismatch")
        if claims.get("exp", 0) < time.time():
            raise AuthError("token expired")
        if required_scope not in claims.get("scopes", []):
            raise AuthError(f"missing scope {required_scope!r}")
        return claims
