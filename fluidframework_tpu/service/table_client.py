"""Networked placement plane: the ``TableClient`` local/remote split.

Ref: memory-orderer/src/reservationManager.ts — the reference's lease
reservations live in Mongo, a NETWORK service, so any orderer node on
any machine can take one. Our ``PlacementDir``/``EpochTable`` pair is
strictly stronger on one box (flock-serialized claims, monotone global
epoch) but both assume a shared filesystem. This module splits every
consumer onto a ``TableClient`` interface with two implementations:

- :class:`LocalTableClient` — binds the raw flock-backed
  ``PlacementDir`` + ``EpochTable`` objects as-is. ZERO indirection:
  ``client.leases`` IS the ``PlacementDir`` and ``client.table`` IS the
  ``EpochTable``, so the single-host hot path pays nothing for the
  split (the knee A/B acceptance gate).
- :class:`RemoteTableClient` — RPC proxies speaking the
  ``admin_table_*`` frame family against the **table door**
  (:class:`TableDoorService`, served next to the storage tier on the
  placement host). Every WRITE still lands under the placement host's
  flock, so the monotone-epoch and 3-layer-fencing proofs carry
  verbatim: remote hosts changed the transport, not the serialization
  point.

Cache coherence for remote readers is the same epoch-gated protocol
``RoutingCache`` already uses for ``fplacement`` pushes: the remote
table proxy serves reads from a short-lived snapshot
(``placement.table.cache_hits``) and drops it the moment a newer epoch
is observed (``note_epoch``) — an older snapshot can never veto a newer
route, it can only cost one extra RPC.

Counters (locked in fluidlint's ``placement.`` family):
``placement.table.rpc_reads`` / ``rpc_writes`` — door round trips;
``placement.table.cache_hits`` — remote reads served from the snapshot;
``placement.table.stale_rejections`` — remote writes the door's fence
refused (a zombie ex-owner writing through yesterday's claim).
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Optional

from ..utils.affinity import any_thread, blocking
from .placement import DEFAULT_TTL_S, PlacementDir
from .placement_plane import EpochTable, placement_counters

#: every table-door frame name starts with this (routed by the storage
#: process's dispatcher next to the blob/ref RPCs)
TABLE_FRAME_PREFIX = "admin_table_"

#: how long a remote snapshot serves reads before re-RPCing; well under
#: the lease TTL so liveness decisions never ride a stale snapshot
SNAP_TTL_S = 0.25


class TableFenceError(RuntimeError):
    """The table door refused a write: the caller's lease claim is no
    longer the one on file (zombie ex-owner) — counted client-side as
    ``placement.table.stale_rejections``."""


# --------------------------------------------------------------- clients


class LocalTableClient:
    """Single-host (shared-filesystem) placement plane: the raw objects.

    ``leases``/``table`` are the unwrapped ``PlacementDir``/``EpochTable``
    so every existing call site, lock marker, and perf characteristic is
    byte-for-byte what it was before the split.
    """

    remote = False

    def __init__(self, shard_dir: str, n_partitions: int,
                 ttl_s: float = DEFAULT_TTL_S, counters=None):
        import os

        self.leases = PlacementDir(
            os.path.join(shard_dir, "placement"), n_partitions, ttl_s)
        self.table = EpochTable.for_shard_dir(shard_dir, counters=counters)


class RemoteTableClient:
    """Placement plane over the wire: proxies against the table door."""

    remote = True

    def __init__(self, addr: str, n_partitions: int,
                 ttl_s: float = DEFAULT_TTL_S, counters=None,
                 timeout: float = 10.0):
        host, _, port_s = addr.rpartition(":")
        self._chan = _DoorChannel(host or "127.0.0.1", int(port_s),
                                  timeout=timeout)
        c = counters if counters is not None else placement_counters()
        self.table = RemoteEpochTable(self._chan, c)
        self.leases = RemoteLeaseDir(self._chan, n_partitions, ttl_s,
                                     self.table, c)

    def close(self) -> None:
        self._chan.close()


class _DoorChannel:
    """One persistent framed-JSON connection to the table door, shared
    by both proxies (lock-serialized call/response; reconnects once on a
    broken pipe — the door is stateless per frame, so a retried frame is
    safe: every write is idempotent-keyed by owner/epoch semantics)."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.host, self.port, self.timeout = host, port, timeout
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._rid = 0

    @blocking("synchronous table-door dial + rid round trip — remote "
              "placement reads/writes run on the lease poll executor or "
              "a ticker, never the loop")
    def call(self, frame: dict) -> dict:
        with self._lock:
            for attempt in (0, 1):
                try:
                    return self._call_locked(frame)
                except (OSError, ConnectionError):
                    self._drop_locked()
                    if attempt:
                        raise
        raise ConnectionError("unreachable")  # pragma: no cover

    def _call_locked(self, frame: dict) -> dict:
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout)
        self._rid += 1
        rid = self._rid
        body = json.dumps(dict(frame, rid=rid)).encode()
        self._sock.sendall(len(body).to_bytes(4, "big") + body)

        def read_exactly(n: int) -> bytes:
            buf = b""
            while len(buf) < n:
                chunk = self._sock.recv(n - len(buf))
                if not chunk:
                    raise ConnectionError("closed")
                buf += chunk
            return buf

        while True:
            n = int.from_bytes(read_exactly(4), "big")
            reply = json.loads(read_exactly(n).decode())
            if reply.get("rid") != rid:
                continue
            if reply.get("t") == "error":
                raise RuntimeError(reply.get("message"))
            return reply

    def _drop_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._drop_locked()


class RemoteEpochTable:
    """``EpochTable`` surface over ``admin_table_*`` frames.

    Reads serve from an epoch-gated snapshot no older than
    ``SNAP_TTL_S``; writes invalidate it (our own write bumped the
    epoch) and every fence rejection raises :class:`TableFenceError`
    after counting ``placement.table.stale_rejections`` — the zombie
    never mistakes a refusal for a transport error.
    """

    def __init__(self, chan: _DoorChannel, counters):
        self._chan = chan
        self.counters = counters
        self._snap: Optional[dict] = None
        self._snap_t = 0.0
        self._snap_epoch = -1

    # ------------------------------------------------------------ readers

    def read(self) -> dict:
        now = time.monotonic()
        if self._snap is not None and now - self._snap_t < SNAP_TTL_S:
            self.counters.inc("placement.table.cache_hits")
            return self._snap
        self.counters.inc("placement.table.rpc_reads")
        rec = self._chan.call({"t": "admin_table_read"})["rec"]
        self._snap, self._snap_t = rec, now
        self._snap_epoch = rec.get("epoch", 0)
        return rec

    def global_epoch(self) -> int:
        return self.read()["epoch"]

    def epoch_of(self, k: int) -> int:
        part = self.read()["parts"].get(str(k))
        return part["epoch"] if part else 0

    def addr_of(self, k: int) -> Optional[str]:
        part = self.read()["parts"].get(str(k))
        return part["addr"] if part else None

    def part_epochs(self) -> dict:
        return {int(k): p["epoch"]
                for k, p in self.read()["parts"].items()}

    def cores(self) -> dict:
        return self.read().get("cores", {})

    def core_state(self, owner: str) -> Optional[str]:
        row = self.cores().get(owner)
        return row["state"] if row else None

    @any_thread
    def note_epoch(self, epoch: int) -> None:
        """Coherence push: a peer told us the table reached ``epoch``
        (an ``fplacement`` frame, a migration reply). A snapshot older
        than that is dead — drop it so the next read re-RPCs."""
        if epoch > self._snap_epoch:
            self._snap = None
            self._snap_epoch = epoch

    def _invalidate(self) -> None:
        self._snap = None

    # ------------------------------------------------------------ writers

    def _write(self, frame: dict) -> dict:
        self.counters.inc("placement.table.rpc_writes")
        self._invalidate()
        reply = self._chan.call(frame)
        if reply.get("t") == "table_reject":
            self.counters.inc("placement.table.stale_rejections")
            raise TableFenceError(
                reply.get("reason", "rejected by table door fence"))
        return reply

    def record_claim(self, k: int, owner: str, addr: str,
                     cause: Optional[str] = None) -> int:
        return self._write({"t": "admin_table_record_claim", "k": k,
                            "owner": owner, "addr": addr,
                            "cause": cause})["epoch"]

    def record_release(self, k: int, owner: str,
                       cause: Optional[str] = None) -> Optional[int]:
        return self._write({"t": "admin_table_record_release", "k": k,
                            "owner": owner, "cause": cause})["epoch"]

    def record_core(self, owner: str, addr: str,
                    host: Optional[str] = None) -> None:
        self._write({"t": "admin_table_record_core", "owner": owner,
                     "addr": addr, "host": host})

    def set_core_state(self, owner: str, state: str,
                       cause: Optional[str] = None) -> bool:
        return self._write({"t": "admin_table_set_core_state",
                            "owner": owner, "state": state,
                            "cause": cause})["ok"]

    def remove_core(self, owner: str,
                    cause: Optional[str] = None) -> None:
        self._write({"t": "admin_table_remove_core", "owner": owner,
                     "cause": cause})


class RemoteLeaseDir:
    """``PlacementDir`` surface over ``admin_table_*`` frames. The flock
    critical sections run door-side, so two racing remote claimants
    serialize exactly like two local ones."""

    def __init__(self, chan: _DoorChannel, n_partitions: int,
                 ttl_s: float, table: RemoteEpochTable, counters):
        self._chan = chan
        self.n = n_partitions
        self.ttl_s = ttl_s
        self._table = table
        self.counters = counters

    def _call(self, frame: dict, write: bool = True) -> dict:
        self.counters.inc("placement.table.rpc_writes" if write
                          else "placement.table.rpc_reads")
        if write:
            self._table._invalidate()
        reply = self._chan.call(frame)
        if reply.get("t") == "table_reject":
            self.counters.inc("placement.table.stale_rejections")
            raise TableFenceError(
                reply.get("reason", "rejected by table door fence"))
        return reply

    def try_claim(self, k: int, owner_id: str, address: str) -> bool:
        return self._call({"t": "admin_table_try_claim", "k": k,
                           "owner": owner_id, "addr": address})["ok"]

    def heartbeat(self, k: int, owner_id: str) -> bool:
        return self._call({"t": "admin_table_heartbeat", "k": k,
                           "owner": owner_id})["ok"]

    def transfer(self, k: int, from_owner: str, to_owner: str,
                 to_address: str) -> bool:
        return self._call({"t": "admin_table_transfer", "k": k,
                           "from_owner": from_owner, "to_owner": to_owner,
                           "to_addr": to_address})["ok"]

    def release(self, k: int, owner_id: str) -> None:
        self._call({"t": "admin_table_release", "k": k,
                    "owner": owner_id})

    def owner_of(self, k: int) -> Optional[str]:
        return self._call({"t": "admin_table_owner_of", "k": k},
                          write=False)["addr"]

    def table(self) -> dict:
        raw = self._call({"t": "admin_table_lease_table"},
                         write=False)["table"]
        return {int(k): v for k, v in raw.items()}


# ------------------------------------------------------------------ door


class TableDoorService:
    """The placement host's table door: ``admin_table_*`` dispatch over
    the REAL flock-backed lease dir + epoch table.

    Served by the storage process (``storage_server --table-dir``) so
    multi-host fleets need exactly one extra socket, not one extra
    process. Every write runs the same flocked critical section the
    local client runs — one serialization point for local cores (direct
    flock) and remote cores (RPC into this door's flock) alike.

    The door adds ONE check the local path never needed: a
    ``record_claim`` whose claimed owner no longer matches the lease on
    file is refused (``table_reject``). Locally a zombie discovers the
    takeover on its next heartbeat; remotely the door is the last line
    before an epoch bump, and a refusal here is observable
    (``placement.table.stale_rejections``) instead of being a silent
    wrong-owner route.
    """

    def __init__(self, shard_dir: str, n_partitions: int,
                 ttl_s: float = DEFAULT_TTL_S):
        import os

        self.leases = PlacementDir(
            os.path.join(shard_dir, "placement"), n_partitions, ttl_s)
        self.table = EpochTable.for_shard_dir(shard_dir)

    def handle(self, frame: dict) -> dict:
        t = frame.get("t", "")
        k = frame.get("k")
        owner = frame.get("owner")
        if t == "admin_table_read":
            return {"t": "table_rec", "rec": self.table.read()}
        if t == "admin_table_ping":
            return {"t": "table_pong", "shards": self.leases.n,
                    "ttl_s": self.leases.ttl_s}
        if t == "admin_table_try_claim":
            return {"t": "ok", "ok": self.leases.try_claim(
                int(k), owner, frame["addr"])}
        if t == "admin_table_heartbeat":
            return {"t": "ok", "ok": self.leases.heartbeat(int(k), owner)}
        if t == "admin_table_transfer":
            return {"t": "ok", "ok": self.leases.transfer(
                int(k), frame["from_owner"], frame["to_owner"],
                frame["to_addr"])}
        if t == "admin_table_release":
            self.leases.release(int(k), owner)
            return {"t": "ok", "ok": True}
        if t == "admin_table_owner_of":
            return {"t": "addr", "addr": self.leases.owner_of(int(k))}
        if t == "admin_table_lease_table":
            return {"t": "lease_table",
                    "table": {str(kk): v
                              for kk, v in self.leases.table().items()}}
        if t == "admin_table_record_claim":
            # the door-side fence: the epoch bump is reserved for the
            # owner the LEASE names — a zombie whose lease was taken
            # over cannot re-route the partition through the door
            cur = self.leases._read(int(k))
            if cur is None or cur.get("owner") != owner:
                return {"t": "table_reject",
                        "reason": f"lease for part {k} not held by "
                                  f"{owner}"}
            epoch = self.table.record_claim(int(k), owner, frame["addr"],
                                            cause=frame.get("cause"))
            return {"t": "epoch", "epoch": epoch}
        if t == "admin_table_record_release":
            epoch = self.table.record_release(int(k), owner,
                                              cause=frame.get("cause"))
            return {"t": "epoch", "epoch": epoch}
        if t == "admin_table_record_core":
            self.table.record_core(owner, frame["addr"],
                                   host=frame.get("host"))
            return {"t": "ok", "ok": True}
        if t == "admin_table_set_core_state":
            return {"t": "ok", "ok": self.table.set_core_state(
                owner, frame["state"], cause=frame.get("cause"))}
        if t == "admin_table_remove_core":
            self.table.remove_core(owner, cause=frame.get("cause"))
            return {"t": "ok", "ok": True}
        raise ValueError(f"unknown table rpc {t!r}")
