"""Per-stage service processes over the shared durable log.

Ref: the reference deploys each pipeline lambda as its own service
process connected only by the Kafka log — alfred/deli/scribe/…
each have a www.ts entrypoint run by the kafka-service runner
(server/routerlicious/packages/routerlicious/src/*/www.ts,
lambdas-driver/src/kafka-service/runner.ts:13, docker-compose.yml).

Here the shared medium is the native C++ op log (native/oplog.cpp): the
CORE process (front_end.py with ``--log-dir``) is the single writer of
the rawops/deltas topics and flushes appends into the page cache; each
stage process opens the same directory READ-ONLY and tails it
(DurableLog.poll). Stage → core communication rides the stage's own
writable log directory (its "backchannel"), which the core polls — every
topic keeps exactly one writer, so no cross-process file locking exists
anywhere.

Stages:

- ``scribe``  — the summary validator/acker (ScribeLambda) out of
  process. Consumes deltas + upload announcements; emits summary
  ack/nack raw messages, version commits, and retention advances on the
  backchannel. Checkpoints its protocol replica + offsets to its own
  log; kill -9 and restart resumes from the checkpoint (deltas replay is
  idempotent by sequence number).
- ``applier`` — the TPU device farm (TpuDocumentApplier) out of
  process: the deli/broadcast hot path never shares a GIL with device
  work. Consumes deltas chanops, checkpoints the device farm
  (save_applier_checkpoint) periodically, and reports per-doc applied
  seqs on its backchannel as status records.

Deployment:

    python -m fluidframework_tpu.service.stage_runner \
        --stage scribe --log-dir LOG --state-dir STATE

The core consumes STATE with ``front_end --consume-backchannel STATE``.
"""

from __future__ import annotations

import argparse
import os
import signal
import time
from typing import Optional

from ..protocol.messages import MessageType
from .core import InMemoryDb, summary_versions_collection
from .durable_log import DurableLog

BACKCHANNEL_TOPIC = "backchannel"
POLL_INTERVAL_S = 0.002


def _doc_of(topic: str) -> tuple[str, str]:
    _, tenant, doc = topic.split("/", 2)
    return tenant, doc


def doc_partition(tenant: str, doc: str, n_partitions: int) -> int:
    """Stable doc → partition map (ref: the Kafka partition-by-docId
    routing, lambdas-driver document-router). md5, NOT hash(): python
    randomizes hash() per process, and every stage process must agree."""
    import hashlib

    digest = hashlib.md5(f"{tenant}/{doc}".encode()).digest()
    return int.from_bytes(digest[:4], "big") % n_partitions


class _StageHostBase:
    """Discovery + poll/drain/checkpoint loop shared by the stages."""

    #: deltas topics are the stage input; uploads only matter to scribe
    topic_prefixes = ("deltas/",)

    #: chaos seam (fluidframework_tpu/chaos): crash-window faults. The
    #: plane raises SimulatedCrash from inside the checkpoint sequence —
    #: between consume and farm save ("stage.pre_checkpoint") or between
    #: farm save and the offset/emit records ("stage.post_checkpoint") —
    #: the two windows whose replay/idempotency story must hold on a real
    #: kill -9. None = disarmed, one branch per checkpoint.
    fault_plane = None

    def _fault(self, point: str, **ctx) -> None:
        if self.fault_plane is not None:
            self.fault_plane(point, stage=type(self).__name__, **ctx)

    def __init__(self, log_dir: str, state_dir: str,
                 partition: Optional[tuple] = None):
        self.shared = DurableLog(log_dir, readonly=True)
        self.state = DurableLog(state_dir)
        # (k, n): this process owns docs with doc_partition(...) == k —
        # N stage processes split the doc space; a redeploy with a
        # different split MOVES docs between processes (the new owner
        # resumes from its checkpoints, or replays from 0 for a doc it
        # never owned)
        self.partition = partition
        self._known: set[str] = set()
        self._last_checkpoint = time.monotonic()
        self.checkpoint_every_s = 1.0

    def _owns(self, topic: str) -> bool:
        if self.partition is None:
            return True
        tenant, doc = _doc_of(topic)
        k, n = self.partition
        return doc_partition(tenant, doc, n) == k

    # ------------------------------------------------------------- plumbing

    def emit(self, record: dict) -> None:
        self.state.append(BACKCHANNEL_TOPIC, record)

    def _cp_topic(self, tenant: str, doc: str) -> str:
        return f"cp/{tenant}/{doc}"

    def load_checkpoint(self, tenant: str, doc: str) -> Optional[dict]:
        topic = self._cp_topic(tenant, doc)
        n = self.state.length(topic)
        return self.state.read(topic, n - 1) if n > 0 else None

    def save_checkpoint(self, tenant: str, doc: str, state: dict) -> None:
        self.state.append(self._cp_topic(tenant, doc), state)

    def discover(self) -> None:
        for prefix in self.topic_prefixes:
            for topic in self.shared.list_topics(prefix):
                if topic not in self._known:
                    self._known.add(topic)
                    if self._owns(topic):
                        self.attach(topic)

    # -------------------------------------------------- deterministic ctl
    # Cross-process stepping (VERDICT r4 #9 — the OpProcessingController
    # role, opProcessingController.ts:16, extended across the process
    # boundary): a controller writes ``<state_dir>/ctl.json`` with
    # {"mode": "pause"|"run", "steps": N} and this stage consumes AT
    # MOST N records total while paused — so a composition bug
    # reproduces op-by-op, each step observable through the backchannel.

    def _read_ctl(self) -> None:
        import json
        import os

        path = os.path.join(self.state.directory, "ctl.json")
        try:
            mtime = os.stat(path).st_mtime_ns
        except OSError:
            return
        if mtime == self._ctl_mtime:
            return
        self._ctl_mtime = mtime
        try:
            with open(path) as f:
                self._ctl = json.load(f)
        except (OSError, ValueError):
            pass

    def _step_once(self) -> bool:
        """Deliver exactly ONE pending record (first lagging topic in
        subscription order). Returns False when fully drained."""
        for topic in list(self.shared._order):
            if self.shared.step(topic):
                return True
        return False

    def run_forever(self) -> None:
        print("READY", flush=True)
        last_discover = 0.0
        self._ctl = {"mode": "run"}
        self._ctl_mtime = None
        self._steps_done = 0
        while True:
            now = time.monotonic()
            if now - last_discover >= 0.25:  # listdir is not free at 2ms
                last_discover = now
                self.discover()
                self._read_ctl()
            moved = self.shared.poll()
            if self._ctl.get("mode") != "pause":
                # leaving (or never entering) a pause episode resets the
                # step ledger: each pause session's budget counts from 0,
                # not from the lifetime total of earlier sessions
                self._steps_done = 0
            if self._ctl.get("mode") == "pause":
                self._read_ctl()
                budget = int(self._ctl.get("steps", 0))
                stepped = False
                while self._steps_done < budget and self._step_once():
                    self._steps_done += 1
                    stepped = True
                if stepped:
                    self.checkpoint()
                    self.state.flush()
                time.sleep(0.01)
                continue
            if moved:
                self.shared.drain()
            now = time.monotonic()
            if now - self._last_checkpoint >= self.checkpoint_every_s:
                self._last_checkpoint = now
                self.checkpoint()
            self.state.flush()
            if not moved:
                time.sleep(POLL_INTERVAL_S)

    def run_once(self) -> bool:
        """ONE deterministic iteration of the run_forever loop body:
        discover, poll, drain, checkpoint, flush. Lets a driver (the
        chaos soak, a test) step a stage in-process and catch a
        SimulatedCrash exactly at the armed window. Returns whether the
        poll found new records."""
        self.discover()
        moved = self.shared.poll()
        if moved:
            self.shared.drain()
        self.checkpoint()
        self.state.flush()
        return moved

    # ------------------------------------------------------------ per-stage

    def attach(self, topic: str) -> None:
        raise NotImplementedError

    def checkpoint(self) -> None:
        pass


class ScribeStage(_StageHostBase):
    """ScribeLambda per doc, out of process (scribe/lambda.ts role)."""

    # uploads BEFORE deltas: an upload announcement always precedes its
    # SUMMARIZE op on disk (the core appends + flushes it during the
    # storage RPC, before the client can submit), and poll marks dirty /
    # drain delivers in SUBSCRIPTION order — so as long as the doc's
    # uploads topic is subscribed before its deltas topic, validation
    # never sees a summarize whose upload record it hasn't ingested.
    # attach() enforces that order by eagerly subscribing the uploads
    # topic when the deltas topic appears (the uploads topic is usually
    # created on disk much later — first upload — and discovery alone
    # would subscribe it AFTER deltas, racing any summarize that lands
    # in the same poll window as its upload: round-5 flake fix)
    topic_prefixes = ("uploads/", "deltas/")

    def __init__(self, log_dir: str, state_dir: str,
                 partition=None):
        super().__init__(log_dir, state_dir, partition=partition)
        self.db = InMemoryDb()
        self.scribes: dict[str, object] = {}  # "tenant/doc" → ScribeLambda

    def _scribe_for(self, tenant: str, doc: str):
        from .scribe import ScribeLambda

        key = f"{tenant}/{doc}"
        scribe = self.scribes.get(key)
        if scribe is None:
            cp = self.load_checkpoint(tenant, doc)

            def send_raw(raw, tenant=tenant, doc=doc):
                # summary ack/nack → core orders it into the stream
                self.emit({"kind": "raw", "tenant": tenant, "doc": doc,
                           "raw": raw})

            def persist_version(handle, version, tenant=tenant, doc=doc):
                self.emit({"kind": "version", "tenant": tenant, "doc": doc,
                           "handle": handle, "version": dict(version)})

            def on_committed(capture_seq, tenant=tenant, doc=doc):
                self.emit({"kind": "retention", "tenant": tenant,
                           "doc": doc, "capture_seq": capture_seq})

            scribe = self.scribes[key] = ScribeLambda(
                tenant, doc, self.db,
                send_to_deli=send_raw,
                checkpoint=cp["scribe"] if cp else None,
                on_summary_committed=on_committed,
                persist_version=persist_version,
            )
        return scribe

    def attach(self, topic: str) -> None:
        tenant, doc = _doc_of(topic)
        scribe = self._scribe_for(tenant, doc)
        if topic.startswith("deltas/"):
            # subscribe the doc's uploads topic FIRST (see class comment)
            up_topic = f"uploads/{tenant}/{doc}"
            if up_topic not in self._known:
                self._known.add(up_topic)
                self.attach(up_topic)
            cp = self.load_checkpoint(tenant, doc)
            start = cp["deltas_offset"] + 1 if cp else 0
            self.shared.subscribe(topic, scribe.handler, from_offset=start)
        else:  # uploads/: version records announced by the core

            def on_upload(message, col=summary_versions_collection(
                    tenant, doc)):
                rec = message.value
                self.db.upsert(col, rec["version_id"], dict(rec["record"]))

            self.shared.subscribe(topic, on_upload, from_offset=0)

    def checkpoint(self) -> None:
        # crash window: records consumed, checkpoint not yet written —
        # a restart replays the window (scribe replay is seq-idempotent)
        self._fault("stage.pre_checkpoint")
        for key, scribe in self.scribes.items():
            tenant, doc = key.split("/", 1)
            self.save_checkpoint(tenant, doc, {
                "scribe": scribe.checkpoint_state(),
                "deltas_offset": scribe.last_offset,
            })


class ApplierStage(_StageHostBase):
    """TpuDocumentApplier out of process: device work off the core GIL."""

    def __init__(self, log_dir: str, state_dir: str,
                 max_docs: int = 64, max_slots: int = 256,
                 ds_id: str = "default", channel_id: str = "text",
                 partition=None):
        super().__init__(log_dir, state_dir, partition=partition)
        from .tpu_applier import TpuDocumentApplier, load_applier_checkpoint

        self.ds_id, self.channel_id = ds_id, channel_id
        ckpt = os.path.join(state_dir, "applier")
        if os.path.exists(ckpt + ".json"):
            self.applier = load_applier_checkpoint(ckpt)
        else:
            self.applier = TpuDocumentApplier(max_docs=max_docs,
                                              max_slots=max_slots)
        self.applier.set_replay_source(lambda t, d: [])
        self._ckpt_path = ckpt
        self._offsets: dict[str, int] = {}
        # highest sequence number CONSUMED per topic (the consumer-group
        # offset semantic): the stream tail includes messages the applier
        # skips (joins, summarize/ack, other channels), and "caught up"
        # must mean consumed-through-tail, not merely
        # last-applicable-op-applied — otherwise a stream ending in a
        # summary ack reads as forever lagging
        self._watermarks: dict[str, int] = {}

    def attach(self, topic: str) -> None:
        tenant, doc = _doc_of(topic)
        cp = self.load_checkpoint(tenant, doc)
        start = cp["offset"] + 1 if cp else 0

        def on_deltas(message, tenant=tenant, doc=doc, topic=topic):
            self._offsets[topic] = message.offset
            value = message.value
            abatch = value.get("abatch")
            if abatch is not None:
                self._watermarks[topic] = max(
                    self._watermarks.get(topic, 0), abatch.last_seq)
                if abatch.last_seq > self.applier.applied_seq(tenant, doc):
                    self.applier.ingest_array_batch(tenant, doc, abatch)
                return
            batch = value.get("boxcar")
            msgs = batch if batch is not None else [value["message"]]
            self._watermarks[topic] = max(
                self._watermarks.get(topic, 0),
                msgs[-1].sequence_number)
            # replay idempotency: the farm checkpoint is saved BEFORE
            # the offset checkpoints, so a crash in between replays a
            # window of already-applied ops — skip by sequence number
            # (double-applying an insert would corrupt the doc)
            applied = self.applier.applied_seq(tenant, doc)
            pairs = []
            for m in msgs:
                if m.sequence_number <= applied:
                    continue
                if m.type is not MessageType.OPERATION:
                    continue
                env = m.contents
                if type(env) is not dict or env.get("kind") != "chanop" \
                        or env.get("address") != self.ds_id:
                    continue
                inner = env["contents"]
                if inner.get("address") != self.channel_id \
                        or "attach" in inner:
                    continue
                pairs.append((m, inner["contents"]))
            if pairs:
                self.applier.ingest_batch(tenant, doc, pairs)

        self.shared.subscribe(topic, on_deltas, from_offset=start)

    def checkpoint(self) -> None:
        from .tpu_applier import save_applier_checkpoint

        # crash window 1: deltas consumed into the farm, nothing saved —
        # a restart resumes from the OLD offsets and replays the window
        # (ingest skips by sequence number)
        self._fault("stage.pre_checkpoint")
        self.applier.flush()
        self.applier.finalize()
        save_applier_checkpoint(self.applier, self._ckpt_path)
        # crash window 2: the farm is saved but the offset checkpoints /
        # "applied" emits are not — the restart replays against a NEWER
        # farm, the skip-by-seq path's hardest case
        self._fault("stage.post_checkpoint")
        # thread the hoptail across the process boundary: the applier's
        # last stage/execute wall stamps ride the "applied" record so
        # the core can fold stage_to_execute into its own registry
        wave_hops = getattr(self.applier, "last_wave_hops", None)
        if wave_hops is not None:  # consume: one fold per wave
            self.applier.last_wave_hops = None
        for topic, offset in self._offsets.items():
            tenant, doc = _doc_of(topic)
            self.save_checkpoint(tenant, doc, {"offset": offset})
            rec = {"kind": "applied", "tenant": tenant, "doc": doc,
                   "applied_seq": max(
                       self._watermarks.get(topic, 0),
                       self.applier.applied_seq(tenant, doc))}
            if wave_hops is not None:
                rec["wave_hops"] = list(wave_hops)
                wave_hops = None  # one observation per wave, not per doc
            self.emit(rec)


STAGES = {"scribe": ScribeStage, "applier": ApplierStage}


def main() -> None:
    parser = argparse.ArgumentParser(description="pipeline stage process")
    parser.add_argument("--stage", choices=sorted(STAGES), required=True)
    parser.add_argument("--log-dir", required=True,
                        help="the core's durable log directory (read-only)")
    parser.add_argument("--state-dir", required=True,
                        help="this stage's own writable log directory")
    parser.add_argument("--partition", default=None, metavar="K/N",
                        help="own only docs with doc_partition == K of N "
                             "(N stage processes split the doc space)")
    args = parser.parse_args()
    signal.signal(signal.SIGTERM, lambda *a: os._exit(0))
    partition = None
    if args.partition:
        k, _, n = args.partition.partition("/")
        partition = (int(k), int(n))
    STAGES[args.stage](args.log_dir, args.state_dir,
                       partition=partition).run_forever()


if __name__ == "__main__":
    main()
