"""Copier: raw-op archival, pre-deli.

Ref: lambdas/src/copier — consumes the RAW ops topic (before any
ticketing) and archives records to the database, giving an audit/debug
trail that survives independent of deli's processing: a nacked or
misrouted submission is still findable here, which is the whole point —
the sequenced log only shows what deli ACCEPTED.
"""

from __future__ import annotations

from .core import InMemoryDb, QueuedMessage
from .deli import RawBoxcar, RawMessage


class CopierLambda:
    """Archives every raw record (message or boxcar) with its log offset."""

    def __init__(self, db: InMemoryDb, collection: str = "rawops-archive"):
        self._db = db
        self._collection = collection
        self.copied = 0

    def handler(self, message: QueuedMessage) -> None:
        raw = message.value
        if isinstance(raw, RawBoxcar):
            doc = {
                "kind": "boxcar",
                "tenant_id": raw.tenant_id,
                "document_id": raw.document_id,
                "client_id": raw.client_id,
                "count": len(raw.ops),
                "ops": [
                    {"type": op.type.value,
                     "clientSeq": op.client_sequence_number}
                    for op in raw.ops
                ],
            }
        elif isinstance(raw, RawMessage):
            doc = {
                "kind": "raw",
                "tenant_id": raw.tenant_id,
                "document_id": raw.document_id,
                "client_id": raw.client_id,
                "type": raw.operation.type.value,
                "clientSeq": raw.operation.client_sequence_number,
            }
        else:  # checkpoint records etc. on shared logs: not raw traffic
            return
        self._db.upsert(self._collection, f"{message.offset}",
                        dict(doc, offset=message.offset))
        self.copied += 1

    def archive(self, tenant_id: str, document_id: str) -> list[dict]:
        """Audit query: a doc's raw records in arrival (offset) order."""
        rows = [
            r for r in self._db.collection(self._collection).values()
            if r["tenant_id"] == tenant_id
            and r["document_id"] == document_id
        ]
        return sorted(rows, key=lambda r: r["offset"])

    def close(self) -> None:
        pass
