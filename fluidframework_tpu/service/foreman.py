"""Foreman: the server-side task broker for external workers.

Ref: lambdas/src/foreman (lambda.ts:21) + services messageSender.ts —
the reference assigns agent tasks (snapshot/intel/translation) to a pool
of external workers (Paparazzi / headless agents) over RabbitMQ, tracks
worker heartbeats, and reassigns the tasks of a dead worker
(foreman/README.md). The queueing transport here is a callable per
worker (the in-proc twin of the AMQP channel); everything else — the
registry, heartbeat expiry, at-most-one live assignment per task,
reassignment, and stale-completion rejection — is the broker logic
itself.

Relationship to runtime/agent_scheduler.py: the scheduler elects one
CLIENT of a document for a task through the data plane (consensus
register); the foreman hands work to processes that are NOT document
clients at all — the task farm.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

DEFAULT_WORKER_TIMEOUT = 30.0


@dataclass
class _Worker:
    worker_id: str
    dispatch: Callable[[dict], None]
    last_heartbeat: float
    assigned: set = field(default_factory=set)


@dataclass
class _Task:
    task_id: str
    payload: Any
    worker_id: Optional[str] = None  # current live assignment
    attempts: int = 0
    done: bool = False
    result: Any = None


class Foreman:
    def __init__(self, clock: Callable[[], float] = time.time,
                 worker_timeout: float = DEFAULT_WORKER_TIMEOUT,
                 logger=None):
        self._clock = clock
        self._timeout = worker_timeout
        self._log = logger
        self._workers: dict[str, _Worker] = {}
        self._tasks: dict[str, _Task] = {}
        self._queue: list[str] = []  # unassigned task ids, FIFO
        self._rr = itertools.cycle([])  # rebuilt on membership change
        self.reassignments = 0

    # ------------------------------------------------------------- workers

    def register_worker(self, worker_id: str,
                        dispatch: Callable[[dict], None]) -> None:
        self._workers[worker_id] = _Worker(
            worker_id, dispatch, self._clock())
        self._drain()

    def heartbeat(self, worker_id: str) -> None:
        w = self._workers.get(worker_id)
        if w is not None:
            w.last_heartbeat = self._clock()

    def check_workers(self) -> None:
        """Expire silent workers and requeue their in-flight tasks (the
        reassign-on-worker-death path, foreman/README.md)."""
        now = self._clock()
        for worker_id in [
            w.worker_id for w in self._workers.values()
            if now - w.last_heartbeat > self._timeout
        ]:
            self._drop_worker(worker_id)
        self._drain()

    def _drop_worker(self, worker_id: str) -> None:
        w = self._workers.pop(worker_id, None)
        if w is None:
            return
        if self._log is not None:
            self._log.error("worker_expired", worker_id=worker_id,
                            inflight=len(w.assigned))
        for task_id in w.assigned:
            task = self._tasks[task_id]
            task.worker_id = None
            self._queue.append(task_id)
            self.reassignments += 1

    # --------------------------------------------------------------- tasks

    def enqueue(self, task_id: str, payload: Any) -> None:
        if task_id in self._tasks and not self._tasks[task_id].done:
            return  # already queued or running
        self._tasks[task_id] = _Task(task_id, payload)
        self._queue.append(task_id)
        self._drain()

    def complete(self, worker_id: str, task_id: str, result: Any) -> bool:
        """A worker reports a result. Stale completions — from a worker
        whose assignment was revoked after heartbeat expiry — are
        REFUSED: the task may already be running elsewhere, and the
        revoked worker must not overwrite the live attempt's outcome."""
        task = self._tasks.get(task_id)
        if task is None or task.done or task.worker_id != worker_id:
            return False
        task.done = True
        task.result = result
        task.worker_id = None
        w = self._workers.get(worker_id)
        if w is not None:
            w.assigned.discard(task_id)
        return True

    def result(self, task_id: str) -> Any:
        task = self._tasks.get(task_id)
        return task.result if task is not None and task.done else None

    def pending_count(self) -> int:
        return sum(1 for t in self._tasks.values() if not t.done)

    # ------------------------------------------------------------ internal

    def _drain(self) -> None:
        """Assign queued tasks to the least-loaded live workers."""
        while self._queue and self._workers:
            task = self._tasks[self._queue.pop(0)]
            if task.done or task.worker_id is not None:
                continue
            w = min(self._workers.values(), key=lambda w: len(w.assigned))
            task.worker_id = w.worker_id
            task.attempts += 1
            w.assigned.add(task.task_id)
            w.dispatch({"task_id": task.task_id, "payload": task.payload,
                        "attempt": task.attempts})
