"""NetworkFrontEnd: the socket front door (the Alfred analog).

Ref: lambdas/src/alfred/index.ts:112-405 — the reference's front end is a
socket.io server doing the connect_document handshake (:159,:285),
submitOp ordering (:310), signal relay (:405), plus REST routes for delta
backfill and snapshot storage. Here it is one asyncio TCP server speaking
length-prefixed JSON frames, serving BOTH the live bidi op stream and the
request/response (REST-role) endpoints over the same wire format.

Frame = 4-byte big-endian length + JSON body {"t": <type>, ...}:

  client → server
    connect        {tenant, doc, details, rid}        → connected {clientId, seq, rid}
    submit         {ops: [DocumentMessage…]}          (fire-and-forget, like socket submitOp)
    signal         {content, type}
    get_deltas     {tenant, doc, from, to, rid}       → deltas {msgs, rid}
    get_deltas_cols {tenant, doc, from, to, rid}      → K × binary FT_COLS_DELTAS pushes,
                                                        then deltas {msgs, blocks, head, rid}
                                                        (direct core connections only)
    get_versions   {tenant, doc, count, rid}          → versions {versions, rid}
    get_tree       {tenant, doc, version, rid}        → tree {tree, rid}
    read_blob      {tenant, doc, id, rid}             → blob {hex, rid}
    write_blob     {tenant, doc, hex, rid}            → blob_id {id, rid}
    upload_summary {tenant, doc, summary, parent, rid} → version_id {id, rid}
    disconnect     {}
  server → client (push, after connect)
    ops {msgs: [SequencedDocumentMessage…]} | nack {nack} | signal {signal}
  server → client (error reply)
    error {message, rid?}

Gateway backbone (the Redis-pub/sub role — N gateway processes terminate
client sockets and mux them over ONE upstream connection each; see
service/gateway.py):

  gateway → core
    fconnect    {sid, tenant, doc, details, rid} → fconnected {sid, rid, …}
    fsubmit     {sid, ops} | fsignal {sid, content, type} | fdisconnect {sid}
    (storage/delta RPCs pass through unchanged — they are stateless)
  core → gateway
    fops {topic, msgs}   ONE per broadcast batch per gateway, however many
                         clients the gateway serves on that doc
    fnack {sid, nack} | fsignal {topic, signal}

Concurrency model: the ENTIRE service (LocalServer pipeline included) runs
on the event-loop thread, so no server-side locking is needed — the same
single-writer discipline the reference gets from Node's event loop.

Service limits: submits above ``max_message_size`` (16 KB default, ref
localDeltaConnectionServer.ts:96) are nacked with BAD_REQUEST without
entering the pipeline.
"""

from __future__ import annotations

import argparse
import asyncio
import functools
import json
import threading
import time
from typing import Any, Optional

from ..obs import get_journal, get_recorder, get_registry, tier_counters
from ..obs.probe import CANARY_TENANT
from ..utils.affinity import loop_only, ticker_thread
from ..protocol import binwire
from ..protocol.messages import Nack, NackErrorType, Signal, TraceHop
from ..protocol.serialization import message_from_dict, message_to_dict
from ..utils.telemetry import (HOP_ADMIT, HOP_SERVICE_ACTION,
                               count_unknown_hops, hop_pairs)
from .admission import AdmissionController, retry_after_ms
from .array_batch import ArrayBoxcar
from .local_server import LocalServer, ServerConnection
from .presence import PresenceLane
from .rehydrate import BootPending
from .scriptorium import LogTruncatedError

MAX_FRAME = 8 * 1024 * 1024  # absolute wire-frame cap (storage payloads)
DEFAULT_MAX_MESSAGE_SIZE = 16 * 1024  # per-op cap, nacked (ref :96)
# backoff hint on submits bounced off a sealed (mid-migration)
# partition: long enough for checkpoint+handoff of a hot partition,
# short enough to keep the client-visible migration blip small
MIGRATION_RETRY_S = 0.05


def _encode_frame(obj: dict) -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode()
    return len(body).to_bytes(4, "big") + body


def _stamp_abatch(batch, topic=None, tenant=None) -> bytes:
    """Sequenced columnar broadcast body: splice deli's stamp onto the
    column bytes the submit frame carried (zero re-encode); a boxcar
    that arrived without them (in-proc submit_array, durable replay)
    re-packs its columns once here.

    Sampled boxcars carry the accumulated hop list; this is the egress
    point where the full server-side path is known, so the consecutive
    hop pairs (submit→relay→admit→deli→fanout) are observed into the
    process registry here — once per encode, which the fan-out caches
    make once per batch — and the list packs back into the broadcast
    frame's hoptail for the client's ack split."""
    box = batch.boxcar
    cols = box.wire_cols
    if cols is None:
        cols = binwire.encode_cols(
            box.ds_id, box.channel_id, box.kind, box.a, box.b,
            box.cseq, box.rseq, box.text, box.text_off, box.props)
    hops = box.hops
    if hops and tenant is None and topic:
        tenant = topic.partition("/")[0]
    if hops and tenant != CANARY_TENANT:
        # canary hops must not land in the windowed series the SLO
        # engine burns on: the probe measures the doors and may not
        # flip the shed machinery those windows gate
        reg = get_registry()
        unknown = count_unknown_hops(hops)
        if unknown:
            # a hop id past this build's taxonomy (version-skewed
            # client): COUNT it rather than silently dropping, so a
            # breakdown that quietly lost legs is visible in the scrape
            reg.inc("obs.trace.unknown_hops", unknown)
        for pair, ms in hop_pairs(hops):
            # cumulative summary (lifetime) and its windowed twin (the
            # SLO engine's read source) — both per sampled batch only,
            # labeled by tenant when the egress point knows it
            if tenant:
                reg.observe("obs.hop.ms", ms, pair=pair, tenant=tenant)
                reg.observe_windowed("obs.hop.window_ms", ms,
                                     pair=pair, tenant=tenant)
            else:
                reg.observe("obs.hop.ms", ms, pair=pair)
                reg.observe_windowed("obs.hop.window_ms", ms, pair=pair)
    return binwire.stamp_cols_ops(cols, box.client_id, batch.base_seq,
                                  batch.msns, batch.timestamp, topic=topic,
                                  hops=hops)


def _stamp_admit(ops) -> None:
    """frontend/admit hop on rec-frame ingress, SAMPLED ops only: an op
    carries traces iff the client armed tracing for it, so unsampled
    traffic pays one empty-list check per op."""
    svc, act = HOP_SERVICE_ACTION[HOP_ADMIT]
    for op in ops:
        if op.traces:
            op.traces.append(
                TraceHop(service=svc, action=act, timestamp=time.time()))


async def _read_body(reader: asyncio.StreamReader) -> Optional[bytes]:
    """Read one length-prefixed frame body (JSON or binary), None on EOF."""
    try:
        header = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    n = int.from_bytes(header, "big")
    if n > MAX_FRAME:
        raise ValueError(f"frame of {n} bytes exceeds cap {MAX_FRAME}")
    try:
        return await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None


async def _read_frame(reader: asyncio.StreamReader) -> Optional[dict]:
    body = await _read_body(reader)
    return None if body is None else json.loads(body.decode())


#: Bulk backfill frame types deferred behind the interactive ops of
#: the same ingress wave (see _handle_conn lane priority).
_BULK_FRAMES = ("get_deltas_cols", "get_deltas", "get_snapshot_cols")


def _frame_buffered(reader: asyncio.StreamReader) -> bool:
    """True when a COMPLETE frame already sits in the stream buffer.

    The drain-batched read loops peek here: ``readexactly`` completes
    synchronously (no event-loop yield) when the buffer holds the bytes,
    so frames the kernel delivered in one wave are handled as one batch
    while flow control stays with the public StreamReader API. Reaching
    into ``_buffer`` is an asyncio-internal dependency, so fail safe:
    no buffer attribute means no batching, never an error."""
    buf = getattr(reader, "_buffer", None)
    if buf is None or len(buf) < 4:
        return False
    return len(buf) - 4 >= int.from_bytes(buf[:4], "big")


class _ClientSession:
    """Server-side state for one TCP connection."""

    def __init__(self, front: "NetworkFrontEnd",
                 writer: asyncio.StreamWriter):
        self.front = front
        self.writer = writer
        self.conn: Optional[ServerConnection] = None
        self.binary = False       # client opted into binary ops push
        self._fbinary = False     # gateway opted into binary fops push
        self._dropping = False
        self._loop = asyncio.get_running_loop()
        # gateway-mode state: sid → ServerConnection, and the doc topics
        # this gateway subscribes (each exactly once, refcounted by its
        # live sessions so the last fdisconnect unsubscribes)
        self._fsessions: dict[int, ServerConnection] = {}
        self._ftopics: dict[str, object] = {}  # topic → pubsub callbacks
        self._ftopic_refs: dict[str, int] = {}
        self._fsession_topics: dict[int, str] = {}
        # presence-lane subscriptions this session holds: (topic, fn)
        self._presence: list = []

    # -- push events (called synchronously from the pipeline drain, which
    # runs on the loop thread) --
    # a session whose unread outbound buffer passes this bound is dropped
    # (slow-consumer protection — fan-out writes are not awaited, so an
    # unread socket would otherwise buffer the doc's whole stream in RAM).
    # Snapshotted once per session: the chained config lookup was a
    # measurable cost at two checks per broadcast push.
    @functools.cached_property
    def MAX_BUFFERED(self) -> int:
        return self.front.server.config.max_buffered_bytes

    def _drop_slow_consumer(self) -> None:
        self.front.logger.error(
            "slow_consumer_dropped",
            client_id=self.conn.client_id if self.conn else None)
        self.closed()
        try:
            self.writer.close()
        except RuntimeError:
            pass

    def push(self, t: str, payload: dict) -> None:
        try:
            if self.writer.is_closing():
                return
            transport = self.writer.transport
            if transport.get_write_buffer_size() > self.MAX_BUFFERED:
                # defer the drop out of the fan-out path: closed() →
                # disconnect() re-enters the pipeline, and doing that from
                # inside a broadcast drain only works while drain iterates
                # a snapshot — schedule it instead of relying on that
                if not self._dropping:
                    self._dropping = True
                    self._loop.call_soon(self._drop_slow_consumer)
                return
            self.writer.write(_encode_frame({"t": t, **payload}))
        except RuntimeError:
            pass  # transport torn down mid-shutdown; peer is gone anyway

    def _push_op_batch(self, batch: list) -> None:
        """Encode a broadcast batch ONCE per format for all subscribers.

        The broadcaster delivers the same batch object to every session
        of the doc back to back; a one-entry cache on the front end keyed
        by (doc, first seq, len) — unique in an append-only stream —
        holds one slot per wire format, so the whole fan-out costs one
        binwire encode (+ one JSON encode ONLY if a legacy subscriber or
        an unpackable batch needs it) and N raw writes. ``False`` in the
        binary slot marks a batch that tried binwire and cannot pack
        (int outside the fixed-field range, >u16 batch) — every binary
        session then shares the JSON frame instead of re-attempting."""
        conn = self.conn
        front = self.front
        key = (conn.tenant_id, conn.document_id,
               batch[0].sequence_number, len(batch))
        cached_key, slots = front._batch_cache
        if cached_key != key:
            slots = [None, None]  # [binwire raw | False, JSON raw]
            front._batch_cache = (key, slots)
        if self.binary:
            raw = slots[0]
            if raw is None:
                try:
                    body = None
                    ctx = front._splice_ctx
                    if ctx is not None:
                        body = binwire.encode_ops_spliced(batch, *ctx)
                    if body is None:
                        body = binwire.encode_ops(batch)
                    raw = binwire.frame(body)
                except Exception:
                    raw = False
                slots[0] = raw
                front.counters.inc("net.fanout.encodes")
            else:
                front.counters.inc("net.fanout.cache_hits")
            if raw is not False:
                self.push_raw(raw)
                return
        raw = slots[1]
        if raw is None:
            raw = _encode_frame(
                {"t": "ops", "msgs": [message_to_dict(m) for m in batch]})
            slots[1] = raw
            front.counters.inc("net.fanout.encodes")
        else:
            front.counters.inc("net.fanout.cache_hits")
        self.push_raw(raw)

    def _push_abatch(self, batch) -> None:
        """Columnar twin of ``_push_op_batch`` for SequencedArrayBatch.

        The binary broadcast frame is the submit frame's column bytes
        with deli's stamp spliced on (``stamp_cols_ops``) — no per-op
        encode at all; the JSON slot materializes lazily only if a
        legacy subscriber shares the doc. Same one-entry cache, so the
        fan-out costs one splice total."""
        conn = self.conn
        front = self.front
        key = (conn.tenant_id, conn.document_id, batch.base_seq, batch.n)
        cached_key, slots = front._batch_cache
        if cached_key != key:
            slots = [None, None]  # [binwire raw | False, JSON raw]
            front._batch_cache = (key, slots)
        if self.binary:
            raw = slots[0]
            if raw is None:
                try:
                    raw = binwire.frame(
                        _stamp_abatch(batch, tenant=conn.tenant_id))
                except Exception:
                    raw = False
                slots[0] = raw
                front.counters.inc("net.fanout.encodes")
                # per-tenant fan-out accounting: once per encode (the
                # cache makes that once per batch), not per subscriber
                get_registry().inc("net.fanout.batches", batch.n,
                                   tenant=conn.tenant_id)
            else:
                front.counters.inc("net.fanout.cache_hits")
            if raw is not False:
                self.push_raw(raw)
                return
        raw = slots[1]
        if raw is None:
            raw = _encode_frame(
                {"t": "ops",
                 "msgs": [message_to_dict(m) for m in batch.messages()]})
            slots[1] = raw
            front.counters.inc("net.fanout.encodes")
        else:
            front.counters.inc("net.fanout.cache_hits")
        self.push_raw(raw)

    def push_raw(self, raw: bytes) -> None:
        try:
            if self.writer.is_closing():
                return
            transport = self.writer.transport
            if transport.get_write_buffer_size() > self.MAX_BUFFERED:
                if not self._dropping:
                    self._dropping = True
                    self._loop.call_soon(self._drop_slow_consumer)
                return
            self.writer.write(raw)
        except RuntimeError:
            pass

    def _subscribe_presence(self, topic: str) -> None:
        """Register this direct session on the doc's presence lane: one
        shared FT_PRESENCE frame for binary clients, legacy per-signal
        JSON otherwise."""
        if any(t == topic for t, _ in self._presence):
            return  # reconnect on a live socket: already registered

        def on_presence(pb):
            if self.binary:
                self.push_raw(pb.presence_frame())
            else:
                for d in pb.signal_dicts():
                    self.push("signal", {"signal": d})

        self.front.presence.subscribe(topic, on_presence)
        self._presence.append((topic, on_presence))

    @loop_only("core")
    def handle(self, frame: dict) -> None:
        t = frame.get("t")
        rid = frame.get("rid")
        try:
            if t == "connect":
                server = self.front.server_for(frame["tenant"],
                                               frame["doc"])
                readonly = bool(frame.get("readonly"))
                conn = server.connect(
                    frame["tenant"], frame["doc"], frame.get("details"),
                    token=frame.get("token"), readonly=readonly)
                if readonly:
                    # no join was ordered: nothing to flush, nothing on
                    # the op path — the whole point of the reader tier
                    self.front.counters.inc("session.readonly.connects")
                else:
                    self.front._dirty_servers.add(server)  # join appended
                self.conn = conn
                self.binary = bool(frame.get("bin"))
                self._subscribe_presence(
                    f"{frame['tenant']}/{frame['doc']}")
                # a broadcast batch rides the wire as ONE frame — at load
                # the per-op frame overhead (json + syscall each) was the
                # front end's dominant cost
                conn.on_ops = self._push_op_batch
                conn.on_abatch = self._push_abatch
                conn.on_nack = lambda n: self.push(
                    "nack", {"nack": message_to_dict(n)})
                conn.on_signal = lambda s: self.push(
                    "signal", {"signal": message_to_dict(s)})
                self.push("connected", {
                    "rid": rid,
                    "clientId": conn.client_id,
                    "seq": conn.initial_sequence_number,
                    "mode": getattr(conn, "mode", "write"),
                    "maxMessageSize": self.front.max_message_size,
                    # columnar backfill door (get_deltas_cols) — only on
                    # DIRECT core connections: the gateway relays rid
                    # replies as JSON and cannot route the binary
                    # FT_COLS_DELTAS pushes, so its own connected reply
                    # never advertises it
                    "colsBackfill": True,
                })
            elif t == "submit":
                if self.conn is None:
                    raise RuntimeError("submit before connect")
                # oversized ops nack without entering the pipeline (ref
                # 16KB limit, localDeltaConnectionServer.ts:96)
                ops = self._filter_oversized(
                    [message_from_dict(d) for d in frame["ops"]], None, None)
                ops = self._admit_or_shed(self.conn, ops, None)
                if ops:
                    self.conn.submit(ops)
                    self.front._dirty_servers.add(self.conn.server)
            elif t == "signal":
                if self.conn is None:
                    raise RuntimeError("signal before connect")
                # presence lane, not submit_signal: coalesce per
                # (doc, client, type) server-side and deliver batched on
                # the flush tick — never touches deli or the durable log
                self.front.presence.publish(
                    f"{self.conn.tenant_id}/{self.conn.document_id}",
                    Signal(client_id=self.conn.client_id,
                           type=frame.get("type", "signal"),
                           content=frame["content"]))
            elif t == "disconnect":
                if self.conn is not None:
                    self.front._dirty_servers.add(self.conn.server)
                    self.conn.disconnect()
                    self.conn = None
            elif t == "get_deltas":
                self._check_rpc_auth(frame, write=False)
                msgs = self.front.server_for(
                    frame["tenant"], frame["doc"]).get_deltas(
                    frame["tenant"], frame["doc"], frame["from"], frame["to"])
                self.push("deltas", {
                    "rid": rid, "msgs": [message_to_dict(m) for m in msgs]})
            elif t == "get_deltas_cols":
                # columnar backfill: the in-range segment blocks push as
                # raw FT_COLS_DELTAS bodies (stamped column bytes straight
                # off the storage mmap — zero re-encode), then ONE JSON
                # terminal carrying any compat-shim ops and the block
                # count so the client knows the pushes all arrived (same
                # wire, same thread: ordering is guaranteed)
                self._check_rpc_auth(frame, write=False)
                server = self.front.server_for(frame["tenant"], frame["doc"])
                res = server.get_delta_blocks(
                    frame["tenant"], frame["doc"], frame["from"], frame["to"])
                if res is None:  # no segment stream: scalar fallback
                    msgs = server.get_deltas(
                        frame["tenant"], frame["doc"],
                        frame["from"], frame["to"])
                    self.push("deltas", {
                        "rid": rid, "blocks": 0,
                        "msgs": [message_to_dict(m) for m in msgs]})
                else:
                    payloads, legacy, head = res
                    for p in payloads:
                        self.push_raw(binwire.frame(
                            binwire.cols_deltas_body(int(rid), p)))
                    self.push("deltas", {
                        "rid": rid, "blocks": len(payloads), "head": head,
                        "msgs": [message_to_dict(m) for m in legacy]})
            elif t == "get_snapshot_cols":
                self._check_rpc_auth(frame, write=False)
                self._handle_snapshot_cols(frame, rid)
            elif t in ("get_versions", "get_tree", "read_blob",
                       "write_blob", "upload_summary"):
                self._check_rpc_auth(
                    frame, write=t in ("write_blob", "upload_summary"))
                self._handle_storage(t, frame, rid)
            elif t in ("history_log", "history_at", "history_deltas"):
                self._check_rpc_auth(frame, write=False)
                self._handle_history(t, frame, rid)
            elif t in ("history_fork", "history_integrate"):
                # fork births a doc and integrate submits ops: both
                # mutate, so they ride the write scope like upload_summary
                self._check_rpc_auth(frame, write=True)
                self._handle_history(t, frame, rid)
            elif t in ("fconnect", "fsubmit", "fsignal", "fdisconnect"):
                self._handle_gateway(t, frame, rid)
            elif t in ("admin_status", "admin_docs", "admin_tenants",
                       "admin_counters", "admin_metrics_scrape",
                       "admin_slo_status", "admin_summarize",
                       "admin_tenant_add", "admin_tenant_remove",
                       "admin_placement", "admin_migrate_doc",
                       "admin_adopt_partition", "admin_core_heat",
                       "admin_tier_snapshot", "admin_rebalance_status",
                       "admin_placement_drain", "admin_migrate_part",
                       "admin_journal", "admin_metrics_history",
                       "admin_flight_dump", "admin_boot_status",
                       "admin_health"):
                self._handle_admin(t, frame, rid)
            elif t == "ping":
                # client liveness probe on an idle connection (the
                # driver's recv-timeout escalation, driver/network.py)
                self.push("pong", {})
            else:
                raise ValueError(f"unknown frame type {t!r}")
        except Exception as e:  # noqa: BLE001 — report, don't kill the loop
            self.front.logger.error("frame_error", frame_type=t,
                                    message=str(e))
            err = {"rid": rid, "message": str(e)}
            if isinstance(e, LogTruncatedError):
                # machine-readable: the driver maps this to its own
                # too-far-behind exception and switches to summary
                # catch-up instead of retrying a range that can never fill
                err["code"] = "log_truncated"
                err["base"] = e.base
                if getattr(e, "snapshot_seq", None) is not None:
                    # the snapshot-backed base: an acked summary at this
                    # seq boots the client past the hole
                    err["snapshotSeq"] = e.snapshot_seq
            elif isinstance(e, BootPending):
                # cold-start storm: the rehydration executor parked this
                # first-route — the driver retries after the hint
                # instead of surfacing a failed session
                err["code"] = "boot_pending"
                err["retryAfterMs"] = e.retry_after_ms
            self.push("error", err)

    def handle_binary(self, body: bytes) -> None:
        """Dispatch a binwire frame: the hot submit path (direct and
        gateway-muxed). Connect/signals/storage stay on the JSON path."""
        try:
            ftype = body[1]
            if ftype == binwire.FT_SUBMIT:
                if self.conn is None:
                    raise RuntimeError("submit before connect")
                _, ops, spans, blob, npool = binwire.decode_submit(
                    body, with_spans=True)
                ops = self._filter_oversized(ops, len(body), None)
                ops = self._admit_or_shed(self.conn, ops, None,
                                          nbytes=len(body))
                if ops:
                    _stamp_admit(ops)
                    # expose the splice context for the SYNCHRONOUS
                    # broadcast this submit triggers: the encoder reuses
                    # the submitted payload bytes instead of re-packing
                    self.front._splice_ctx = (spans, blob, npool)
                    try:
                        self.conn.submit(ops)
                    finally:
                        self.front._splice_ctx = None
                    self.front._dirty_servers.add(self.conn.server)
            elif ftype == binwire.FT_FSUBMIT:
                sid, ops, spans, blob, npool = binwire.decode_submit(
                    body, with_spans=True)
                conn = self._fsessions[sid]
                ops = self._filter_oversized(ops, len(body), sid)
                ops = self._admit_or_shed(conn, ops, sid,
                                          nbytes=len(body))
                if ops:
                    _stamp_admit(ops)
                    self.front._splice_ctx = (spans, blob, npool)
                    try:
                        conn.submit(ops)
                    finally:
                        self.front._splice_ctx = None
                    self.front._dirty_servers.add(conn.server)
            elif (ftype == binwire.FT_COLS_SUBMIT
                  or ftype == binwire.FT_COLS_FSUBMIT):
                self._submit_columns(body)
            else:
                raise ValueError(f"unexpected binary frame type {ftype}")
        except Exception as e:  # noqa: BLE001 — report, don't kill the loop
            self.front.logger.error("frame_error", frame_type="binary",
                                    message=str(e))
            self.push("error", {"message": str(e)})

    def _filter_oversized(self, ops: list, body_len: Optional[int],
                          sid) -> list:
        """Enforce the per-op service limit; nack what exceeds it.

        The limit is DEFINED as JSON size (so one op is admitted or
        nacked identically through either door); JSON callers pass
        ``body_len=None`` and every op is measured. Binary callers pass
        the frame length for a fast path: binwire is more compact than
        JSON — \\uXXXX escaping inflates a control/non-ASCII byte up to
        6× and the envelope keys add ~200 bytes — so a whole boxcar body
        under (limit - 512) / 6 cannot contain an op whose JSON measure
        exceeds the limit. Typical boxcars (KBs) pass in one comparison;
        only outsized frames pay per-op JSON dumps."""
        limit = self.front.max_message_size
        if body_len is not None and 6 * body_len + 512 <= limit:
            return ops
        kept = []
        for op in ops:
            d = message_to_dict(op)
            if len(json.dumps(d).encode()) > limit:
                nack = Nack(
                    operation=op, sequence_number=-1, code=413,
                    type=NackErrorType.BAD_REQUEST,
                    message=f"message exceeds {limit} byte limit")
                if sid is None:
                    self.push("nack", {"nack": message_to_dict(nack)})
                else:
                    self.push("fnack", {"sid": sid,
                                        "nack": message_to_dict(nack)})
            else:
                kept.append(op)
        return kept

    def _admit_or_shed(self, conn, ops: list, sid,
                       nbytes: int = 0) -> list:
        """THE admission gate: every rec-lane submit door passes its
        ops through here after the size filter (the columnar door runs
        the same check on its packed columns in ``_submit_columns``).
        Also the per-tenant ingress accounting point — one labeled
        registry inc per boxcar, never per op — and the per-partition
        heat recording point the rebalancer plans from (admitted ops
        only: shed traffic is load the partition did NOT carry)."""
        if not ops:
            return ops
        # synthetic canary traffic (obs/probe.py) rides the real door
        # but is invisible to the control loops: no ingress accounting,
        # no tenant bucket charge, no partition heat — probing can
        # never shed a tenant or trigger a rebalance. The seal bounce
        # DOES apply: a canary on a migrating partition should observe
        # exactly what a client would.
        canary = conn.tenant_id == CANARY_TENANT
        if not canary:
            get_registry().inc("net.ingress.ops", len(ops),
                               tenant=conn.tenant_id)
        if getattr(conn.server, "sealed", False):
            # partition mid-migration: bounce on the shed-retry lane
            # (echoed op + retry_after_ms — the driver parks and
            # resubmits in cseq order against the new owner)
            from .placement_plane import placement_counters

            placement_counters().inc("placement.submits.redirected", len(ops))
            self._push_shed_nacks(
                ops, MIGRATION_RETRY_S, sid,
                message="partition migrating: resubmit shortly")
            return []
        adm = self.front.admission
        if adm is not None and not canary:
            retry_s = adm.check(conn, len(ops),
                                ops[0].client_sequence_number)
            if retry_s > 0.0:
                self._push_shed_nacks(ops, retry_s, sid)
                return []
        if not canary:
            self.front.record_heat(conn.server, len(ops), nbytes)
        return ops

    def _push_shed_nacks(self, ops: list, retry_s: float, sid,
                         message: str = "tenant over admission "
                                        "budget") -> None:
        """Shed a whole boxcar through the shared nack door: one
        THROTTLING nack per op carrying the op itself plus
        ``retry_after_ms``, pushed over the same wire (or fnack-muxed
        for gateway clients) as every other refusal — the driver
        resubmits transparently after the backoff."""
        ms = retry_after_ms(retry_s)
        for op in ops:
            nack = Nack(
                operation=op, sequence_number=-1, code=429,
                type=NackErrorType.THROTTLING,
                message=message,
                retry_after_ms=ms)
            if sid is None:
                self.push("nack", {"nack": message_to_dict(nack)})
            else:
                self.push("fnack", {"sid": sid,
                                    "nack": message_to_dict(nack)})

    def _submit_columns(self, body: bytes) -> None:
        """Columnar ingress: hand a submit boxcar to deli's array lane
        with the op payload still in packed columns.

        Bulk admission happens in two vectorized stages, each with a
        per-op scalar fallback so nack semantics are byte-identical to
        the rec path: the front end verifies writability and the
        boxcar-level size bound here (failure → materialize +
        ``_filter_oversized`` + ``conn.submit``, same as a rec frame);
        deli's ``_ticket_array_boxcar`` verifies join/clientSeq/refSeq
        on the columns and falls back to the scalar ``_ticket`` loop
        itself when they don't hold. The admitted path never builds a
        per-op object: the columns become an ArrayBoxcar (frombuffer
        views) carrying the frame's column bytes for splice-stamped
        fan-out (``_push_abatch``)."""
        front = self.front
        sid, sc, hops = binwire.decode_submit_columns(body, with_hops=True)
        if sid is None:
            conn = self.conn
            if conn is None:
                raise RuntimeError("submit before connect")
        else:
            conn = self._fsessions[sid]
        n = len(sc.cseq)
        if n:
            # canary isolation, columnar door: same seams as
            # _admit_or_shed (no accounting, no bucket, no heat)
            canary = conn.tenant_id == CANARY_TENANT
            if not canary:
                get_registry().inc("net.ingress.ops", n,
                                   tenant=conn.tenant_id)
            if getattr(conn.server, "sealed", False):
                # mid-migration bounce, cold path: materialize the ops
                # so the shed nacks are byte-identical to the rec door's
                from .placement_plane import placement_counters

                placement_counters().inc("placement.submits.redirected", n)
                self._push_shed_nacks(
                    binwire.cols_to_ops(sc), MIGRATION_RETRY_S, sid,
                    message="partition migrating: resubmit shortly")
                return
            adm = front.admission
            if adm is not None and not canary:
                retry_s = adm.check(conn, n, int(sc.cseq[0]))
                if retry_s > 0.0:
                    # shed is the cold path: materialize the ops once
                    # so the per-op nacks are byte-identical to the
                    # rec door's
                    self._push_shed_nacks(binwire.cols_to_ops(sc),
                                          retry_s, sid)
                    return
            if not canary:
                front.record_heat(conn.server, n, len(body))
        limit = front.max_message_size
        if (getattr(conn, "can_write", True)
                and 6 * len(body) + 512 <= limit):
            if hops:
                # sampled frame: stamp frontend/admit; downstream tiers
                # append to the same list and the egress encode packs it
                hops.append((HOP_ADMIT, time.time()))
            box = ArrayBoxcar(
                tenant_id="", document_id="", client_id="",
                ds_id=sc.ds_id, channel_id=sc.channel_id,
                kind=sc.kind, a=sc.a, b=sc.b, cseq=sc.cseq, rseq=sc.rseq,
                text=sc.text, text_off=sc.text_off, props=sc.props,
                wire_cols=sc.cols, hops=hops or None)
            conn.submit_array(box)
            front.counters.inc("net.ingress.columnar")
        else:
            # read-only connections nack PER OP through the scalar door
            # (the array door nacks once per boxcar); oversize frames
            # need the per-op JSON measure anyway
            ops = self._filter_oversized(binwire.cols_to_ops(sc),
                                         None, sid)
            if ops:
                conn.submit(ops)
            front.counters.inc("net.ingress.fallback")
        front._dirty_servers.add(conn.server)

    def _handle_gateway(self, t: str, frame: dict, rid) -> None:
        """Backbone mux for a gateway connection (see module docstring).

        The key property: broadcast fan-out to this gateway is ONE fops
        frame per batch per doc, not per client — the per-connection
        subscription server.connect() made is replaced by a per-topic
        subscription owned by this gateway session."""
        if t == "fconnect":
            sid = frame["sid"]
            from .broadcaster import BroadcasterLambda

            tenant, doc = frame["tenant"], frame["doc"]
            server = self.front.server_for(tenant, doc)
            # validate BEFORE creating the topic subscription: a refused
            # connect must not leak a subscription. Require only read
            # scope here — server.connect() below assigns read/write mode
            # from the token exactly as the direct door does, so a
            # read-only token gets a read-mode connection, not a refusal.
            if server.tenants is not None:
                from .tenants import SCOPE_READ
                server.tenants.validate(frame.get("token"), tenant, doc,
                                        required_scope=SCOPE_READ)
            topic = BroadcasterLambda.topic(tenant, doc)
            self._fbinary = bool(frame.get("bin"))
            # the gateway's topic subscription must exist BEFORE the join
            # is ordered: connect() sequences + broadcasts the join
            # synchronously, and a lone client that misses its own join
            # never activates (nothing later triggers gap repair)
            if topic not in self._ftopics:
                if self._fbinary:
                    def on_batch(batch, topic=topic):
                        # one binwire encode per batch, shared across
                        # gateways via the front-end fops cache; a
                        # SequencedArrayBatch (columnar array lane)
                        # splice-stamps its column bytes instead
                        if type(batch) is not list:
                            key = (topic, batch.base_seq, batch.n)
                            ck, raw = self.front._fops_cache
                            if ck != key:
                                try:
                                    raw = binwire.frame(
                                        _stamp_abatch(batch, topic=topic))
                                except Exception:
                                    raw = None  # unpackable: JSON
                                self.front._fops_cache = (key, raw)
                                self.front.counters.inc(
                                    "net.fanout.encodes")
                                get_registry().inc(
                                    "net.fanout.batches", batch.n,
                                    tenant=topic.partition("/")[0])
                            else:
                                self.front.counters.inc(
                                    "net.fanout.cache_hits")
                            if raw is not None:
                                self.push_raw(raw)
                            else:
                                self.push("fops", {
                                    "topic": topic,
                                    "msgs": [message_to_dict(m)
                                             for m in batch.messages()]})
                            return
                        key = (topic, batch[0].sequence_number, len(batch))
                        ck, raw = self.front._fops_cache
                        if ck != key:
                            try:
                                body = None
                                ctx = self.front._splice_ctx
                                if ctx is not None:
                                    body = binwire.encode_ops_spliced(
                                        batch, *ctx, topic=topic)
                                if body is None:
                                    body = binwire.encode_ops(batch,
                                                              topic=topic)
                                raw = binwire.frame(body)
                            except Exception:
                                raw = None  # unpackable: JSON fallback
                            self.front._fops_cache = (key, raw)
                            self.front.counters.inc("net.fanout.encodes")
                        else:
                            self.front.counters.inc("net.fanout.cache_hits")
                        if raw is not None:
                            self.push_raw(raw)
                        else:
                            self.push("fops", {
                                "topic": topic,
                                "msgs": [message_to_dict(m) for m in batch]})
                else:
                    def on_batch(batch, topic=topic):
                        msgs = (batch if type(batch) is list
                                else batch.messages())
                        self.push("fops", {
                            "topic": topic,
                            "msgs": [message_to_dict(m) for m in msgs]})
                server.pubsub.subscribe(topic, on_batch)

                def on_signal(sig, topic=topic):
                    self.push("fsignal", {
                        "topic": topic, "signal": message_to_dict(sig)})
                server.pubsub.subscribe(f"signal/{tenant}/{doc}", on_signal)

                def on_presence(pb, topic=topic):
                    # one FT_FPRESENCE frame per flush shared by every
                    # backbone link; relays strip the topic by splice
                    if self._fbinary:
                        self.push_raw(pb.fpresence_frame())
                    else:
                        for d in pb.signal_dicts():
                            self.push("fsignal",
                                      {"topic": topic, "signal": d})
                self.front.presence.subscribe(topic, on_presence)
                self._ftopics[topic] = (on_batch, on_signal,
                                        f"signal/{tenant}/{doc}", server,
                                        on_presence)
            readonly = bool(frame.get("readonly"))
            conn = server.connect(tenant, doc, frame.get("details"),
                                  token=frame.get("token"),
                                  readonly=readonly)
            if readonly:
                self.front.counters.inc("session.readonly.connects")
            else:
                self.front._dirty_servers.add(server)  # join was appended
            self._fsessions[sid] = conn
            self._fsession_topics[sid] = topic
            self._ftopic_refs[topic] = self._ftopic_refs.get(topic, 0) + 1
            # drop the per-connection op/signal subscriptions (the topic
            # subscription above covers them ONCE per gateway — and their
            # handler-less buffers would otherwise grow unbounded); nacks
            # stay per-connection, routed by sid
            server.pubsub.unsubscribe(topic, conn._op_cb)
            server.pubsub.unsubscribe(f"signal/{tenant}/{doc}", conn._sig_cb)
            conn.on_nack = lambda n, sid=sid: self.push(
                "fnack", {"sid": sid, "nack": message_to_dict(n)})
            self.push("fconnected", {
                "rid": rid, "sid": sid,
                "clientId": conn.client_id,
                "seq": conn.initial_sequence_number,
                "mode": getattr(conn, "mode", "write"),
                "maxMessageSize": self.front.max_message_size,
            })
        elif t == "fsubmit":
            conn = self._fsessions[frame["sid"]]
            # same 16 KB service limit as the direct door
            ops = self._filter_oversized(
                [message_from_dict(d) for d in frame["ops"]], None,
                frame["sid"])
            ops = self._admit_or_shed(conn, ops, frame["sid"])
            if ops:
                conn.submit(ops)
                self.front._dirty_servers.add(conn.server)
        elif t == "fsignal":
            conn = self._fsessions[frame["sid"]]
            self.front.presence.publish(
                f"{conn.tenant_id}/{conn.document_id}",
                Signal(client_id=conn.client_id,
                       type=frame.get("type", "signal"),
                       content=frame["content"]))
        elif t == "fdisconnect":
            sid = frame["sid"]
            conn = self._fsessions.pop(sid, None)
            if conn is not None:
                self.front._dirty_servers.add(conn.server)
                conn.disconnect()
            topic = self._fsession_topics.pop(sid, None)
            if topic is not None:
                self._ftopic_refs[topic] -= 1
                if self._ftopic_refs[topic] == 0:
                    # the gateway's last session on this doc is gone:
                    # stop encoding/pushing its broadcasts
                    del self._ftopic_refs[topic]
                    self._unsubscribe_ftopic(topic)

    def _check_rpc_auth(self, frame: dict, write: bool) -> None:
        """Tenancy applies to the REST-role endpoints too: delta backfill
        and storage reads need doc:read, blob/summary writes need
        doc:write — otherwise a tokenless connection could read a secured
        doc's whole op stream or write into its storage."""
        tenants = self.front.server.tenants
        if tenants is None:
            return
        from .tenants import SCOPE_READ, SCOPE_WRITE

        tenants.validate(frame.get("token"), frame["tenant"], frame["doc"],
                         required_scope=SCOPE_WRITE if write else SCOPE_READ)

    def _handle_snapshot_cols(self, frame: dict, rid) -> None:
        """Encode-once snapshot serving: push the latest snapcols
        version's chunks as FT_COLS_SNAP frames spliced from a
        per-(doc, version) cache of ALREADY-FRAMED bytes, then one JSON
        terminal with the version header. 10k joiners of the same doc get
        byte-identical splices — the cache frames each chunk exactly once
        per version (``storage.snapshot.encodes``), like the broadcast
        fan-out cache. Chunks the client proves it holds (``have``:
        content-addressed hashes from its snapshot cache) are skipped
        entirely. Chunk frames carry rid 0: the content hash, not the
        request, identifies the bytes — that rid-independence is what
        makes the cached frames shareable across joiners."""
        front = self.front
        tenant, doc = frame["tenant"], frame["doc"]
        storage = front.server_for(tenant, doc).storage(tenant, doc)
        versions = storage.get_versions(1)
        if not versions:
            self.push("snapshot", {"rid": rid, "version": None})
            return
        version = versions[0]
        entry = front._snap_cache.get((tenant, doc))
        if entry is None or entry[0] != version["id"]:
            root = json.loads(storage.read_blob(version["tree_id"]).decode())
            if root.get("t") != "snapcols":
                # pre-columnar summary at head: the client boots through
                # the legacy tree shim instead
                self.push("snapshot", {"rid": rid,
                                       "version": version["id"],
                                       "legacy": True})
                return
            framed = {h: binwire.frame(binwire.snap_chunk_body(
                0, h, storage.read_blob(h))) for h in root["chunks"]}
            entry = (version["id"], framed, root)
            front._snap_cache[(tenant, doc)] = entry
            front.counters.inc("storage.snapshot.encodes")
        else:
            front.counters.inc("storage.snapshot.cache_hits")
        vid, framed, root = entry
        have = set(frame.get("have") or ())
        plane = front.fault_plane
        sent = 0
        for h in root["chunks"]:
            if h in have:
                continue
            raw = framed[h]
            if plane is not None:
                directive = plane("snapshot.chunk", tenant=tenant,
                                  doc=doc, chunk=h)
                if directive == "drop":
                    continue  # the client sees a hole and falls back
                if directive == "torn":
                    # mangled wire bytes under the real hash: the
                    # client's sha256 verify must refuse them
                    raw = binwire.frame(binwire.snap_chunk_body(
                        0, h, b"\x00chaos-torn\x00"))
            self.push_raw(raw)
            sent += 1
        front.counters.inc("storage.snapshot.served")
        self.push("snapshot", {
            "rid": rid, "version": vid, "chunks": list(root["chunks"]),
            "sent": sent, "seq": root["sequence_number"],
            "tree_seq": root["tree_seq"], "min_seq": root["min_seq"],
            "protocol": root["protocol"], "pkg": root["pkg"],
            "ds": root["ds"], "channel": root["channel"]})

    def _handle_storage(self, t: str, frame: dict, rid) -> None:
        storage = self.front.server_for(
            frame["tenant"], frame["doc"]).storage(
            frame["tenant"], frame["doc"])
        if t == "get_versions":
            self.push("versions", {
                "rid": rid,
                "versions": storage.get_versions(frame.get("count", 1))})
        elif t == "get_tree":
            # legacy shim: whole-tree JSON materialization per join — the
            # deprecation counter is the migration's progress gauge
            self.front.counters.inc("storage.snapshot.legacy_tree")
            self.push("tree", {
                "rid": rid,
                "tree": storage.get_snapshot_tree(frame.get("version"))})
        elif t == "read_blob":
            self.push("blob", {
                "rid": rid, "hex": storage.read_blob(frame["id"]).hex()})
        elif t == "write_blob":
            self.push("blob_id", {
                "rid": rid,
                "id": storage.write_blob(bytes.fromhex(frame["hex"]))})
        elif t == "upload_summary":
            self.push("version_id", {
                "rid": rid,
                "id": storage.upload_summary(frame["summary"],
                                             frame.get("parent"))})

    @loop_only("core")
    def _handle_history(self, t: str, frame: dict, rid) -> None:
        """Doc history doors onto the history plane. ``history_log``
        pushes each commit as one binary FT_HISTORY frame (the same
        refgraph codec the ref files use, so the driver exercises the
        torn-tail framing end to end) then a JSON terminal carrying the
        refs and the count — same wire, same thread, ordering holds.
        The other doors are plain JSON request/reply. Historical boots
        themselves ride the EXISTING storage doors (``get_tree`` with an
        explicit version) so replay adds no second snapshot path."""
        tenant, doc = frame["tenant"], frame["doc"]
        history = self.front.server_for(tenant, doc).history
        if t == "history_log":
            commits = history.log(tenant, doc, frame.get("count"))
            for c in commits:
                self.push_raw(binwire.frame(
                    binwire.encode_history_commit(int(rid), c)))
            self.push("history", {
                "rid": rid, "commits": len(commits),
                "refs": history.refs(tenant, doc)})
        elif t == "history_at":
            self.push("history", {
                "rid": rid,
                "at": history.replay_read(tenant, doc, frame["seq"])})
        elif t == "history_deltas":
            msgs = history.read_deltas(
                tenant, doc, frame["from"], frame["to"])
            self.push("history", {
                "rid": rid, "msgs": [message_to_dict(m) for m in msgs]})
        elif t == "history_fork":
            res = history.fork(tenant, doc, at_seq=frame.get("seq"),
                               new_doc=frame.get("new_doc"))
            self.push("history", {"rid": rid, "fork": res})
        elif t == "history_integrate":
            res = history.integrate(tenant, doc,
                                    batch=frame.get("batch", 64))
            self.push("history", {"rid": rid, "integrate": res})

    def _reply_offloop(self, rid, work, reply) -> None:
        """Run ``work()`` on the default executor and push
        ``reply(result)`` from the future's done-callback, which asyncio
        runs back on the loop thread — so a slow fan-out (peer socket
        dials with multi-second timeouts) never stalls the event loop
        the way a synchronous call from ``handle()`` would. Failures get
        the same error frame the dispatcher's wrapper would have sent."""
        fut = self._loop.run_in_executor(None, work)

        def _done(f) -> None:
            try:
                reply(f.result())
            except Exception as e:  # noqa: BLE001 — report, don't kill the loop
                self.front.logger.error("frame_error", frame_type="admin",
                                        message=str(e))
                self.push("error", {"rid": rid, "message": str(e)})

        fut.add_done_callback(_done)

    @loop_only("core")
    def _handle_admin(self, t: str, frame: dict, rid) -> None:
        """Management surface (ref: server/admin + riddler's
        tenantManager REST): per-doc pipeline status, doc listing, and
        tenant CRUD, secured by ``--admin-secret`` whenever one is set
        (and ALWAYS required once tenancy is enforcing — an open admin
        door next to secured tenants would be a bypass)."""
        front = self.front
        secret = front.admin_secret
        tenants = front.server.tenants
        if secret is not None:
            import hmac as _hmac

            if not _hmac.compare_digest(str(frame.get("secret") or ""),
                                        secret):
                raise PermissionError("bad admin secret")
        elif tenants is not None and tenants.enforcing:
            raise PermissionError(
                "admin surface requires --admin-secret on a secured "
                "deployment")
        if secret is None and t in ("admin_tenant_add",
                                    "admin_tenant_remove"):
            # no open bootstrap: on a secret-less deployment ANY client
            # could otherwise register the first tenant, flip tenancy to
            # enforcing, and lock every other client out
            raise PermissionError(
                "mutating admin calls require --admin-secret")
        if t == "admin_status":
            tenant, doc = frame["tenant"], frame["doc"]
            server = front.server_for(tenant, doc)
            orderer = server._orderers.get(f"{tenant}/{doc}")
            if orderer is None:
                self.push("admin", {"rid": rid, "status": None})
                return
            deli = orderer.deli
            clients = [
                {"clientId": c.client_id,
                 "clientSeq": c.client_sequence_number,
                 "refSeq": c.reference_sequence_number}
                for c in deli.clients.values()]
            msn = min((c.reference_sequence_number
                       for c in deli.clients.values()),
                      default=deli.sequence_number)
            self.push("admin", {"rid": rid, "status": {
                "tenant": tenant, "doc": doc,
                "seq": deli.sequence_number,
                "msn": msn,
                "clients": clients,
                "summaryHead": orderer.scribe.last_summary_head,
                "retainedBase": orderer.scriptorium.retained_base(
                    tenant, doc),
                "applierSeq": front.applier_status.get((tenant, doc)),
            }})
        elif t == "admin_docs":
            docs = []
            for server in front._all_servers():
                docs.extend(sorted(server._orderers))
            self.push("admin", {"rid": rid, "docs": docs})
        elif t == "admin_tenants":
            self.push("admin", {
                "rid": rid,
                "tenants": tenants.list_tenants() if tenants else []})
        elif t == "admin_counters":
            # read-only: the socket-tier batching counters, so bench and
            # soak can assert coalescing/flush-eliding actually engaged
            self.push("admin", {"rid": rid,
                                "counters": front.counters.snapshot()})
        elif t == "admin_metrics_scrape":
            # read-only: the process-wide registry as Prometheus text —
            # every live tier Counters plus the labeled hop-pair series
            self.push("admin", {"rid": rid,
                                "scrape": get_registry().scrape()})
        elif t == "admin_slo_status":
            # read-only: per-spec health rows from the SLO engine (the
            # `admin slo` CLI view); no engine → empty list, not an error
            engine = front.slo_engine
            self.push("admin", {
                "rid": rid,
                "slos": engine.status() if engine is not None else [],
                "shedding": (front.admission.shedding
                             if front.admission is not None else False)})
        elif t == "admin_summarize":
            # force ONE service summary now — the operator/bench door
            # onto the same machinery as the --summarize-every loop.
            # Synchronous by design (a loop tick blocks this event loop
            # identically): the reply returns only once the version is
            # committed and flushed, so the caller can immediately boot
            # a joiner through it. Not in the no-secret mutating set:
            # it only materializes state the op stream already holds.
            tenant, doc = frame["tenant"], frame["doc"]
            server = front.server_for(tenant, doc)
            if server._orderers.get(f"{tenant}/{doc}") is None:
                # non-creating lookup (like admin_status): a typo'd doc
                # must not be born as an empty committed summary
                raise ValueError(f"unknown doc {tenant}/{doc}")
            version = front._summarizer_for(server).summarize_doc(
                tenant, doc)
            if front._log_flush and hasattr(server.log, "flush"):
                server.log.flush()
            get_journal().emit("summary.commit", tenant=tenant, doc=doc,
                               version=version, forced=True)
            self.push("admin", {"rid": rid, "version": version})
        elif t == "admin_tenant_add":
            if tenants is None:
                from .tenants import TenantManager

                tenants = front.server.tenants = TenantManager()
                for server in front._all_servers():
                    server.tenants = tenants
            tenants.register(frame["id"], frame["tenant_secret"])
            if front.shard_host is not None:
                # deployment-wide: the other cores reload the registry
                # file on their next lease poll
                front.shard_host.save_tenants()
            self.push("admin", {"rid": rid, "ok": True})
        elif t == "admin_tenant_remove":
            ok = tenants.remove(frame["id"]) if tenants else False
            if ok and front.shard_host is not None:
                front.shard_host.save_tenants()
            self.push("admin", {"rid": rid, "ok": ok})
        elif t == "admin_placement":
            # read-only: this core's view of the routing plane — the
            # epoch table, its own claims, the lease liveness view, and
            # the placement.* counter snapshot (net_smoke's gate source)
            sh = front.shard_host
            if sh is None:
                self.push("admin", {"rid": rid, "placement": None})
                return
            rec = sh.table.read()
            from ..obs import tier_snapshot

            placement = {
                "owner": sh.owner_id,
                "address": sh.address,
                "epoch": rec["epoch"],
                "parts": rec["parts"],
                "cores": rec.get("cores", {}),
                "owned": sorted(sh.servers),
                "leases": sh.placement.table(),
                "counters": None,
            }
            if frame.get("fleet"):
                # fleet totals: this core's snapshot summed with every
                # reachable peer's (admin_tier_snapshot fan-out) — the
                # operator sees migrations the WHOLE loop issued, not
                # just the local lane's. Each peer is a synchronous
                # socket dial with a multi-second timeout, so the
                # fan-out runs off-loop and the reply is pushed from
                # the done-callback.
                self._reply_offloop(
                    rid, lambda: front._fleet_placement_counters(rec),
                    lambda counters: self.push("admin", {
                        "rid": rid,
                        "placement": dict(placement,
                                          counters=counters)}))
                return
            snap = tier_snapshot("placement")
            placement["counters"] = {name: v for name, v in snap.items()
                                     if name.startswith("placement.")}
            self.push("admin", {"rid": rid, "placement": placement})
        elif t == "admin_migrate_doc":
            # live migration trigger: move the doc's PARTITION to the
            # named core. Synchronous ON the event loop by design — the
            # single-threaded seal→fence→handoff cannot interleave with
            # a submit frame, which is the no-two-writers proof for the
            # in-process window (deli's epoch fence covers the rest).
            # Not in the no-secret mutating set (like admin_summarize):
            # it moves state the deployment already holds, creates none.
            sh = front.shard_host
            if sh is None:
                raise ValueError("not a sharded core")
            from .stage_runner import doc_partition

            tenant, doc = frame["tenant"], frame["doc"]
            k = doc_partition(tenant, doc, sh.n)
            op_id = sh.journal.emit(
                "operator.command", command=t, tenant=tenant, doc=doc,
                part=k, target=frame["target"])
            result = front.migration_engine.migrate(
                k, frame["target"], on_flip=front._on_migration_flip,
                cause=op_id)
            self.push("admin", {"rid": rid, **result})
        elif t == "admin_adopt_partition":
            # core→core handoff target side (MigrationEngine._rpc_adopt)
            sh = front.shard_host
            if sh is None:
                raise ValueError("not a sharded core")
            result = front.migration_engine.adopt(
                int(frame["k"]), frame["from_owner"],
                cause=frame.get("journal_cause"),
                log_blob=frame.get("log_blob"))
            self.push("admin", {"rid": rid, **result})
        elif t == "admin_core_heat":
            # read-only: this core's windowed per-partition heat — the
            # rebalancer's fleet scrape AND the `admin placement heat`
            # table both read this; a failed dial here marks the core
            # unreachable (never a migration target)
            sh = front.shard_host
            if sh is None:
                self.push("admin", {"rid": rid, "heat": None})
                return
            from .rebalancer import HEAT_WINDOW_S, read_local_heat

            heat = read_local_heat(list(sh.servers))
            self.push("admin", {"rid": rid, "heat": {
                "owner": sh.owner_id,
                "addr": sh.address,
                "draining": bool(getattr(sh, "draining", False)),
                "window_s": HEAT_WINDOW_S,
                "parts": {str(k): {"ops": round(h.ops, 3),
                                   "bytes": round(h.bytes, 3)}
                          for k, h in sorted(heat.items())},
            }})
        elif t == "admin_tier_snapshot":
            # read-only: one tier's per-process counter sums — the
            # fleet-aggregation building block (obs.sum_counter_snapshots
            # over every core's reply = fleet totals)
            from ..obs import tier_snapshot

            self.push("admin", {
                "rid": rid,
                "counters": tier_snapshot(str(frame["tier"]))})
        elif t == "admin_rebalance_status":
            # read-only: the loop's own account of itself (armed, last
            # plan, suppressions, flap count) + optional fleet counters
            reb = front.rebalancer
            status = (reb.status() if reb is not None
                      else {"armed": False})
            if frame.get("fleet") and front.shard_host is not None:
                # same off-loop treatment as admin_placement: the peer
                # fan-out must not stall the loop
                table_rec = front.shard_host.table.read()
                self._reply_offloop(
                    rid,
                    lambda: front._fleet_placement_counters(table_rec),
                    lambda counters: self.push("admin", {
                        "rid": rid,
                        "rebalance": dict(status,
                                          fleet_counters=counters)}))
                return
            self.push("admin", {"rid": rid, "rebalance": status})
        elif t == "admin_placement_drain":
            # mark a member draining: every rebalancer tick on that core
            # now evacuates its partitions (dwell/threshold exempt) until
            # it owns nothing and flips itself to drained
            sh = front.shard_host
            if sh is None:
                raise ValueError("not a sharded core")
            from .placement_plane import CORE_DRAINING

            op_id = sh.journal.emit("operator.command", command=t,
                                    owner=frame["owner"])
            ok = sh.table.set_core_state(frame["owner"], CORE_DRAINING,
                                         cause=op_id)
            if not ok:
                raise ValueError(
                    f"unknown core {frame['owner']!r} (not registered)")
            self.push("admin", {"rid": rid, "ok": True,
                                "owner": frame["owner"]})
        elif t == "admin_migrate_part":
            # partition-addressed migration trigger (admin_migrate_doc's
            # sibling): the rebalancer daemon actuates through a loopback
            # RPC to THIS handler so the seal→fence→handoff runs on the
            # event loop — same single-threaded no-two-writers proof as
            # the operator door
            sh = front.shard_host
            if sh is None:
                raise ValueError("not a sharded core")
            # a rebalancer loopback carries its actuation entry id as
            # journal_cause; a bare operator call roots its own chain
            cause = frame.get("journal_cause") or sh.journal.emit(
                "operator.command", command=t, part=int(frame["k"]),
                target=frame["target"])
            result = front.migration_engine.migrate(
                int(frame["k"]), frame["target"],
                on_flip=front._on_migration_flip, cause=cause)
            self.push("admin", {"rid": rid, **result})
        elif t == "admin_journal":
            # read-only: this core's audit-journal tail (the `admin
            # journal` CLI and the fleet merge both read this); a
            # disarmed journal answers empty rather than erroring so a
            # fleet fan-out over mixed deployments still completes
            jr = get_journal()
            part = frame.get("part")
            entries = jr.tail(
                n=int(frame.get("n", 100)),
                kind=frame.get("kind") or None,
                doc=frame.get("doc"),
                part=int(part) if part is not None else None)
            self.push("admin", {"rid": rid, "journal": {
                "armed": jr.armed, "core": jr.core, "path": jr.path,
                "entries": entries}})
        elif t == "admin_metrics_history":
            # read-only: the windowed series' retained history rings.
            # Points are stamped on THIS process's monotonic clock, so
            # both clocks ride along and the caller rebases:
            # wall = now_wall - (now_mono - t)
            self.push("admin", {
                "rid": rid,
                "history": get_registry().window_history(
                    frame.get("name")),
                "now_mono": time.monotonic(),
                "now_wall": time.time()})
        elif t == "admin_boot_status":
            # read-only: this core's cold-start rehydration progress —
            # per-partition booted/pending docs, executor depth, and the
            # process's boot.part.* counters (tier-summed: the orderers
            # count on their own frontend-tier sheet, not front.counters)
            from ..obs import tier_snapshot

            boot_counts = {k: v
                           for k, v in tier_snapshot("frontend").items()
                           if k.startswith(("boot.part.", "topology."))}
            sh = front.shard_host
            if sh is not None:
                parts = [s.boot_status()
                         for _, s in sorted(sh.servers.items())]
                rehydrator = sh.rehydrator
                owner = sh.owner_id
            else:
                parts = [front.server.boot_status()]
                rehydrator = front.server.rehydrator
                owner = None
            self.push("admin", {"rid": rid, "boot": {
                "owner": owner,
                "parts": parts,
                "executor": (rehydrator.status()
                             if rehydrator is not None else None),
                "counters": boot_counts}})
        elif t == "admin_flight_dump":
            # operator door onto the flight recorder: dump the rings NOW
            # (incident in progress, evidence wanted before it scrolls
            # out) and journal the dump so the bundle joins both
            jr = get_journal()
            op_id = jr.emit("operator.command", command=t,
                            reason=frame.get("reason") or "operator")
            path = get_recorder().dump(
                "operator", detail=frame.get("reason") or "operator")
            dump_id = jr.emit("flight.dump", cause=op_id,
                              reason="operator", path=path)
            self.push("admin", {"rid": rid, "path": path,
                                "journal": dump_id})
        elif t == "admin_health":
            # read-only: the streaming doctor's live verdict for this
            # core (and, with fleet=1, every peer's — worst verdict
            # wins). An unarmed core answers verdict="unknown" rather
            # than erroring so a fan-out over a mixed deployment
            # (some cores without --probe) still completes.
            engine = front.health_engine
            if engine is not None:
                local = dict(engine.status(), armed=True)
            else:
                owner = (front.shard_host.owner_id
                         if front.shard_host is not None else "")
                local = {"core": owner, "verdict": "unknown",
                         "components": {}, "armed": False}
            if not frame.get("fleet"):
                self.push("admin", {"rid": rid, "health": local})
                return
            sh = front.shard_host
            rec = sh.table.read() if sh is not None else {}
            # each peer is a synchronous socket dial with a
            # multi-second timeout: fan out off-loop, push the
            # aggregate from the done-callback (admin_placement's
            # --fleet pattern)
            self._reply_offloop(
                rid, lambda: front._fleet_health(rec, local),
                lambda health: self.push(
                    "admin", {"rid": rid, "health": health}))

    def _unsubscribe_ftopic(self, topic: str) -> None:
        entry = self._ftopics.pop(topic, None)
        if entry is not None:
            on_batch, on_signal, sig_topic, server, on_presence = entry
            pubsub = server.pubsub
            pubsub.unsubscribe(topic, on_batch)
            pubsub.unsubscribe(sig_topic, on_signal)
            self.front.presence.unsubscribe(topic, on_presence)

    def drop_server(self, server) -> None:
        """Tear down everything this session holds on a revoked
        partition server (lease lost): direct connections close the
        socket (the client auto-reconnects to the takeover owner);
        gateway-muxed sids get an ``fdropped`` so the gateway closes
        just THAT client, not the whole backbone."""
        if self.conn is not None and self.conn.server is server:
            self.closed()
            try:
                self.writer.close()
            except Exception:
                pass
            return
        for sid in [s for s, c in self._fsessions.items()
                    if c.server is server]:
            conn = self._fsessions.pop(sid)
            conn.disconnect()
            topic = self._fsession_topics.pop(sid, None)
            if topic is not None:
                self._ftopic_refs[topic] -= 1
                if self._ftopic_refs[topic] == 0:
                    del self._ftopic_refs[topic]
                    self._unsubscribe_ftopic(topic)
            self.push("fdropped", {"sid": sid})

    def closed(self) -> None:
        if self.conn is not None:
            self.front._dirty_servers.add(self.conn.server)
            self.conn.disconnect()
            self.conn = None
        for conn in self._fsessions.values():
            self.front._dirty_servers.add(conn.server)
            conn.disconnect()
        self._fsessions.clear()
        self._fsession_topics.clear()
        self._ftopic_refs.clear()
        for topic in list(self._ftopics):
            self._unsubscribe_ftopic(topic)
        for topic, fn in self._presence:
            self.front.presence.unsubscribe(topic, fn)
        self._presence.clear()


class ShardHost:
    """A core process's claim over doc partitions (VERDICT r4 #4).

    Ref: memory-orderer/src/reservationManager.ts:21 + remoteNode.ts:92 —
    the reference's multi-node orderer leases documents and routes
    connections to the owner. Here the lease unit is the doc partition;
    partition ``k``'s pipeline is durable in ``<shard_dir>/log-<k>`` and
    whoever holds the lease resumes it from its checkpoints (the same
    restart path a single-core kill -9 recovery uses). ``prefer`` seeds
    the initial placement: non-preferred partitions are only claimed
    after the lease TTL grace (i.e. takeover of a dead peer).
    """

    def __init__(self, shard_dir: str, n: int, prefer=(),
                 storage_server=None, ttl_s: float = None,
                 table_client=None, host_id: Optional[str] = None,
                 claim_policy: Optional[str] = None):
        import os
        import uuid

        from .placement import DEFAULT_TTL_S

        self.shard_dir = shard_dir
        self.n = n
        self.prefer = set(prefer)
        self.storage_server = storage_server
        self.owner_id = uuid.uuid4().hex[:8]
        self.address: Optional[str] = None  # set once the port is bound
        # placement plane behind the TableClient split (table_client.py):
        # local (the raw flock-backed PlacementDir + EpochTable — zero
        # indirection) unless a remote client was injected, in which case
        # every lease/table op is an RPC into the placement host's table
        # door and the flock runs THERE. Either way ``self.placement`` /
        # ``self.table`` keep their historical shapes, so the fencing
        # layers below are implementation-blind.
        if table_client is None:
            from .table_client import LocalTableClient

            table_client = LocalTableClient(
                shard_dir, n,
                ttl_s if ttl_s is not None else DEFAULT_TTL_S)
        self.table_client = table_client
        self.placement = table_client.leases
        self.table = table_client.table
        # multi-host fleets: which host group this core runs in (None =
        # classic single-host). Advertised in the table's cores rows for
        # the rebalancer's locality tiebreak and gateway accounting.
        self.host_id = host_id
        # "prefer" pins this core to its preferred partitions — it never
        # claims outside them, not even stale leases. Multi-host fleets
        # without log replication run this way: a partition's durable
        # log lives in ONE host group's dir, so a cross-host takeover
        # (unlike a cross-host MIGRATION, which ships the log) could not
        # resume it. claim_policy=None/"any" is the historical behavior.
        self.claim_policy = claim_policy or "any"
        # epoch under which this host claimed each owned partition vs
        # the latest table epoch seen for it (refreshed once per poll):
        # table newer than claim ⇒ someone adopted it ⇒ deli's epoch
        # fence refuses with the current epoch (see _make_server)
        self.claim_epochs: dict[int, int] = {}
        self.table_epochs: dict[int, int] = {}
        # partitions mid-migration: poll must not re-claim them
        self.migrating: set[int] = set()
        # shared secret for core→core adoption RPCs (uniform deployment)
        self.admin_secret: Optional[str] = None
        self.servers: dict[int, LocalServer] = {}
        # ONE TenantManager shared by every partition server of this
        # process (including ones claimed later by takeover), kept in
        # sync with the DEPLOYMENT-WIDE registry file
        # <shard_dir>/tenants.json: admin tenant-add on any core secures
        # every core — other processes pick the file up on their next
        # lease poll, and a core started later loads it at boot. A
        # late-claimed or freshly-started tenant-less server would
        # otherwise silently accept unsigned connects (riddler's
        # tenantManager role, but file-backed like the leases).
        from .tenants import TenantManager

        self.tenants = TenantManager()
        self._tenants_path = os.path.join(shard_dir, "tenants.json")
        self._tenants_mtime = None
        self._reload_tenants()
        self._start_t = None
        # monotonic time of the last CONFIRMED lease per partition (the
        # fencing clock — see _make_server)
        self.hb_times: dict[int, float] = {}
        # fired as on_drop(k, server) AFTER a lost partition is revoked —
        # the front end closes the partition's live sessions so clients
        # reconnect to the takeover owner
        self.on_drop = None
        # control-plane audit journal (obs/journal.py): the process
        # singleton, disarmed (free) unless main() armed it — lease
        # lifecycle events land here next to the epoch bumps the table
        # itself records
        self.journal = get_journal()
        # elastic membership: set from the epoch table's cores section
        # each poll — a draining host claims nothing (the rebalancer
        # evacuates what it still owns)
        self.draining = False
        # fleet cold start (service/rehydrate.py): claiming a partition
        # builds NO doc pipelines; first routes boot O(snapshot+tail).
        # The rehydrator — when the front end arms one — bounds a boot
        # storm by parking excess first-routes on the retry lane.
        self.lazy_boot = True
        self.rehydrator = None
        self._cold_boot_noted = False

    def _make_server(self, k: int) -> LocalServer:
        import os
        import time

        from .durable_log import DurableLog

        log = DurableLog(os.path.join(self.shard_dir, f"log-{k}"))
        server = LocalServer(log=log, storage_server=self.storage_server,
                             tenants=self.tenants)
        # lease fencing: orders are refused unless the lease was
        # confirmed within 75% of the TTL — a stalled-and-resumed
        # process fails this check on its first buffered frame, before
        # its heartbeat loop has even run (see LocalServer.lease_fresh)
        margin = self.placement.ttl_s * 0.75
        server.lease_fresh = (
            lambda k=k, margin=margin:
            time.monotonic() - self.hb_times.get(k, 0.0) < margin)
        # placement epoch fence (deli admission): pure dict compares on
        # the hot path — the table file is read once per poll
        server.epoch_fence = (
            lambda k=k: (self.table_epochs[k]
                         if (self.table_epochs.get(k, 0)
                             > self.claim_epochs.get(k, 0))
                         else None))
        # which partition this server sequences — the front end's heat
        # recording labels the windowed series with it
        server.part_k = k
        server.lazy_boot = self.lazy_boot
        server.rehydrator = self.rehydrator
        if self.lazy_boot:
            pending = server.scan_boot_pending()
            if pending and not self._cold_boot_noted:
                # cold start: docs exist on disk and none are booted —
                # journal the recovery shape once per process
                self._cold_boot_noted = True
                self.journal.emit("core.cold_boot", owner=self.owner_id,
                                  part=k, docs_pending=pending)
        return server

    def _reload_tenants(self) -> None:
        import json
        import os

        try:
            mtime = os.stat(self._tenants_path).st_mtime_ns
        except OSError:
            return
        if mtime == self._tenants_mtime:
            return
        self._tenants_mtime = mtime
        try:
            with open(self._tenants_path) as f:
                self.tenants.replace_all(json.load(f))
        except (OSError, ValueError):
            pass  # mid-replace race: next poll rereads

    def save_tenants(self) -> None:
        """Persist the registry for the OTHER cores (atomic replace)."""
        import json
        import os

        tmp = self._tenants_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.tenants._secrets, f)
        os.replace(tmp, self._tenants_path)
        try:
            self._tenants_mtime = os.stat(
                self._tenants_path).st_mtime_ns
        except OSError:
            pass

    def poll(self) -> None:
        """Heartbeat owned partitions; claim unowned/stale ones."""
        import time

        self._reload_tenants()
        # refresh the epoch-fence view (one mtime-cached file read);
        # writes are flock-ordered, so this can only move forward
        self.table_epochs = self.table.part_epochs()
        if self.address:
            # membership: advertise this core (no-op when unchanged) and
            # pick up an operator drain mark — a draining host stops
            # claiming; the rebalancer evacuates what it still owns
            self.table.record_core(self.owner_id, self.address,
                                   host=self.host_id)
            from .placement_plane import CORE_DRAINED, CORE_DRAINING

            self.draining = self.table.core_state(self.owner_id) in (
                CORE_DRAINING, CORE_DRAINED)
        if self._start_t is None:
            self._start_t = time.monotonic()
        for k in list(self.servers):
            if k in self.migrating:
                continue  # the MigrationEngine owns k's lifecycle now
            if self.placement.heartbeat(k, self.owner_id):
                self.hb_times[k] = time.monotonic()
            else:
                # lease lost to a takeover: revoke (no further append
                # can reach the log this process no longer owns), then
                # let the front end tear down the live sessions. The
                # lease_fresh fence already refused orders the moment
                # the confirmation went stale, so there is no
                # two-writer window even if this heartbeat ran late.
                server = self.servers.pop(k)
                self.claim_epochs.pop(k, None)
                server.revoke()
                self.journal.emit("lease.takeover", part=k,
                                  lost_by=self.owner_id)
                if self.on_drop is not None:
                    self.on_drop(k, server)
        in_grace = (time.monotonic() - self._start_t
                    < self.placement.ttl_s + 0.5)
        if self.draining:
            return  # evacuating: never claim, not even takeovers
        for k in range(self.n):
            if k in self.servers or k in self.migrating:
                continue
            if k not in self.prefer and self.claim_policy == "prefer":
                continue  # pinned: this core's logs can't serve others
            if k not in self.prefer and in_grace:
                continue  # let the preferring core take it first
            if self.placement.try_claim(k, self.owner_id, self.address):
                claim_id = self.journal.emit(
                    "lease.claim", part=k, owner=self.owner_id,
                    takeover=k not in self.prefer)
                self.claim_epochs[k] = self.table.record_claim(
                    k, self.owner_id, self.address or "", cause=claim_id)
                self.table_epochs[k] = self.claim_epochs[k]
                self.hb_times[k] = time.monotonic()
                self.servers[k] = self._make_server(k)

    def release_all(self) -> None:
        for k in list(self.servers):
            self.placement.release(k, self.owner_id)
            rel_id = self.journal.emit("lease.release", part=k,
                                       owner=self.owner_id)
            self.table.record_release(k, self.owner_id, cause=rel_id)
            self.claim_epochs.pop(k, None)
        self.servers.clear()
        self.journal.emit("core.stop", owner=self.owner_id)


class NetworkFrontEnd:
    """Owns the LocalServer pipeline and serves it over TCP.

    ``start_background()`` runs the event loop (and thus the whole
    pipeline) on a dedicated thread — the in-process deployment.
    ``serve_forever()`` blocks — the subprocess deployment
    (``python -m fluidframework_tpu.service.front_end``).

    With ``shard_host`` set the process serves only the doc partitions
    whose leases it holds — ``server_for`` routes each frame to the
    partition's LocalServer and refuses docs this core doesn't own.
    """

    #: chaos seam (fluidframework_tpu/chaos): directives at
    #: ``snapshot.chunk`` corrupt ("torn") or withhold ("drop") a served
    #: chunk's WIRE bytes only — the encode-once cache and the durable
    #: blobs stay intact, so the client's hash check trips and its
    #: legacy-tree fallback still converges
    fault_plane = None

    def __init__(self, server: Optional[LocalServer] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 max_message_size: Optional[int] = None,
                 shard_host: Optional[ShardHost] = None,
                 admin_secret: Optional[str] = None):
        self.shard_host = shard_host
        self.admin_secret = admin_secret
        self.migration_engine = None
        if shard_host is not None:
            # config/tenants shell; never serves. Shares the shard
            # host's deployment-wide tenant registry so the admin
            # surface and enforcement checks see the same state.
            server = LocalServer(tenants=shard_host.tenants)
            from .placement_plane import MigrationEngine

            shard_host.admin_secret = admin_secret
            self.migration_engine = MigrationEngine(shard_host)
        self.server = server if server is not None else LocalServer()
        self.logger = self.server.logger.child("front_end")
        self.host = host
        self.port = port
        # service limits come from the unified config registry unless a
        # caller overrides explicitly
        self.max_message_size = (
            max_message_size if max_message_size is not None
            else self.server.config.max_message_size)
        # (key, [binwire raw | False, JSON raw]) — one entry, one slot
        # per wire format (see _ClientSession._push_op_batch)
        self._batch_cache: tuple = (None, [None, None])
        self._fops_cache: tuple = (None, b"")
        # (tenant, doc) → (version_id, {chunk_hash: framed bytes}, root):
        # the encode-once snapshot serving cache (_handle_snapshot_cols)
        self._snap_cache: dict = {}
        # service-summarizer loop (enable_summarizer): per-LocalServer
        # summarizer instances + the ops-per-summary threshold
        self.summarize_every: Optional[int] = None
        self._summarizers: dict = {}
        # socket-tier batching telemetry (net.ingress.*, net.flush.*,
        # net.fanout.*), served read-only by the admin_counters RPC and
        # aggregated under tier="frontend" by the registry scrape
        self.counters = tier_counters("frontend")
        # ephemeral signal tier: network-origin signals coalesce here
        # per (doc, client, type) and batch out on the presence tick —
        # they never touch deli or the durable log (service/presence.py)
        self.presence = PresenceLane(self.counters)
        # partition servers dirtied by the current ingress batch; the
        # batch flushes exactly these (see _flush_dirty)
        self._dirty_servers: set = set()
        # splice context of the binary submit currently on the stack
        # (handle_binary sets it around conn.submit)
        self._splice_ctx: Optional[tuple] = None
        # split-service composition (stage_runner.py): stage backchannel
        # logs this core consumes, and whether the shared log needs
        # visibility flushes for external consumers
        self._backchannels: list = []
        self._bg_tasks: list = []  # strong refs; the loop's are weak
        self._log_flush = (shard_host is not None
                           or hasattr(self.server.log, "flush"))
        # (tenant, doc) → applied seq reported by an applier stage
        self.applier_status: dict = {}
        # overload-control loop: the admission gate stays None (one
        # attribute check on the submit path) until a tenant rate or an
        # SLO engine is attached
        self.admission: Optional[AdmissionController] = None
        self.slo_engine = None
        # self-driving placement: armed by --rebalance (enable_rebalancer
        # stores the config; _start constructs the daemon once the port
        # is bound and the shard host registered in the epoch table)
        self.rebalancer = None
        self._rebalance_cfg: Optional[dict] = None
        # live health plane (--probe): a canary prober walking this
        # core's own doors + a HealthEngine running the doctor's rules
        # continuously. Config stored here; both start in _start once
        # the bound address exists (the canary dials it).
        self.prober = None
        self.health_engine = None
        self._health_cfg: Optional[dict] = None
        # live _ClientSessions (lease-loss teardown walks these)
        self._sessions: set = set()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._ready = threading.Event()
        self._aio_server: Optional[asyncio.base_events.Server] = None

    def enable_admission(self) -> AdmissionController:
        """The admission gate, created on first use; rates are re-read
        from the tenant registry per boxcar so runtime changes apply."""
        if self.admission is None:
            self.admission = AdmissionController(self._rate_for)
        return self.admission

    def _rate_for(self, tenant: str):
        tm = self.server.tenants
        return None if tm is None else tm.rate_for(tenant)

    def attach_slo(self, engine, shedding: bool = True) -> "NetworkFrontEnd":
        """Close the loop: the engine's windowed verdicts arm (or, with
        ``shedding=False``, merely observe) the admission gate."""
        self.slo_engine = engine
        adm = self.enable_admission()
        adm.engine = engine
        adm.shedding = shedding
        return self

    def enable_boot_admission(self, boots_per_s: float = 200.0,
                              burst: int = 32) -> "NetworkFrontEnd":
        """Arm boot-storm admission: one rehydration executor per core
        shared by every partition server (current AND late-claimed —
        ShardHost stamps it in _make_server)."""
        from .rehydrate import RehydrationExecutor

        ex = RehydrationExecutor(boots_per_s, burst)
        if self.shard_host is not None:
            self.shard_host.rehydrator = ex
            for s in self.shard_host.servers.values():
                s.rehydrator = ex
        else:
            self.server.rehydrator = ex
        return self

    def record_heat(self, server, n_ops: int, n_bytes: int) -> None:
        """Per-partition load accounting (the rebalancer's input): one
        windowed observe per admitted boxcar, labeled with the serving
        partition. Single-pipeline deployments have no ``part_k`` and
        record nothing — there is nowhere to rebalance to."""
        k = getattr(server, "part_k", None)
        if k is None:
            return
        from .rebalancer import HEAT_BYTES, HEAT_OPS

        reg = get_registry()
        reg.observe_windowed(HEAT_OPS, float(n_ops), part=str(k))
        if n_bytes:
            reg.observe_windowed(HEAT_BYTES, float(n_bytes), part=str(k))

    def enable_rebalancer(self, tick_s: float = 0.5,
                          dwell_s: float = 10.0, budget: int = 2,
                          improvement: float = 0.25) -> "NetworkFrontEnd":
        """Arm the self-driving placement loop (--rebalance). Stored as
        config here; the daemon itself starts in ``_start`` once the
        bound address exists (migration targets need it)."""
        if self.shard_host is None:
            raise ValueError("--rebalance requires a sharded core")
        self._rebalance_cfg = {
            "tick_s": tick_s, "dwell_s": dwell_s,
            "budget": budget, "improvement": improvement}
        return self

    @ticker_thread("rebalancer")
    def _rebalance_actuate(self, k: int, target_addr: str,
                           cause: Optional[str] = None) -> None:
        """Actuation seam for the rebalancer's ticker THREAD: a loopback
        ``admin_migrate_part`` RPC against our own event loop, so the
        seal→fence→handoff runs exactly where the operator door runs it
        (single-threaded, no submit frame can interleave). ``cause`` is
        the rebalance.actuate journal id; it rides the frame so the
        migration chain roots at the plan, not at the loopback RPC."""
        from .placement_plane import admin_rpc

        frame = {"t": "admin_migrate_part", "k": k, "target": target_addr}
        if cause is not None:
            frame["journal_cause"] = cause
        if self.admin_secret:
            frame["secret"] = self.admin_secret
        admin_rpc(self.host, self.port, frame)

    def enable_health(self, probe_tick_s: float = 2.0,
                      tick_s: float = 1.0, critical_ticks: int = 3,
                      probe_fail_critical: int = 3,
                      probe_timeout: float = 5.0,
                      max_route_peers: int = 2) -> "NetworkFrontEnd":
        """Arm the live health plane (--probe): stored as config here;
        the prober and engine start in ``_start`` once the socket is
        bound (the canary dials our own listening address) and — on a
        sharded core — the first poll has claimed partitions, so the
        canary doc routes."""
        self._health_cfg = {
            "probe_tick_s": probe_tick_s, "tick_s": tick_s,
            "critical_ticks": critical_ticks,
            "probe_fail_critical": probe_fail_critical,
            "probe_timeout": probe_timeout,
            "max_route_peers": max_route_peers}
        return self

    def _arm_health(self) -> None:
        """Construct + start the canary prober and the health engine.

        Every engine source closes over LIVE surfaces and returns the
        bundle-shaped artifact the doctor would read offline — that is
        what makes the offline/live equivalence test possible. The
        prober's transport is the driver's ``_Transport`` (a real
        client dial, not a shortcut into the pipeline: the probe must
        traverse the same socket, reader thread, and frame codec a
        user does)."""
        from ..driver.network import _Transport
        from ..obs.health import HealthEngine
        from ..obs.probe import CANARY_DOC, CanaryProber

        cfg = self._health_cfg or {}
        sh = self.shard_host
        owner = sh.owner_id if sh is not None else "fe"
        timeout = cfg.get("probe_timeout", 5.0)

        def dial(host, port, timeout=timeout):
            return _Transport(host, port, timeout=timeout)

        def doc_fn():
            # a canary doc routed to THIS core: sharded cores refuse
            # docs whose partition they don't own, so walk suffixes
            # until one hashes into our claims (None while we own
            # nothing — the session doors idle, not fail)
            if sh is None:
                return CANARY_DOC
            from .stage_runner import doc_partition

            owned = set(sh.servers)
            if not owned:
                return None
            for i in range(64):
                doc = f"{CANARY_DOC}{i}"
                if doc_partition(CANARY_TENANT, doc, sh.n) in owned:
                    return doc
            return None

        def peers_fn():
            if sh is None:
                return {}
            try:
                rec = sh.table.read()
            except Exception:  # noqa: BLE001 — table read is advisory
                return {}
            # membership is append-only (a capacity advertisement, not
            # a route): a kill -9'd core's row outlives it forever.
            # Route-probe only owners a gateway would actually traverse
            # — those holding ≥1 partition NOW — so a replaced core's
            # stale row stops counting against fleet health the moment
            # its partitions are re-claimed.
            routed = {p.get("owner")
                      for p in (rec.get("parts") or {}).values()}
            return {o: {"addr": row.get("addr"),
                        "host": row.get("host")}
                    for o, row in (rec.get("cores") or {}).items()
                    if o in routed}

        def token_fn(tenant, doc):
            # canary auth: mint against a per-process secret, and
            # re-assert the registration per mint — the shared-registry
            # reload on the lease poll replaces the dict and would
            # silently drop us. On open (dev-mode) deployments we must
            # NOT register: the first registration flips tenancy to
            # enforcing and locks every real client out.
            tm = self.server.tenants
            if tm is None or not tm.enforcing:
                return None
            from .tenants import sign_token

            tm.register(CANARY_TENANT, self._canary_secret)
            return sign_token(tenant, doc, self._canary_secret)

        import secrets as _secrets

        self._canary_secret = _secrets.token_hex(16)
        self.prober = CanaryProber(
            dial, self.host, self.port, core=owner,
            doc_fn=doc_fn,
            peers_fn=peers_fn if sh is not None else None,
            token_fn=token_fn,
            tick_s=cfg.get("probe_tick_s", 2.0), timeout=timeout,
            snapshot=True,
            max_route_peers=cfg.get("max_route_peers", 2)).start()

        def boot_fn():
            from ..obs import tier_snapshot

            if sh is not None:
                parts = [s.boot_status()
                         for _, s in sorted(sh.servers.items())]
                rehydrator = sh.rehydrator
            else:
                parts = [self.server.boot_status()]
                rehydrator = self.server.rehydrator
            return {"parts": parts,
                    "executor": (rehydrator.status()
                                 if rehydrator is not None else None),
                    "counters": {k: v for k, v in
                                 tier_snapshot("frontend").items()
                                 if k.startswith("boot.part.")}}

        def slo_fn():
            eng = self.slo_engine
            return {"slos": eng.status() if eng is not None else []}

        self.health_engine = HealthEngine(
            core=owner,
            scrape_fn=get_registry().scrape,
            journal_fn=lambda: get_journal().tail(n=400),
            placement_fn=(sh.table.read if sh is not None else None),
            cores_fn=self.prober.peer_rows,
            slo_fn=slo_fn,
            boot_fn=boot_fn,
            probe_fn=self.prober.status,
            # a deliberately-unarmed journal (in-process fleets,
            # bare dev cores) is config, not a failure: report
            # journal_armed only when it IS armed, so the doctor's
            # disarmed rule (written for bundles, where a core that
            # SHOULD journal didn't) stays quiet live
            self_row_fn=lambda: (
                {"journal_armed": True} if get_journal().armed else {}),
            tick_s=cfg.get("tick_s", 1.0),
            critical_ticks=cfg.get("critical_ticks", 3),
            probe_fail_critical=cfg.get("probe_fail_critical", 3),
        ).start()

    def _fleet_health(self, table_rec: dict, local: dict) -> dict:
        """Fleet verdict: this core's health joined with every peer
        core's (``admin_health`` fan-out) — worst verdict wins, and an
        UNREACHABLE peer is critical, not skipped: the go/no-go gate
        must fail closed, a dead core cannot answer "I'm fine"."""
        from .placement_plane import admin_rpc

        order = {"ok": 0, "unknown": 1, "degraded": 2, "critical": 3}
        self_owner = (self.shard_host.owner_id
                      if self.shard_host is not None else None)
        cores = {local.get("core") or "": local}
        worst = local.get("verdict", "unknown")
        # same routed-owner filter as the prober's peers_fn: membership
        # rows never expire, so gate only on cores that currently hold
        # partitions — a kill -9'd core's stale row must not hold the
        # fleet at critical after its replacement claimed its parts
        routed = {p.get("owner")
                  for p in (table_rec.get("parts") or {}).values()}
        for owner, row in sorted(
                (table_rec.get("cores") or {}).items()):
            if owner == self_owner or owner not in routed:
                continue
            host_s, _, port_s = row.get("addr", "").rpartition(":")
            frame = {"t": "admin_health"}
            if self.admin_secret:
                frame["secret"] = self.admin_secret
            try:
                reply = admin_rpc(host_s or "127.0.0.1", int(port_s),
                                  frame, timeout=5.0)
                h = dict(reply.get("health") or {})
                h.setdefault("core", owner)
            except (OSError, ValueError, RuntimeError) as e:
                h = {"core": owner, "verdict": "critical",
                     "armed": False,
                     "reasons": [f"core {owner}: admin_health "
                                 f"unreachable ({e})"]}
            cores[owner] = h
            if (order.get(h.get("verdict"), 1)
                    > order.get(worst, 1)):
                worst = h.get("verdict")
        return {"fleet": True, "verdict": worst, "cores": cores}

    def _fleet_placement_counters(self, table_rec: dict) -> dict:
        """Fleet-total placement counters: this process's snapshot summed
        with every reachable peer core's (``admin_tier_snapshot``)."""
        from ..obs import sum_counter_snapshots, tier_snapshot
        from .rebalancer import peer_tier_snapshots

        snaps = [tier_snapshot("placement")]
        if self.shard_host is not None:
            snaps.extend(peer_tier_snapshots(
                table_rec, self.shard_host.owner_id, "placement",
                secret=self.admin_secret))
        total = sum_counter_snapshots(snaps)
        return {name: v for name, v in total.items()
                if name.startswith("placement.")}

    def server_for(self, tenant: str, doc: str) -> LocalServer:
        """The LocalServer serving this doc: the single pipeline, or the
        doc partition's server in a sharded core (which refuses docs
        whose lease this process doesn't hold — the gateway routes)."""
        if self.shard_host is None:
            return self.server
        from .stage_runner import doc_partition

        k = doc_partition(tenant, doc, self.shard_host.n)
        server = self.shard_host.servers.get(k)
        if server is None:
            raise RuntimeError(f"not the owner of partition {k}")
        return server

    def _all_servers(self):
        if self.shard_host is not None:
            return list(self.shard_host.servers.values())
        return [self.server]

    def _flush_logs(self) -> None:
        for server in self._all_servers():
            if hasattr(server.log, "flush"):
                server.log.flush()

    def _flush_dirty(self) -> None:
        """Flush only the logs the current ingress batch dirtied.

        The old per-frame path flushed EVERY partition's log on every
        frame — at 2 cores each frame paid for all shards (the sharded
        regression's prime suspect). Read-only batches (pings, storage
        RPCs, signals) flush nothing at all."""
        dirty = self._dirty_servers
        n_all = (len(self.shard_host.servers)
                 if self.shard_host is not None else 1)
        if not dirty:
            self.counters.inc("net.flush.elided", n_all)
            return
        flushed = 0
        for server in dirty:
            log = server.log
            if hasattr(log, "flush"):
                try:
                    log.flush()
                except OSError:
                    continue  # partition revoked mid-teardown
                flushed += 1
        dirty.clear()
        self.counters.inc("net.flush.performed", flushed)
        if n_all > flushed:
            self.counters.inc("net.flush.elided", n_all - flushed)

    def _on_migration_flip(self, k: int, target_addr: str, epoch: int,
                           server) -> None:
        """Post-handoff routing flip (MigrationEngine ``on_flip``, on the
        loop thread): push the new route to every gateway backbone FIRST
        — their routing caches patch in-memory, so the reconnects that
        the session drop below triggers resolve to the target without a
        table read — then tear down the sealed partition's sessions
        (direct clients reconnect, gateway sids get ``fdropped``)."""
        route = {"k": k, "addr": target_addr, "epoch": epoch}
        for session in list(self._sessions):
            if session._fsessions or session._ftopics:
                try:
                    session.push("fplacement", route)
                except Exception as e:  # noqa: BLE001
                    self.logger.error("fplacement_push_error",
                                      message=str(e))
        self._drop_server_sessions(server)

    def _drop_server_sessions(self, server) -> None:
        """Close every live session bound to a revoked partition server
        (runs on the loop thread via call_soon_threadsafe)."""
        self._dirty_servers.discard(server)
        for session in list(self._sessions):
            try:
                session.drop_server(server)
            except Exception as e:  # noqa: BLE001
                self.logger.error("drop_session_error", message=str(e))

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        import socket as _socket

        sock = writer.get_extra_info("socket")
        if sock is not None:
            # small latency-bound frames: disable Nagle coalescing
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        session = _ClientSession(self, writer)
        self._sessions.add(session)
        counters = self.counters
        recorder = get_recorder()
        conn_id = f"fe-{id(session) & 0xFFFFFF:06x}"
        try:
            while True:
                body = await _read_body(reader)
                if body is None:
                    break
                # drain-batched serving: every frame already buffered on
                # this socket is handled as ONE batch, then the dirtied
                # logs flush and the writer drains once for the whole
                # wave — the old per-frame flush+drain was the dominant
                # fixed cost of the socket tier. The cap keeps one hot
                # connection from starving its peers on the loop.
                n = 0
                deferred: list = []
                while body is not None:
                    n += 1
                    recorder.frame(conn_id, "in", body)
                    if binwire.is_binary(body):
                        session.handle_binary(body)
                    else:
                        frame = json.loads(body.decode())
                        if frame.get("t") in _BULK_FRAMES:
                            # lane priority: bulk backfill yields to the
                            # interactive ops of the same wave — a
                            # catch-up client's multi-MB range read must
                            # not sit between a submit and its ack
                            deferred.append(frame)
                        else:
                            session.handle(frame)
                    body = None
                    if n < 64 and _frame_buffered(reader):
                        # completes synchronously — the bytes are
                        # already in the stream buffer
                        body = await _read_body(reader)
                for frame in deferred:
                    session.handle(frame)
                if deferred:
                    counters.inc("net.ingress.deprioritized",
                                 len(deferred))
                counters.inc("net.ingress.frames", n)
                counters.inc("net.ingress.batches")
                if n > 1:
                    counters.inc("net.ingress.coalesced", n - 1)
                if self._log_flush:
                    # make this batch's appends visible to the stage
                    # processes tailing the shared log
                    self._flush_dirty()
                await writer.drain()
        except (ValueError, json.JSONDecodeError):
            pass  # malformed stream: drop the connection
        except (ConnectionResetError, BrokenPipeError):
            pass  # client died mid-frame: treat like a clean close
        except Exception as e:  # noqa: BLE001 — unhandled tier failure:
            # the per-frame handlers catch their own errors, so anything
            # arriving here escaped the serving machinery itself. Dump
            # the flight rings before dropping the connection.
            self.logger.error("conn_unhandled", message=str(e))
            try:
                path = recorder.dump("frontend_unhandled", conn=conn_id,
                                     error=str(e))
                get_journal().emit("flight.dump",
                                   reason="frontend_unhandled",
                                   path=path, conn=conn_id)
            except Exception:
                pass
        finally:
            self._sessions.discard(session)
            session.closed()
            if self._log_flush:
                # the teardown's leave records must reach the log too
                self._flush_dirty()
            try:
                writer.close()
            except Exception:
                pass

    def attach_backchannel(self, state_dir: str) -> None:
        """Consume a stage process's backchannel log (stage_runner.py):
        summary ack/nack raw messages are ordered into the stream,
        version commits land through the orderer's ref path, retention
        advances truncate, applier status is recorded."""
        from .durable_log import DurableLog
        from .stage_runner import BACKCHANNEL_TOPIC

        bc = DurableLog(state_dir, readonly=True)
        bc.subscribe(BACKCHANNEL_TOPIC, self._on_backchannel_record)
        self._backchannels.append(bc)

    def _on_backchannel_record(self, message) -> None:
        rec = message.value
        kind = rec.get("kind")
        tenant, doc = rec["tenant"], rec["doc"]
        orderer = self.server._get_orderer(tenant, doc)
        if kind == "raw":
            orderer.order(rec["raw"])
            self.server._maybe_drain()
        elif kind == "version":
            orderer.commit_external_version(rec["handle"], rec["version"])
        elif kind == "retention":
            orderer.apply_retention(rec["capture_seq"])
        elif kind == "applied":
            self.applier_status[(tenant, doc)] = rec["applied_seq"]
            # hoptail thread across the process boundary: the applier
            # stage's stage/execute wall stamps fold into THIS core's
            # registry so net_hop_breakdown attributes device dispatch
            hops = rec.get("wave_hops")
            if hops and len(hops) == 2:
                ms = (hops[1] - hops[0]) * 1e3
                reg = get_registry()
                reg.observe("obs.hop.ms", ms, pair="stage_to_execute")
                reg.observe_windowed("obs.hop.window_ms", ms,
                                     pair="stage_to_execute")

    def enable_summarizer(self, every: int) -> "NetworkFrontEnd":
        """Arm the threshold-driven service-summarizer loop: every doc
        whose stream advanced ≥ ``every`` sequenced ops since its last
        summary gets a columnar snapcols summary (host-replica content
        source — no device applier in this process)."""
        self.summarize_every = every
        return self

    def _summarizer_for(self, server):
        summ = self._summarizers.get(id(server))
        if summ is None:
            from .service_summarizer import HostReplicaSource, ServiceSummarizer

            summ = ServiceSummarizer(
                server, HostReplicaSource(server),
                ops_per_summary=self.summarize_every)
            self._summarizers[id(server)] = summ
        return summ

    async def _presence_loop(self) -> None:
        """The presence tick: drain the LWW store to watchers. Runs on
        the serving loop AFTER any already-queued op pushes, so presence
        never overtakes a sequenced op it followed."""
        lane = self.presence
        while True:
            await asyncio.sleep(lane.flush_interval)
            try:
                lane.flush()
            except Exception as e:  # noqa: BLE001
                self.logger.error("presence_flush_error", message=str(e))

    async def _summarize_loop(self, interval: float = 0.05) -> None:
        while True:
            try:
                for server in self._all_servers():
                    by_tenant: dict = {}
                    for key in list(server._orderers):
                        tenant, _, doc = key.partition("/")
                        by_tenant.setdefault(tenant, []).append(doc)
                    summ = self._summarizer_for(server)
                    wrote = 0
                    for tenant, docs in by_tenant.items():
                        wrote += summ.run_pass(tenant, docs)
                    if wrote and self._log_flush and \
                            hasattr(server.log, "flush"):
                        server.log.flush()
                    if wrote:
                        get_journal().emit(
                            "summary.commit", docs=wrote,
                            part=getattr(server, "part_k", None))
            except Exception as e:  # noqa: BLE001 — the loop must outlive
                # one doc's refusal/IO error
                self.logger.error("summarize_loop_error", message=str(e))
            await asyncio.sleep(interval)

    async def _poll_backchannels(self) -> None:
        while True:
            moved = False
            for bc in self._backchannels:
                try:
                    if bc.poll():
                        bc.drain()
                        moved = True
                except Exception as e:  # noqa: BLE001
                    # drain advances the cursor BEFORE the handler runs,
                    # so continuing resumes at the NEXT record — one
                    # poisoned record must not kill every stage's
                    # pipeline (it used to: the task died silently)
                    self.logger.error("backchannel_record_error",
                                      message=str(e))
                    moved = True
            if moved and self._log_flush:
                # acks ordered above must become visible to the stages
                self.server.log.flush()
            await asyncio.sleep(0.002)

    async def _start(self) -> None:
        # deep backlog: load tests open hundreds of connections at once,
        # and an overflowing accept queue turns into 1-3 s SYN
        # retransmission outliers in the latency measurement
        self._aio_server = await asyncio.start_server(
            self._handle_conn, self.host, self.port, backlog=1024)
        self.port = self._aio_server.sockets[0].getsockname()[1]
        if self._backchannels:
            # the loop holds only a WEAK ref to tasks: an unreferenced
            # poller is garbage-collected at an arbitrary gc cycle and
            # backchannel consumption silently stops (the round-4
            # full-composition failure — summary acks never returned)
            self._bg_tasks.append(asyncio.get_running_loop().create_task(
                self._poll_backchannels()))
        if self.summarize_every is not None:
            self._bg_tasks.append(asyncio.get_running_loop().create_task(
                self._summarize_loop()))
        self._bg_tasks.append(asyncio.get_running_loop().create_task(
            self._presence_loop()))
        if self.shard_host is not None:
            loop = asyncio.get_running_loop()

            def on_drop(k, server, loop=loop):
                # poll may run on an executor thread: hop to the loop
                loop.call_soon_threadsafe(self._drop_server_sessions,
                                          server)
            self.shard_host.on_drop = on_drop
            self.shard_host.address = f"{self.host}:{self.port}"
            self.shard_host.poll()  # claim preferred partitions NOW
            if self._rebalance_cfg is not None:
                # armed after the first poll: the bound address is in the
                # epoch table's membership, so peers can target us; the
                # ticker thread actuates via loopback admin RPCs
                from .rebalancer import Rebalancer

                self.rebalancer = Rebalancer(
                    self.shard_host, self.migration_engine,
                    slo_engine=self.slo_engine,
                    actuate=self._rebalance_actuate,
                    secret=self.admin_secret,
                    **self._rebalance_cfg).start()

            async def lease_loop():
                interval = self.shard_host.placement.ttl_s / 3.0
                while True:
                    await asyncio.sleep(interval)
                    try:
                        # takeover construction replays the partition's
                        # durable log — off the event loop, so live
                        # sessions on OTHER partitions never stall
                        await loop.run_in_executor(None,
                                                   self.shard_host.poll)
                    except Exception as e:  # noqa: BLE001
                        self.logger.error("lease_poll_error",
                                          message=str(e))
            self._bg_tasks.append(loop.create_task(lease_loop()))
        if self._health_cfg is not None:
            # after the first poll: the canary doc must route to a
            # claimed partition, and peers must see our address
            self._arm_health()
        self._ready.set()

    def start_background(self) -> "NetworkFrontEnd":
        def run():
            loop = asyncio.new_event_loop()
            self._loop = loop
            asyncio.set_event_loop(loop)
            loop.run_until_complete(self._start())
            loop.run_forever()
            # drain pending callbacks, then close
            loop.run_until_complete(loop.shutdown_asyncgens())
            loop.close()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="fluid-front-end")
        self._thread.start()
        self._ready.wait(timeout=10)
        return self

    def stop(self) -> None:
        if self.rebalancer is not None:
            self.rebalancer.stop()
            self.rebalancer = None
        if self.prober is not None:
            self.prober.stop()
            self.prober = None
        if self.health_engine is not None:
            self.health_engine.stop()
            self.health_engine = None
        if self._loop is not None:
            loop = self._loop

            def _shutdown():
                if self._aio_server is not None:
                    self._aio_server.close()
                loop.stop()

            loop.call_soon_threadsafe(_shutdown)
            if self._thread is not None:
                self._thread.join(timeout=10)
            self._loop = None

    def serve_forever(self) -> None:
        import gc

        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        loop.run_until_complete(self._start())
        if not gc.isenabled():
            # The host disabled the cycle collector (see main()): sweep
            # accumulated cycles on a timer instead. Freeze after every
            # sweep, and sweep OFTEN (2 s): the sweep cost is one walk
            # over everything allocated since the last freeze, so the
            # cadence bounds the stall — at 30 s cadence with no freeze
            # the first sweep after a 10k-connection storm held the loop
            # ~1 s in the middle of steady-state traffic (the dominant
            # config-4 p99 tail); at 2 s each walk stays tens of ms and
            # the post-storm one lands while the deployment is still
            # settling. Frozen survivors are never rescanned — which
            # also means cyclic garbage that DIES after being frozen is
            # never reclaimed, so a long-lived core under connection
            # churn needs the rare FULL cycle below: unfreeze + collect
            # (one bounded stall every ~10 min) reclaims dead frozen
            # cycles and re-freezes the true survivors.
            sweep_n = [0]

            def _sweep():
                sweep_n[0] += 1
                if sweep_n[0] % 300 == 0:
                    gc.unfreeze()
                gc.collect()
                gc.freeze()
                loop.call_later(2.0, _sweep)
            loop.call_later(2.0, _sweep)
        if self._log_flush:
            # durable-log deployment: periodic pipeline checkpoints so a
            # killed core resumes from them (deli/scribe offsets +
            # scriptorium retention base ride the checkpoint topic)
            def _checkpoint():
                for server in self._all_servers():
                    server.checkpoint_all()
                self._flush_logs()
                loop.call_later(2.0, _checkpoint)
            loop.call_later(2.0, _checkpoint)
        # readiness marker for process supervisors / tests
        print(f"LISTENING {self.host}:{self.port}", flush=True)
        loop.run_forever()


def _apply_overload_flags(front: "NetworkFrontEnd", args, parser) -> None:
    """Arm the overload-control loop from the CLI flags: per-tenant
    rate caps into the tenant registry, SLO specs into a ticking
    engine attached to the admission gate."""
    if args.tenant_rate:
        from .tenants import TenantManager

        tm = front.server.tenants
        if tm is None:
            # rates alone must NOT flip tenancy to enforcing — the
            # registry stays secret-less (open auth) and only carries
            # the budgets
            tm = front.server.tenants = TenantManager()
            for server in front._all_servers():
                server.tenants = tm
        for spec in args.tenant_rate:
            parts = spec.split(":")
            try:
                tm.set_rate(parts[0], float(parts[1]),
                            float(parts[2]) if len(parts) > 2 else None)
            except (IndexError, ValueError):
                parser.error(f"bad --tenant-rate {spec!r} "
                             "(want ID:RATE[:BURST])")
        front.enable_admission()
    if args.slo:
        from ..obs.slo import SloEngine, parse_slo_spec

        try:
            specs = [parse_slo_spec(s) for s in args.slo]
        except ValueError as e:
            parser.error(str(e))
        front.attach_slo(SloEngine(specs).start(),
                         shedding=not args.no_shed)


def main() -> None:
    import gc

    parser = argparse.ArgumentParser(description="Fluid TPU network front end")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--max-message-size", type=int, default=None)
    parser.add_argument("--tenant", action="append", default=[],
                        metavar="ID:SECRET",
                        help="register a tenant (token auth enforced)")
    # split-service composition (stage_runner.py): the core owns the
    # durable log + sockets + deli/scriptorium/broadcaster; scribe and
    # the applier run as separate OS processes over the same log
    parser.add_argument("--log-dir", default=None,
                        help="durable C++ op log directory (this process "
                             "is its single writer)")
    parser.add_argument("--storage-dir", default=None,
                        help="native chunk-store directory for blobs")
    parser.add_argument("--storage-server", default=None, metavar="PORT",
                        help="route ALL storage to a storage_server.py "
                             "process (host:port or port on localhost)")
    parser.add_argument("--external-scribe", action="store_true",
                        help="scribe runs out of process; summary "
                             "uploads are announced on the log")
    parser.add_argument("--consume-backchannel", action="append",
                        default=[], metavar="STATE_DIR",
                        help="a stage process's state dir to consume")
    # sharded ordering core (VERDICT r4 #4): N core processes share a
    # deployment dir; each claims doc partitions via placement leases
    # and serves only its docs; gateways route by partition
    parser.add_argument("--shard-dir", default=None,
                        help="sharded-core deployment dir (leases + "
                             "per-partition durable logs)")
    parser.add_argument("--shards", type=int, default=0,
                        help="number of doc partitions")
    parser.add_argument("--prefer", default="", metavar="K[,K...]",
                        help="partitions to claim at startup (others "
                             "only by stale-lease takeover)")
    parser.add_argument("--lease-ttl", type=float, default=None,
                        help="lease staleness threshold in seconds")
    parser.add_argument("--admin-secret", default=None,
                        help="shared secret gating the admin RPCs "
                             "(required when tenancy is enforcing)")
    parser.add_argument("--journal", default=None, metavar="PATH",
                        help="arm the control-plane audit journal at "
                             "PATH (sharded cores arm automatically "
                             "under the shard dir; this is the "
                             "single-pipeline / bench A/B door)")
    # overload-control loop (see service/admission.py + obs/slo.py)
    parser.add_argument("--tenant-rate", action="append", default=[],
                        metavar="ID:RATE[:BURST]",
                        help="cap a tenant's admission rate in ops/s "
                             "(unlisted tenants stay unlimited)")
    parser.add_argument("--slo", action="append", default=[],
                        metavar="NAME=PAIR[@TENANT]:BUDGET_MS"
                                "[:WINDOW_S[:BURN_TICKS]]",
                        help="arm a windowed p99 SLO; a sustained burn "
                             "sheds over-budget tenants")
    parser.add_argument("--no-shed", action="store_true",
                        help="evaluate SLOs but never shed (the "
                             "overload bench's control arm)")
    parser.add_argument("--summarize-every", type=int, default=None,
                        metavar="N",
                        help="run the service summarizer loop: a new "
                             "columnar snapshot every N sequenced ops "
                             "per doc (the snapshot fast-boot plane)")
    # self-driving placement (service/rebalancer.py): close the
    # load→decision→migration loop on this core
    parser.add_argument("--rebalance", action="store_true",
                        help="arm the placement rebalancer daemon "
                             "(requires --shard-dir)")
    parser.add_argument("--rebalance-tick", type=float, default=0.5,
                        metavar="S", help="planner tick interval")
    parser.add_argument("--rebalance-dwell", type=float, default=10.0,
                        metavar="S", help="per-partition minimum dwell "
                                          "between moves")
    parser.add_argument("--rebalance-budget", type=int, default=2,
                        metavar="N", help="max migrations per tick from "
                                          "this core")
    parser.add_argument("--rebalance-improvement", type=float,
                        default=0.25, metavar="F",
                        help="min hottest→coldest gap as a fraction of "
                             "mean load before a move is worth it")
    # live health plane (obs/probe.py + obs/health.py): canary probes
    # through this core's own doors + the doctor's rules evaluated
    # continuously, served by the admin_health RPC
    parser.add_argument("--probe", action="store_true",
                        help="arm the live health plane: a canary "
                             "prober walking this core's doors on the "
                             "reserved __canary__ tenant plus the "
                             "streaming doctor (admin_health)")
    parser.add_argument("--probe-tick", type=float, default=2.0,
                        metavar="S", help="canary probe interval")
    parser.add_argument("--health-tick", type=float, default=1.0,
                        metavar="S", help="health rule evaluation "
                                          "interval")
    parser.add_argument("--health-critical-ticks", type=int, default=3,
                        metavar="N",
                        help="consecutive anomalous ticks before a "
                             "component goes degraded → critical")
    # fleet topology spec (service/topology.py): the whole deployment
    # as one JSON object; every sharded construction path converges on
    # topology.build_core, so a restart from the spec IS the start
    parser.add_argument("--topology", default=None, metavar="SPEC.json",
                        help="start one core of a declarative fleet "
                             "spec (supersedes the --shard-dir flag "
                             "family)")
    parser.add_argument("--core-index", type=int, default=0,
                        metavar="I", help="which spec core this "
                                          "process is")
    parser.add_argument("--boot-rate", type=float, default=200.0,
                        metavar="N",
                        help="boot-storm admission: doc rehydrations "
                             "per second this core will run; excess "
                             "first-routes park on the retry lane "
                             "(0 disarms)")
    parser.add_argument("--boot-burst", type=int, default=32,
                        metavar="N",
                        help="boot-storm admission burst size")
    args = parser.parse_args()
    if args.rebalance and args.shard_dir is None and args.topology is None:
        parser.error("--rebalance requires --shard-dir")
    if args.topology is not None or args.shard_dir is not None:
        import gc as _gc

        from .topology import CoreSpec, TopologySpec, build_core

        if args.consume_backchannel or args.external_scribe:
            parser.error("sharded cores do not compose with per-stage "
                         "backchannels yet")
        if args.tenant or args.log_dir or args.storage_dir:
            # refuse loudly: silently dropping --tenant would start an
            # auth-less deployment the operator believes is secured
            parser.error("sharded cores do not compose with --tenant/"
                         "--log-dir/--storage-dir (per-partition logs "
                         "live under the shard dir; use "
                         "--storage-server for storage)")
        if args.topology is not None:
            spec = TopologySpec.load(args.topology)
            core_index = args.core_index
        else:
            # the flag family is now sugar: one single-core spec, same
            # construction path. The core's journal file is named by
            # its STABLE role (preferred partitions) so a restarted
            # core reopens its own journal and continues the id space
            # — that is what makes core.recover detectable.
            prefer = [int(k) for k in args.prefer.split(",") if k != ""]
            name = ("core-" + "-".join(str(k) for k in prefer)
                    if prefer else "")
            spec = TopologySpec(
                shard_dir=args.shard_dir, n_partitions=args.shards,
                cores=[CoreSpec(name=name, prefer=prefer,
                                port=args.port)],
                host=args.host, lease_ttl=args.lease_ttl,
                admin_secret=args.admin_secret,
                summarize_every=args.summarize_every,
                storage_server=args.storage_server,
                boot_rate=args.boot_rate, boot_burst=args.boot_burst,
                rebalance=({
                    "tick_s": args.rebalance_tick,
                    "dwell_s": args.rebalance_dwell,
                    "budget": args.rebalance_budget,
                    "improvement": args.rebalance_improvement,
                } if args.rebalance else None))
            core_index = 0
        _gc.freeze()
        _gc.disable()
        front = build_core(spec, core_index)
        if args.max_message_size is not None:
            front.max_message_size = args.max_message_size
        _apply_overload_flags(front, args, parser)
        if args.probe and front._health_cfg is None:
            # flag-armed on top of a spec without a health stanza
            # (spec.health goes through build_core)
            front.enable_health(
                probe_tick_s=args.probe_tick,
                tick_s=args.health_tick,
                critical_ticks=args.health_critical_ticks)
        front.serve_forever()
        return
    server = None
    tenants = None
    if args.tenant:
        from .tenants import TenantManager

        tenants = TenantManager()
        for spec in args.tenant:
            tid, _, secret = spec.partition(":")
            tenants.register(tid, secret)
    if args.tenant or args.log_dir or args.storage_dir \
            or args.external_scribe or args.storage_server:
        log = None
        if args.log_dir:
            from .durable_log import DurableLog

            log = DurableLog(args.log_dir)
        storage_server = None
        if args.storage_server:
            host, _, port = args.storage_server.rpartition(":")
            storage_server = (host or "127.0.0.1", int(port))
        server = LocalServer(tenants=tenants, log=log,
                             storage_dir=args.storage_dir,
                             external_scribe=args.external_scribe,
                             storage_server=storage_server)
        if args.external_scribe:
            def announce_upload(tenant, doc, vid, rec, server=server):
                server.log.append(f"uploads/{tenant}/{doc}",
                                  {"version_id": vid, "record": rec})
                server.log.flush()
            server.on_version_uploaded = announce_upload
    # GC posture for a long-lived service process: the op path allocates
    # acyclic object graphs only (messages, dicts, frames), so the cycle
    # collector buys nothing on the hot path — mid-drain collections
    # scanning the scriptorium logs were the largest latency-spike
    # source under load. Disable it and sweep cycles (asyncio exception
    # tracebacks etc.) on a coarse idle timer instead.
    gc.freeze()
    gc.disable()

    if args.journal:
        from ..obs import arm_journal

        jr = arm_journal(args.journal, core="fe")
        jr.emit("core.recover" if jr.seq else "core.start", owner="fe")
    front = NetworkFrontEnd(server=server, host=args.host, port=args.port,
                            max_message_size=args.max_message_size,
                            admin_secret=args.admin_secret)
    _apply_overload_flags(front, args, parser)
    if args.probe:
        front.enable_health(probe_tick_s=args.probe_tick,
                            tick_s=args.health_tick,
                            critical_ticks=args.health_critical_ticks)
    if args.summarize_every is not None:
        front.enable_summarizer(args.summarize_every)
    for state_dir in args.consume_backchannel:
        front.attach_backchannel(state_dir)
    front.serve_forever()


if __name__ == "__main__":
    main()
