"""LocalServer: the complete service in one process.

Ref: local-server/src/localDeltaConnectionServer.ts:59-118 (the test
backbone) and server/tinylicious (the single-process deployment). The
connection handshake mirrors alfred's ``connect_document``
(lambdas/src/alfred/index.ts:112-310): assign a client id, sequence a join
op, hand back the current sequence state; ``submit_op`` orders client
messages; disconnect sequences a leave. Signals are relayed un-sequenced
(:405).

``auto_drain=True`` delivers everything synchronously (the easy mode);
``auto_drain=False`` + explicit ``drain()``/``step()`` gives tests
deterministic control over interleaving — the OpProcessingController role.
"""

from __future__ import annotations

import itertools
import time
import uuid
from typing import Any, Callable, Optional

from ..protocol.messages import (
    DocumentMessage,
    MessageType,
    SequencedDocumentMessage,
    Signal,
)
from .broadcaster import BroadcasterLambda, PubSub
from .core import InMemoryDb
from .deli import RawBoxcar, RawMessage
from .local_log import LocalLog
from .local_orderer import LocalOrderer


class ServerConnection:
    """One client's live connection (the socket analog).

    Callbacks: ``on_op(SequencedDocumentMessage)`` per message, or
    ``on_ops(list[SequencedDocumentMessage])`` per broadcast batch (set
    one; ``on_ops`` wins when both are set — high-rate consumers want the
    batch form), plus ``on_nack(Nack)`` and ``on_signal(Signal)``. Events
    arriving before a callback is attached are buffered and flushed on
    attach, so nothing delivered between the handshake and handler
    registration is lost.
    """

    def __init__(self, server: "LocalServer", tenant_id: str, document_id: str,
                 client_id: str, details: Any):
        self.server = server
        self.tenant_id = tenant_id
        self.document_id = document_id
        self.client_id = client_id
        self.details = details
        self._handlers: dict[str, Optional[Callable]] = {
            "op": None, "ops": None, "abatch": None, "nack": None,
            "signal": None}
        # op events buffer as batches; nack/signal as single events
        self._buffers: dict[str, list] = {"op": [], "nack": [], "signal": []}
        self.connected = True
        # sequence state at connect time (ref: IConnected payload)
        self.initial_sequence_number = 0

    def _deliver(self, kind: str, event) -> None:
        cb = self._handlers[kind]
        if cb is None:
            self._buffers[kind].append(event)
        else:
            cb(event)

    def _deliver_ops(self, batch) -> None:
        if type(batch) is not list:  # array lane: SequencedArrayBatch
            cb = self._handlers["abatch"]
            if cb is not None:  # array-aware consumer: no materialization
                cb(batch)
                return
            batch = batch.messages()  # legacy consumer: cold materialize
        cb = self._handlers["ops"]
        if cb is not None:
            cb(batch)
            return
        cb = self._handlers["op"]
        if cb is None:
            self._buffers["op"].append(batch)
        else:
            for msg in batch:
                cb(msg)

    def _set_handler(self, kind: str, cb: Optional[Callable]) -> None:
        self._handlers[kind] = cb
        if cb is None:
            return
        if kind in ("op", "ops", "abatch"):
            # op events (message lists AND array batches) share one
            # buffer; re-dispatch through _deliver_ops so each entry
            # reaches the best now-attached handler
            pending, self._buffers["op"] = self._buffers["op"], []
            for batch in pending:
                self._deliver_ops(batch)
        else:
            pending, self._buffers[kind] = self._buffers[kind], []
            for event in pending:
                cb(event)

    on_op = property(
        lambda self: self._handlers["op"],
        lambda self, cb: self._set_handler("op", cb))
    on_ops = property(
        lambda self: self._handlers["ops"],
        lambda self, cb: self._set_handler("ops", cb))
    # array-aware consumers get the SequencedArrayBatch raw (the deli-tpu
    # marshal lane); others transparently receive materialized messages
    on_abatch = property(
        lambda self: self._handlers["abatch"],
        lambda self, cb: self._set_handler("abatch", cb))
    on_nack = property(
        lambda self: self._handlers["nack"],
        lambda self, cb: self._set_handler("nack", cb))
    on_signal = property(
        lambda self: self._handlers["signal"],
        lambda self, cb: self._set_handler("signal", cb))

    def submit(self, messages: list[DocumentMessage]) -> None:
        if not self.connected:
            raise RuntimeError("connection closed")
        self.server._submit(self, messages)

    def submit_array(self, boxcar) -> None:
        """Submit an ArrayBoxcar (service/array_batch.py) — the SoA
        boxcar deli tickets without building per-op objects."""
        if not self.connected:
            raise RuntimeError("connection closed")
        self.server._submit_array(self, boxcar)

    def submit_signal(self, content: Any, type: str = "signal") -> None:
        if not self.connected:
            raise RuntimeError("connection closed")
        self.server._signal(self, Signal(client_id=self.client_id, type=type,
                                         content=content))

    def disconnect(self) -> None:
        if self.connected:
            self.connected = False
            self.server._disconnect(self)


class LocalServer:
    def __init__(
        self,
        auto_drain: bool = True,
        clock: Callable[[], float] = time.time,
        client_timeout: Optional[float] = None,
        log=None,
        storage_dir: Optional[str] = None,
        logger=None,
        config=None,
        tenants=None,
        external_scribe: bool = False,
        storage_server=None,
    ):
        from ..config import DEFAULT
        from ..utils import TelemetryLogger

        # unified config registry (SURVEY §5.6): explicit args still win
        self.config = config if config is not None else DEFAULT
        # tenant registry (riddler role); empty/None = open dev mode
        self.tenants = tenants
        if client_timeout is None:
            client_timeout = self.config.client_timeout_s
        # sink-less by default: zero cost until a host injects a sink
        self.logger = logger if logger is not None else TelemetryLogger("service")
        # always-on flight-recorder rings (obs/flight.py): per-boxcar
        # admission events land here so a crash dump carries the traffic
        # that preceded it
        from ..obs import get_recorder

        self._flight = get_recorder()
        # any object with the LocalLog surface works — pass a DurableLog
        # to persist the pipeline across process restarts
        self.log = log if log is not None else LocalLog()
        self.db = InMemoryDb()
        self.pubsub = PubSub()
        # content-addressed blob store: native C++ chunk store when given
        # a directory (the gitrest/libgit2 role), else db-backed
        self.storage_dir = storage_dir
        # doc history plane (commit/ref graph over snapshot generations):
        # constructed on first use — the summarizer's commit hook, the
        # history doors, and chunk GC all go through it
        self._history = None
        if storage_dir is not None:
            from .blob_store import NativeBlobStore

            self.blob_store = NativeBlobStore(storage_dir)
        else:
            from .blob_store import DbBlobStore

            self.blob_store = DbBlobStore(self.db)
        # storage as its own PROCESS (storage_server.py — the
        # gitrest+historian role): all storage reads/writes and the
        # scribe's ref updates route to it instead of this process's
        # blob store
        self._storage_conn = None
        if storage_server is not None:
            from .storage_client import StorageConnection

            self._storage_conn = StorageConnection(*storage_server)
        # summary-upload accounting (handle reuse), per server
        self.storage_stats = {"handles_reused": 0, "trees_written": 0,
                              "blobs_written": 0}
        self._orderers: dict[str, LocalOrderer] = {}
        # per-stage process composition (stage_runner.py): scribe runs in
        # its own OS process; uploads are announced to it via the hook
        self.external_scribe = external_scribe
        # fired as (tenant, doc, version_id, record) after a summary
        # upload lands in the versions collection
        self.on_version_uploaded = None
        self._auto_drain = auto_drain
        self._clock = clock
        self._client_timeout = client_timeout
        # ids must be unique across SERVER restarts too (a durable log
        # carries the old incarnation's ops, and clients classify local
        # vs remote by id), hence the random epoch component
        self._client_epoch = uuid.uuid4().hex[:6]
        self._client_counter = itertools.count(1)
        # sharded core: set when this partition's lease was lost — every
        # order path refuses, so a dispossessed server can never write
        # its (now someone else's) durable log again
        self._revoked = False
        # lease fencing (sharded core): a callable returning True while
        # this partition's lease was confirmed RECENTLY. Checked on
        # every order path: a process that stalled past the TTL (GC
        # pause, SIGSTOP) and wakes with buffered submits must refuse
        # them BEFORE its heartbeat loop discovers the takeover —
        # otherwise it interleaves appends into a log the new owner is
        # already writing (the classic two-writer corruption).
        self.lease_fresh = None
        # migration seal (placement_plane.MigrationEngine): submits are
        # refused while the partition's state ships to the new owner —
        # softer than revoke (reads/broadcasts still flow; the front end
        # bounces submits on the retryable shed lane instead of erroring)
        self._sealed = False
        # epoch fence (deli admission): a callable returning the CURRENT
        # table epoch when this server's claim epoch is stale, else None
        self.epoch_fence = None
        # which doc partition this server sequences (sharded cores only;
        # ShardHost._make_server stamps it) — the front end labels the
        # rebalancer's windowed heat series with it, and None means
        # single-pipeline: no heat accounting, nowhere to rebalance
        self.part_k = None
        # fleet cold start (service/rehydrate.py): lazy_boot makes every
        # first-route pipeline build O(snapshot+tail); the rehydrator —
        # when ShardHost arms one — parks excess first-routes during a
        # boot storm instead of letting them monopolize the loop
        self.lazy_boot = False
        self.rehydrator = None
        self._rehydrated_noted = False
        self._boot_inventory: set[str] = set()

    @property
    def history(self):
        """The doc history plane (service/history_plane.py): commit/ref
        graph, fork, point-in-time replay, integrate, chunk GC."""
        if self._history is None:
            from .history_plane import HistoryPlane

            self._history = HistoryPlane(self)
        return self._history

    def seal(self) -> None:
        """Migration fence point: refuse new submits (they bounce with a
        retryable redirect) while the checkpoint ships to the target."""
        self._sealed = True

    def unseal(self) -> None:
        self._sealed = False

    @property
    def sealed(self) -> bool:
        return self._sealed

    def doc_sequence_numbers(self) -> dict[str, int]:
        """Fence seqs per live doc (``tenant/doc`` → deli seq) — exact
        once sealed, since the ordering loop is single-threaded."""
        return {key: o.deli.sequence_number
                for key, o in self._orderers.items()}

    def revoke(self) -> None:
        """Partition lease lost (ShardHost.poll): stop sequencing NOW.
        The front end also tears down the partition's live sessions so
        clients reconnect to the takeover owner."""
        self._revoked = True

    def _check_revoked(self) -> None:
        if self._revoked or (self.lease_fresh is not None
                             and not self.lease_fresh()):
            raise RuntimeError("partition lease lost: reconnect")
        if self._sealed:
            raise RuntimeError("partition sealed for migration: reconnect")

    # ------------------------------------------------------------------ api

    def connect(
        self,
        tenant_id: str,
        document_id: str,
        details: Any = None,
        can_evict: bool = True,
        token: Optional[str] = None,
        readonly: bool = False,
    ) -> ServerConnection:
        """The connect_document handshake: join the quorum, get a live
        connection primed at the current sequence number. With a tenant
        registry configured, the token is validated riddler-style BEFORE
        any document state is touched (ref: alfred connect_document →
        tenantManager.verifyToken). A doc:read-only token gets a READ
        connection: it may watch the stream, but submits are nacked with
        InvalidScopeError (ref: readonly connections, tokens.ts scopes).

        ``readonly=True`` requests the fast reader session regardless of
        token scope: no join op is ordered, the clientId never enters
        the quorum, and the session costs the op path nothing — the
        audience tier for read-scale fan-out."""
        self._check_revoked()
        can_write = not readonly
        if self.tenants is not None:
            from .tenants import SCOPE_READ, SCOPE_WRITE

            claims = self.tenants.validate(token, tenant_id, document_id,
                                           required_scope=SCOPE_READ)
            can_write = can_write and SCOPE_WRITE in claims.get(
                "scopes", [])
        if (self.rehydrator is not None
                and f"{tenant_id}/{document_id}" not in self._orderers):
            # boot-storm admission: a first-route to a cold doc takes a
            # boot slot or parks (BootPending → retryable nack); routes
            # to already-warm docs never touch the bucket
            self.rehydrator.admit(tenant_id, document_id)
        orderer = self._get_orderer(tenant_id, document_id)
        client_id = f"client-{self._client_epoch}-{next(self._client_counter)}"
        conn = ServerConnection(self, tenant_id, document_id, client_id, details)
        conn.can_write = can_write
        conn.mode = "readonly" if readonly else (
            "write" if can_write else "read")

        topic = BroadcasterLambda.topic(tenant_id, document_id)
        conn._op_cb = conn._deliver_ops  # op topics carry batches
        conn._nack_cb = lambda nack: conn._deliver("nack", nack)
        conn._sig_cb = lambda sig: conn._deliver("signal", sig)
        self.pubsub.subscribe(topic, conn._op_cb)
        self.pubsub.subscribe(
            f"nack/{tenant_id}/{document_id}/{client_id}", conn._nack_cb)
        self.pubsub.subscribe(f"signal/{tenant_id}/{document_id}", conn._sig_cb)

        conn.initial_sequence_number = orderer.deli.sequence_number
        if can_write:
            orderer.order(
                RawMessage(
                    tenant_id=tenant_id,
                    document_id=document_id,
                    client_id=None,
                    operation=DocumentMessage(
                        client_sequence_number=-1,
                        reference_sequence_number=-1,
                        type=MessageType.CLIENT_JOIN,
                        contents={
                            "clientId": client_id,
                            "detail": details,
                            "canEvict": can_evict,
                        },
                    ),
                    timestamp=self._clock(),
                )
            )
        # read connections NEVER join: they are not quorum members and
        # must not contribute to the msn — a reader cannot submit (its
        # ops scope-nack), so a joined reader would pin the collaboration
        # window forever (ref: read connections stay out of the quorum;
        # they exist only in the audience)
        self._maybe_drain()
        return conn

    def storage(self, tenant_id: str, document_id: str):
        """The doc's storage binding: the in-proc store, or the storage
        PROCESS when one is deployed. Every storage consumer (front-end
        RPCs, summarizer, drivers) goes through here."""
        if self._storage_conn is not None:
            from .storage_client import RemoteStorage

            def on_uploaded(vid, record, tenant=tenant_id,
                            doc=document_id):
                # mirror the version record into this process's db —
                # scribe validation reads it there — and announce it
                # (external scribe stages learn of uploads this way)
                from .core import summary_versions_collection

                self.db.upsert(summary_versions_collection(tenant, doc),
                               vid, dict(record))
                hook = self.on_version_uploaded
                if hook is not None:
                    hook(tenant, doc, vid, dict(record))
            return RemoteStorage(self._storage_conn, tenant_id,
                                 document_id, on_uploaded=on_uploaded)
        from ..driver.local import LocalStorage

        return LocalStorage(self, tenant_id, document_id)

    def commit_storage_ref(self, tenant_id: str, document_id: str,
                           handle: str) -> None:
        """Advance the doc's named head in the storage process after a
        scribe ack (no-op for in-proc storage, whose acked flag plays
        the ref role)."""
        if self._storage_conn is not None:
            self.storage(tenant_id, document_id).commit_ref(handle)

    def get_deltas(
        self, tenant_id: str, document_id: str, from_seq: int, to_seq: int
    ) -> list[SequencedDocumentMessage]:
        """REST backfill (alfred /deltas): ops with from_seq < seq < to_seq."""
        from .scriptorium import LogTruncatedError

        orderer = self._get_orderer(tenant_id, document_id)
        try:
            return orderer.scriptorium.get_deltas(
                tenant_id, document_id, from_seq, to_seq)
        except LogTruncatedError as e:
            # report the snapshot-backed base so the joiner knows a
            # bootable summary covers the hole
            e.snapshot_seq = orderer.acked_boot_seq()
            raise

    def get_delta_blocks(
        self, tenant_id: str, document_id: str, from_seq: int, to_seq: int
    ):
        """Columnar backfill door: ``(payloads, msgs, head)`` covering
        from_seq < seq < to_seq, or None when the durable log has no
        segment stream for this doc (caller falls back to
        :meth:`get_deltas`). ``payloads`` are raw segment-block byte
        ranges (a boundary block may span past the range — the CLIENT
        trims by seq); ``msgs`` are legacy-record ops materialized
        through the compat shim. Enforces the same retention contract as
        the scalar door: reaching below the trim base raises
        :class:`~.scriptorium.LogTruncatedError` rather than silently
        serving a partial range."""
        from .scriptorium import LogTruncatedError

        blocks = getattr(self.log, "delta_blocks", None)
        if blocks is None:
            return None
        orderer = self._get_orderer(tenant_id, document_id)
        base = orderer.scriptorium.retained_base(tenant_id, document_id)
        if from_seq < base:
            raise LogTruncatedError(base,
                                    snapshot_seq=orderer.acked_boot_seq())
        res = blocks(f"deltas/{tenant_id}/{document_id}", from_seq, to_seq)
        if res is None:
            return None
        payloads, legacy = res
        head = orderer.scriptorium.head_seq(tenant_id, document_id)
        return payloads, legacy, head

    def drain(self) -> int:
        """Deliver all queued messages through the pipeline to quiescence."""
        return self.log.drain()

    def expire_idle_clients(self) -> None:
        for orderer in self._orderers.values():
            orderer.deli.check_idle_clients()
        self._maybe_drain()

    def checkpoint_all(self) -> None:
        for orderer in self._orderers.values():
            orderer.checkpoint()

    def restart_orderer(self, tenant_id: str, document_id: str) -> None:
        """Simulate a partition restart: tear down the document's pipeline
        and rebuild it from the db checkpoint (ref: KafkaRunner partition
        restart, lambdas-driver/src/kafka-service/partition.ts)."""
        key = f"{tenant_id}/{document_id}"
        orderer = self._orderers.pop(key, None)
        if orderer is not None:
            orderer.checkpoint()
            orderer.close()
        self._get_orderer(tenant_id, document_id)

    def crash_orderer(self, tenant_id: str, document_id: str) -> None:
        """Simulate a kill -9 of the document's pipeline: tear down
        WITHOUT checkpointing and rebuild from the last durable
        checkpoint. Deli replays the raw log from its checkpointed
        offset and re-tickets the window with identical sequence
        numbers; downstream consumers dedupe by seq (the chaos soak's
        stage-crash fault). An injected crash is a flight-recorder
        trigger: the rings dump so the run carries the traffic that
        preceded the kill."""
        from ..obs import get_recorder

        key = f"{tenant_id}/{document_id}"
        orderer = self._orderers.pop(key, None)
        if orderer is not None:
            orderer.close()
        recorder = get_recorder()
        recorder.event("deli", "orderer_crash", tenant=tenant_id,
                       doc=document_id)
        try:
            recorder.dump("orderer_crash", tenant=tenant_id,
                          doc=document_id)
        except OSError:
            pass  # a failed dump must not break the crash simulation
        self._get_orderer(tenant_id, document_id)

    # ------------------------------------------------------------- internal

    def _get_orderer(self, tenant_id: str, document_id: str) -> LocalOrderer:
        key = f"{tenant_id}/{document_id}"
        if key not in self._orderers:
            kw = {}
            if self._client_timeout is not None:
                kw["client_timeout"] = self._client_timeout
            retention = self.config.log_retention_ops
            on_persisted = None
            if self._storage_conn is not None:
                def on_persisted(handle, version, t=tenant_id,
                                 d=document_id):
                    self.commit_storage_ref(t, d, handle)
            self._orderers[key] = LocalOrderer(
                tenant_id, document_id, self.log, self.db, self.pubsub,
                clock=self._clock, logger=self.logger,
                log_retention_ops=retention if retention >= 0 else None,
                external_scribe=self.external_scribe,
                on_version_persisted=on_persisted,
                lazy_boot=self.lazy_boot,
                **kw)
            # epoch fence: deli consults the server's CURRENT fence on
            # every record (closure, so arming after boot still applies)
            self._orderers[key].deli.epoch_fence = (
                lambda: self.epoch_fence() if self.epoch_fence is not None
                else None)
            if (self._orderers[key].boot_mode == "lazy"
                    and not self._rehydrated_noted):
                self._rehydrated_noted = True
                from ..obs.journal import get_journal

                get_journal().emit("part.rehydrated", part=self.part_k,
                                   doc=key)
        return self._orderers[key]

    # ------------------------------------------------------- cold start

    def scan_boot_pending(self) -> int:
        """Cold-start inventory: docs present on this partition's log
        with no live pipeline yet. Listing is one directory scan (no
        record reads) — the lazy contract. Feeds ``admin placement
        boot`` progress."""
        topics = getattr(self.log, "list_topics", None)
        if topics is None:
            return 0
        self._boot_inventory = {
            t[len("rawops/"):] for t in topics("rawops/")}
        return len(self._boot_inventory)

    def boot_status(self) -> dict:
        """Rehydration progress for the operator door."""
        pending = sum(1 for k in self._boot_inventory
                      if k not in self._orderers)
        return {"part": self.part_k,
                "docs_booted": len(self._orderers),
                "docs_pending": pending}

    def _submit(self, conn: ServerConnection, messages: list[DocumentMessage]) -> None:
        self._check_revoked()
        if not getattr(conn, "can_write", True):
            from ..protocol.messages import Nack, NackErrorType

            for op in messages:
                self.pubsub.publish(
                    f"nack/{conn.tenant_id}/{conn.document_id}/"
                    f"{conn.client_id}",
                    Nack(operation=op, sequence_number=-1, code=403,
                         type=NackErrorType.INVALID_SCOPE,
                         message="token lacks doc:write scope"))
            return
        orderer = self._get_orderer(conn.tenant_id, conn.document_id)
        now = self._clock()
        self._flight.event("deli", "boxcar", doc=conn.document_id,
                           client=conn.client_id, n=len(messages))
        # the whole submitted batch rides the raw log as ONE boxcar record
        # (ref: IBoxcarMessage); deli's fast lane tickets it in one pass
        orderer.order(
            RawBoxcar(
                tenant_id=conn.tenant_id,
                document_id=conn.document_id,
                client_id=conn.client_id,
                ops=messages,
                timestamp=now,
            )
        )
        self._maybe_drain()

    def _submit_array(self, conn: ServerConnection, boxcar) -> None:
        self._check_revoked()
        if not getattr(conn, "can_write", True):
            from ..protocol.messages import Nack, NackErrorType

            self.pubsub.publish(
                f"nack/{conn.tenant_id}/{conn.document_id}/"
                f"{conn.client_id}",
                Nack(operation=None, sequence_number=-1, code=403,
                     type=NackErrorType.INVALID_SCOPE,
                     message="token lacks doc:write scope"))
            return
        boxcar.tenant_id = conn.tenant_id
        boxcar.document_id = conn.document_id
        boxcar.client_id = conn.client_id
        boxcar.timestamp = self._clock()
        self._flight.event("deli", "aboxcar", doc=conn.document_id,
                           client=conn.client_id, n=boxcar.n)
        orderer = self._get_orderer(conn.tenant_id, conn.document_id)
        orderer.order(boxcar)
        self._maybe_drain()

    def _signal(self, conn: ServerConnection, signal: Signal) -> None:
        self.pubsub.publish(
            f"signal/{conn.tenant_id}/{conn.document_id}", signal)

    def _disconnect(self, conn: ServerConnection) -> None:
        if self._revoked or not getattr(conn, "can_write", True):
            # revoked: the takeover owner expires the client instead
            # (idle timeout) — this process may not write the log.
            # read connections never joined: nothing to leave.
            self._unsubscribe_conn(conn)
            return
        orderer = self._get_orderer(conn.tenant_id, conn.document_id)
        orderer.order(
            RawMessage(
                tenant_id=conn.tenant_id,
                document_id=conn.document_id,
                client_id=None,
                operation=DocumentMessage(
                    client_sequence_number=-1,
                    reference_sequence_number=-1,
                    type=MessageType.CLIENT_LEAVE,
                    contents={"clientId": conn.client_id},
                ),
                timestamp=self._clock(),
            )
        )
        self._unsubscribe_conn(conn)
        self._maybe_drain()

    def _unsubscribe_conn(self, conn: ServerConnection) -> None:
        topic = BroadcasterLambda.topic(conn.tenant_id, conn.document_id)
        self.pubsub.unsubscribe(topic, conn._op_cb)
        self.pubsub.unsubscribe(
            f"nack/{conn.tenant_id}/{conn.document_id}/{conn.client_id}",
            conn._nack_cb)
        self.pubsub.unsubscribe(
            f"signal/{conn.tenant_id}/{conn.document_id}", conn._sig_cb)

    def _maybe_drain(self) -> None:
        if self._auto_drain:
            self.log.drain()
