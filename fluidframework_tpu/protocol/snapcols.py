"""snapcols: the columnar merge-tree snapshot chunk codec.

Encodes ``MergeTreeClient.snapshot()`` output (the canonical segment
list, wire string client ids) as packed little-endian column chunks —
the snapshot-side twin of the FT_COLS op lane in :mod:`binwire`.
Layout per chunk (all LE)::

    u16 ver (=1)
    u16 n                       segment count
    u16 k + k×(u16 len + utf8)  chunk client-id string table
    n  × u8  kind               bit flags (marker/props/ins/rem/remClients)
    n  × i32 ins_seq            valid iff KIND_INS
    n  × i32 ins_client         client-table index (-1 = null)
    n  × i32 rem_seq            valid iff KIND_REM
    n  × i32 rem_client         client-table index (-1 = null)
    (n+1) × i32 text_off        byte offsets into the text blob
    u32 tlen + text             concatenated utf-8 text runs
    u32 alen + aux              tagged-value records (props/marker/remClients)

The i32 columns decode with ``np.frombuffer`` — a booting client never
walks segments in Python to parse stamps. The aux section is a
hand-rolled binary tagged-value codec (None/bool/int/float/str/list/
dict with sorted keys), NOT json: this module sits on the snapshot hot
path and is covered by fluidlint's storage json ban; the legacy JSON
tree shim in ``summary_trees.py`` is the sole exempted twin.

Chunking is by fixed segment count — but ``snapshot()`` is CANONICAL
(adjacent text runs with identical stamps coalesce), so a quiet
single-writer doc collapses into one ever-growing segment and naive
segment-count chunking would re-encode everything each generation.
Encode therefore first SPLITS oversized text runs into fixed-size
pieces (``TEXT_SPLIT_CHARS``): an append-only doc changes only its
trailing partial piece, every earlier piece — and thus every earlier
chunk — re-encodes byte-identical, and the content-addressed chunk
store dedupes them. Decode re-coalesces adjacent same-stamp pieces,
restoring the exact canonical form, so round-trips are byte-identical.
"""

from __future__ import annotations

import struct

import numpy as np

SNAPCOLS_VER = 1

#: default segments per chunk: big enough that chunk-count overhead is
#: noise, small enough that a single edited segment dirties one chunk
SEGS_PER_CHUNK = 256

#: max characters per encoded text run: the dedupe granularity for
#: coalesced base content (see module docstring)
TEXT_SPLIT_CHARS = 1024

KIND_MARKER = 0x01
KIND_PROPS = 0x02
KIND_INS = 0x04
KIND_REM = 0x08
KIND_REMCLIENTS = 0x10

_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")

# aux tagged-value codec tags
_T_NONE = 0
_T_FALSE = 1
_T_TRUE = 2
_T_INT = 3
_T_FLOAT = 4
_T_STR = 5
_T_LIST = 6
_T_DICT = 7


# ------------------------------------------------------- aux value codec
def _enc_value(v, out: list) -> None:
    if v is None:
        out.append(bytes((_T_NONE,)))
    elif v is True:
        out.append(bytes((_T_TRUE,)))
    elif v is False:
        out.append(bytes((_T_FALSE,)))
    elif isinstance(v, int):
        out.append(bytes((_T_INT,)) + _I64.pack(v))
    elif isinstance(v, float):
        out.append(bytes((_T_FLOAT,)) + _F64.pack(v))
    elif isinstance(v, str):
        b = v.encode()
        out.append(bytes((_T_STR,)) + _U32.pack(len(b)) + b)
    elif isinstance(v, (list, tuple)):
        out.append(bytes((_T_LIST,)) + _U32.pack(len(v)))
        for item in v:
            _enc_value(item, out)
    elif isinstance(v, dict):
        # sorted keys: identical dicts → identical bytes → chunk dedupe
        out.append(bytes((_T_DICT,)) + _U32.pack(len(v)))
        for k in sorted(v):
            kb = str(k).encode()
            out.append(_U32.pack(len(kb)) + kb)
            _enc_value(v[k], out)
    else:
        raise TypeError(f"snapcols aux cannot encode {type(v).__name__}")


def _dec_value(buf: bytes, off: int):
    tag = buf[off]
    off += 1
    if tag == _T_NONE:
        return None, off
    if tag == _T_TRUE:
        return True, off
    if tag == _T_FALSE:
        return False, off
    if tag == _T_INT:
        return _I64.unpack_from(buf, off)[0], off + 8
    if tag == _T_FLOAT:
        return _F64.unpack_from(buf, off)[0], off + 8
    if tag == _T_STR:
        (ln,) = _U32.unpack_from(buf, off)
        off += 4
        return buf[off:off + ln].decode(), off + ln
    if tag == _T_LIST:
        (cnt,) = _U32.unpack_from(buf, off)
        off += 4
        items = []
        for _ in range(cnt):
            item, off = _dec_value(buf, off)
            items.append(item)
        return items, off
    if tag == _T_DICT:
        (cnt,) = _U32.unpack_from(buf, off)
        off += 4
        d = {}
        for _ in range(cnt):
            (kl,) = _U32.unpack_from(buf, off)
            off += 4
            key = buf[off:off + kl].decode()
            off += kl
            d[key], off = _dec_value(buf, off)
        return d, off
    raise ValueError(f"snapcols aux: unknown tag {tag}")


# ---------------------------------------------------------- chunk codec
def encode_chunk(segs: list) -> bytes:
    """Encode one run of snapshot segment dicts as a snapcols chunk."""
    n = len(segs)
    kinds = bytearray(n)
    ins_seq = np.zeros(n, "<i4")
    ins_cli = np.full(n, -1, "<i4")
    rem_seq = np.zeros(n, "<i4")
    rem_cli = np.full(n, -1, "<i4")
    text_off = np.zeros(n + 1, "<i4")
    clients: dict = {}  # wire client id str → chunk-table index

    def cli_idx(c) -> int:
        if c is None:
            return -1
        if not isinstance(c, str):
            raise TypeError(
                f"snapcols client ids are wire strings, got {c!r}")
        return clients.setdefault(c, len(clients))

    texts: list[bytes] = []
    aux: list[bytes] = []
    tpos = 0
    for i, d in enumerate(segs):
        k = 0
        if "props" in d:
            k |= KIND_PROPS
            _enc_value(d["props"], aux)
        if "marker" in d:
            k |= KIND_MARKER
            _enc_value(d["marker"], aux)
        else:
            tb = d["text"].encode()
            texts.append(tb)
            tpos += len(tb)
        if "insSeq" in d:
            k |= KIND_INS
            ins_seq[i] = d["insSeq"]
            ins_cli[i] = cli_idx(d["insClient"])
        if "remSeq" in d:
            k |= KIND_REM
            rem_seq[i] = d["remSeq"]
            rem_cli[i] = cli_idx(d["remClient"])
            if "remClients" in d:
                k |= KIND_REMCLIENTS
                _enc_value(list(d["remClients"]), aux)
        kinds[i] = k
        text_off[i + 1] = tpos
    table = [_U16.pack(len(clients))]
    for c in clients:  # insertion order == index order
        cb = c.encode()
        table.append(_U16.pack(len(cb)) + cb)
    text = b"".join(texts)
    auxb = b"".join(aux)
    return b"".join((
        _U16.pack(SNAPCOLS_VER), _U16.pack(n), b"".join(table),
        bytes(kinds), ins_seq.tobytes(), ins_cli.tobytes(),
        rem_seq.tobytes(), rem_cli.tobytes(), text_off.tobytes(),
        _U32.pack(len(text)), text, _U32.pack(len(auxb)), auxb,
    ))


def decode_chunk(chunk: bytes) -> list:
    """Decode one snapcols chunk back to snapshot segment dicts."""
    (ver,) = _U16.unpack_from(chunk, 0)
    if ver != SNAPCOLS_VER:
        raise ValueError(f"snapcols: unknown chunk version {ver}")
    (n,) = _U16.unpack_from(chunk, 2)
    off = 4
    (nclients,) = _U16.unpack_from(chunk, off)
    off += 2
    table: list[str] = []
    for _ in range(nclients):
        (cl,) = _U16.unpack_from(chunk, off)
        off += 2
        table.append(chunk[off:off + cl].decode())
        off += cl

    def cli(idx: int):
        return None if idx < 0 else table[idx]

    kinds = chunk[off:off + n]
    off += n
    ins_seq = np.frombuffer(chunk, "<i4", n, off)
    off += 4 * n
    ins_cli = np.frombuffer(chunk, "<i4", n, off)
    off += 4 * n
    rem_seq = np.frombuffer(chunk, "<i4", n, off)
    off += 4 * n
    rem_cli = np.frombuffer(chunk, "<i4", n, off)
    off += 4 * n
    text_off = np.frombuffer(chunk, "<i4", n + 1, off)
    off += 4 * (n + 1)
    (tlen,) = _U32.unpack_from(chunk, off)
    off += 4
    # keep bytes: text_off are BYTE offsets (utf-8 runs decode per-slice)
    text = chunk[off:off + tlen]
    off += tlen
    (alen,) = _U32.unpack_from(chunk, off)
    off += 4
    if off + alen > len(chunk):
        raise ValueError("snapcols: truncated aux section")
    apos = off
    segs: list[dict] = []
    for i in range(n):
        k = kinds[i]
        d: dict = {}
        if k & KIND_PROPS:
            d["props"], apos = _dec_value(chunk, apos)
        if k & KIND_MARKER:
            d["marker"], apos = _dec_value(chunk, apos)
        else:
            d["text"] = text[int(text_off[i]):int(text_off[i + 1])].decode()
        if k & KIND_INS:
            d["insSeq"] = int(ins_seq[i])
            d["insClient"] = cli(int(ins_cli[i]))
        if k & KIND_REM:
            d["remSeq"] = int(rem_seq[i])
            d["remClient"] = cli(int(rem_cli[i]))
            if k & KIND_REMCLIENTS:
                d["remClients"], apos = _dec_value(chunk, apos)
        segs.append(d)
    return segs


# ------------------------------------------------------- snapshot level
def _split_segments(segs: list, text_split: int) -> list:
    """Split oversized text runs into ≤ ``text_split``-char pieces with
    identical stamps — semantically a no-op (adjacent same-stamp runs
    are one run), but it pins the piece boundaries so appends leave
    every full piece byte-stable."""
    out: list = []
    for d in segs:
        t = d.get("text")
        if t is None or len(t) <= text_split:
            out.append(d)
            continue
        attrs = {k: v for k, v in d.items() if k != "text"}
        for i in range(0, len(t), text_split):
            out.append({**attrs, "text": t[i:i + text_split]})
    return out


def _coalesce_segments(segs: list) -> list:
    """The exact canonicalization rule of ``MergeTree.snapshot()``:
    adjacent text runs whose non-text fields match merge — the inverse
    of :func:`_split_segments`, so round-trips are byte-identical."""
    out: list = []
    for d in segs:
        prev = out[-1] if out else None
        if (prev is not None and "text" in prev and "text" in d
                and {k: v for k, v in prev.items() if k != "text"}
                == {k: v for k, v in d.items() if k != "text"}):
            prev["text"] += d["text"]
        else:
            out.append(dict(d))
    return out


def encode_snapshot_chunks(snap: dict,
                           segs_per_chunk: int = SEGS_PER_CHUNK,
                           text_split: int = TEXT_SPLIT_CHARS) -> list:
    """``snapshot()`` dict → list of chunk byte strings.

    minSeq/seq ride the version header (the root record), NOT the
    chunks — keeping chunks pure content is what makes an unchanged
    snapshot prefix hash-stable across generations.
    """
    segs = _split_segments(snap["segments"], text_split)
    if not segs:
        return [encode_chunk([])]
    return [encode_chunk(segs[i:i + segs_per_chunk])
            for i in range(0, len(segs), segs_per_chunk)]


def decode_snapshot_chunks(chunks: list, min_seq: int, seq: int) -> dict:
    """Chunk byte strings (+ header seqs) → the snapshot dict twin."""
    segs: list = []
    for c in chunks:
        segs.extend(decode_chunk(c))
    return {"minSeq": min_seq, "seq": seq,
            "segments": _coalesce_segments(segs)}
