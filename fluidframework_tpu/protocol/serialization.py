"""Wire serialization for protocol messages (JSON).

One canonical encoding shared by the durable native log, the network
front end, and the replay tooling — the analog of the reference's JSON
socket/Kafka payloads (protocol-definitions types are the schema).
"""

from __future__ import annotations

import json
from dataclasses import asdict
from typing import Any

from .messages import (
    DocumentMessage,
    MessageType,
    Nack,
    NackErrorType,
    SequencedDocumentMessage,
    Signal,
    TraceHop,
)

_KINDS = {
    "doc": DocumentMessage,
    "seq": SequencedDocumentMessage,
    "nack": Nack,
    "signal": Signal,
}
# custom codecs for types outside protocol.messages (e.g. service
# RawMessage): kind → (cls, to_dict, from_dict)
_CUSTOM: dict[str, tuple] = {}


def register_message_type(kind: str, cls: type, to_dict, from_dict) -> None:
    _CUSTOM[kind] = (cls, to_dict, from_dict)


def message_to_dict(msg: Any) -> dict:
    for kind, cls in _KINDS.items():
        if isinstance(msg, cls):
            d = asdict(msg)
            d["_kind"] = kind
            return d
    for kind, (cls, to_dict, _) in _CUSTOM.items():
        if isinstance(msg, cls):
            return dict(to_dict(msg), _kind=kind)
    raise TypeError(f"unknown message type {type(msg)!r}")


def message_from_dict(d: dict) -> Any:
    d = dict(d)
    kind = d.pop("_kind")
    if kind in _CUSTOM:
        return _CUSTOM[kind][2](d)
    cls = _KINDS[kind]
    if "traces" in d:
        d["traces"] = [TraceHop(**t) for t in d["traces"]]
    if "type" in d:
        d["type"] = (
            NackErrorType(d["type"]) if kind == "nack"
            else d["type"] if kind == "signal"
            else MessageType(d["type"])
        )
    if kind == "nack" and d.get("operation") is not None:
        op = dict(d["operation"])
        op["type"] = MessageType(op["type"])
        op["traces"] = [TraceHop(**t) for t in op.get("traces", [])]
        d["operation"] = DocumentMessage(**op)
    return cls(**d)


def encode_message(msg: Any) -> bytes:
    return json.dumps(message_to_dict(msg), separators=(",", ":")).encode()


def decode_message(data: bytes) -> Any:
    return message_from_dict(json.loads(data.decode()))
