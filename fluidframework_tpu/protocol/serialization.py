"""Wire serialization for protocol messages (JSON).

One canonical encoding shared by the durable native log, the network
front end, and the replay tooling — the analog of the reference's JSON
socket/Kafka payloads (protocol-definitions types are the schema).
"""

from __future__ import annotations

import json
from typing import Any

from .messages import (
    DocumentMessage,
    MessageType,
    Nack,
    NackErrorType,
    SequencedDocumentMessage,
    Signal,
    TraceHop,
)

_KINDS = {
    "doc": DocumentMessage,
    "seq": SequencedDocumentMessage,
    "nack": Nack,
    "signal": Signal,
}
# custom codecs for types outside protocol.messages (e.g. service
# RawMessage): kind → (cls, to_dict, from_dict)
_CUSTOM: dict[str, tuple] = {}


def register_message_type(kind: str, cls: type, to_dict, from_dict) -> None:
    _CUSTOM[kind] = (cls, to_dict, from_dict)


# Hand-rolled encoders: ``dataclasses.asdict`` recursed into (and
# deep-copied) every ``contents`` payload, and was the front end's
# second-largest CPU cost under load. Payload dicts are shared by
# reference — encoders feed json.dumps immediately and nothing mutates
# wire dicts.

def _hop_dicts(traces) -> list[dict]:
    return [
        {"service": t.service, "action": t.action, "timestamp": t.timestamp}
        for t in traces
    ]


def _doc_fields(m: DocumentMessage) -> dict:
    return {
        "client_sequence_number": m.client_sequence_number,
        "reference_sequence_number": m.reference_sequence_number,
        "type": m.type,
        "contents": m.contents,
        "metadata": m.metadata,
        "traces": _hop_dicts(m.traces),
    }


_ENCODERS = {
    DocumentMessage: lambda m: dict(_doc_fields(m), _kind="doc"),
    SequencedDocumentMessage: lambda m: {
        "_kind": "seq",
        "client_id": m.client_id,
        "sequence_number": m.sequence_number,
        "minimum_sequence_number": m.minimum_sequence_number,
        "client_sequence_number": m.client_sequence_number,
        "reference_sequence_number": m.reference_sequence_number,
        "type": m.type,
        "contents": m.contents,
        "metadata": m.metadata,
        "origin": m.origin,
        "timestamp": m.timestamp,
        "traces": _hop_dicts(m.traces),
    },
    Nack: lambda m: {
        "_kind": "nack",
        "operation": None if m.operation is None
        else _doc_fields(m.operation),
        "sequence_number": m.sequence_number,
        "code": m.code,
        "type": m.type,
        "message": m.message,
        "retry_after_seconds": m.retry_after_seconds,
        # omitted when unset: pre-overload-control nacks must stay
        # byte-identical (format freeze, tests/test_compat.py)
        **({} if m.retry_after_ms is None
           else {"retry_after_ms": m.retry_after_ms}),
    },
    Signal: lambda m: {
        "_kind": "signal",
        "client_id": m.client_id,
        "type": m.type,
        "content": m.content,
    },
}


def message_to_dict(msg: Any) -> dict:
    enc = _ENCODERS.get(type(msg))
    if enc is not None:
        return enc(msg)
    for kind, (cls, to_dict, _) in _CUSTOM.items():
        if isinstance(msg, cls):
            return dict(to_dict(msg), _kind=kind)
    raise TypeError(f"unknown message type {type(msg)!r}")


def message_from_dict(d: dict) -> Any:
    d = dict(d)
    kind = d.pop("_kind")
    if kind in _CUSTOM:
        return _CUSTOM[kind][2](d)
    cls = _KINDS[kind]
    if "traces" in d:
        d["traces"] = [TraceHop(**t) for t in d["traces"]]
    if "type" in d:
        d["type"] = (
            NackErrorType(d["type"]) if kind == "nack"
            else d["type"] if kind == "signal"
            else MessageType(d["type"])
        )
    if kind == "nack" and d.get("operation") is not None:
        op = dict(d["operation"])
        op["type"] = MessageType(op["type"])
        op["traces"] = [TraceHop(**t) for t in op.get("traces", [])]
        d["operation"] = DocumentMessage(**op)
    return cls(**d)


def encode_message(msg: Any) -> bytes:
    return json.dumps(message_to_dict(msg), separators=(",", ":")).encode()


def decode_message(data: bytes) -> Any:
    return message_from_dict(json.loads(data.decode()))
