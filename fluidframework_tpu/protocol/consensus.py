"""Quorum member and proposal types (ref: protocol-definitions/src/consensus.ts)."""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Optional


@dataclass
class ClientDetails:
    """Connection-time client description (ref: protocol-definitions IClient)."""

    user_id: str = ""
    mode: str = "write"  # "read" | "write"
    interactive: bool = True  # False for summarizer/agent clients
    details: dict = field(default_factory=dict)
    scopes: list[str] = field(default_factory=list)


@dataclass
class SequencedClient:
    """A quorum member: a client plus the seq of its join op.

    Ref: consensus.ts ISequencedClient — join-op order is what makes
    "oldest client" well-defined for summarizer election.
    """

    client: ClientDetails
    sequence_number: int


class ProposalState(Enum):
    PENDING = "pending"
    ACCEPTED = "accepted"
    REJECTED = "rejected"


@dataclass
class QuorumProposal:
    """A key/value proposal flowing through the total order.

    Commit rule (ref: protocol-base/src/quorum.ts:67): a proposal is accepted
    once the minimum sequence number passes its sequence number with no
    rejection — unanimous-silence consensus.
    """

    key: str
    value: Any
    sequence_number: int  # seq of the propose op (0 until sequenced)
    local: bool = False
    state: ProposalState = ProposalState.PENDING
    rejections: set[str] = field(default_factory=set)
    approval_seq: Optional[int] = None
