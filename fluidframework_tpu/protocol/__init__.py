"""Wire protocol: the contract shared by clients and the ordering service.

Ref: server/routerlicious/packages/protocol-definitions/src/protocol.ts,
summary.ts, consensus.ts, storage.ts and protocol-base/src/quorum.ts,
protocol.ts (see SURVEY.md §2.7).
"""

from .messages import (
    MessageType,
    NackErrorType,
    DocumentMessage,
    SequencedDocumentMessage,
    Nack,
    TraceHop,
    Signal,
    UNASSIGNED_SEQ,
    UNIVERSAL_SEQ,
)
from .summary import (
    SummaryType,
    SummaryBlob,
    SummaryHandle,
    SummaryAttachment,
    SummaryTree,
    SummaryObject,
)
from .consensus import (
    ClientDetails,
    SequencedClient,
    QuorumProposal,
    ProposalState,
)
from .quorum import Quorum, ProtocolOpHandler

__all__ = [
    "MessageType",
    "NackErrorType",
    "DocumentMessage",
    "SequencedDocumentMessage",
    "Nack",
    "TraceHop",
    "Signal",
    "UNASSIGNED_SEQ",
    "UNIVERSAL_SEQ",
    "SummaryType",
    "SummaryBlob",
    "SummaryHandle",
    "SummaryAttachment",
    "SummaryTree",
    "SummaryObject",
    "ClientDetails",
    "SequencedClient",
    "QuorumProposal",
    "ProposalState",
    "Quorum",
    "ProtocolOpHandler",
]
