"""Commit/ref codec for the doc history plane (PR 17).

Every service-summarizer commit becomes a **history commit**
``{id, version, base_seq, parents, chunk_ids, ts}`` — a node in a per-doc
commit graph over snapshot generations, where ``chunk_ids`` are the
content-addressed snapcols chunks the generation references (shared
across generations and across forked docs). **Refs** are named branch
heads (``refs/main``, ``fork/<doc>`` pins) pointing at commit ids.

Both record kinds live in one append-only per-doc history file. Each
record is framed ``u32 len | u32 crc32(payload) | payload`` so a torn
tail (crash mid-append) is detected by length/CRC and dropped — the
scan never raises on trailing garbage, it returns what decoded cleanly
plus the byte offset where the clean prefix ends. ``RefLog`` wraps the
file with an ``flock`` around appends so concurrent writers (summarizer
ticker vs. a fork door) serialize; readers never need the lock because
the clean-prefix scan is safe against a concurrent append.

The codec is pure protocol-layer: fixed fields ride structs, string
lists ride ``u16 len`` frames, and open-ended metadata (fork origin,
integrate provenance) rides a JSON tail — mirroring binwire's
fixed-header + JSON-fallback idiom.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Any, Optional

_U16 = struct.Struct(">H")
_U32 = struct.Struct(">I")
_FRAME = struct.Struct(">II")      # record framing: payload len, crc32
_COMMIT_FIXED = struct.Struct(">qd")  # base_seq, ts
_F64 = struct.Struct(">d")

REC_COMMIT = 1
REC_REF = 2
REC_DISCARD = 3   # recovery marker: a pending fork commit was discarded

# A ref record with an empty commit id deletes the ref.
_MAX_STR = 0xFFFF


def _pack_str(s: str) -> bytes:
    b = s.encode()
    if len(b) > _MAX_STR:
        raise ValueError("refgraph string too long")
    return _U16.pack(len(b)) + b


def _read_str(buf: bytes, off: int) -> tuple[str, int]:
    (n,) = _U16.unpack_from(buf, off)
    off += 2
    return buf[off:off + n].decode(), off + n


def encode_commit(commit: dict) -> bytes:
    """Commit dict → record payload (unframed)."""
    parents = commit.get("parents") or []
    chunk_ids = commit.get("chunk_ids") or []
    extra = commit.get("extra") or {}
    out = [bytes((REC_COMMIT,)),
           _COMMIT_FIXED.pack(int(commit["base_seq"]),
                              float(commit.get("ts") or 0.0)),
           _pack_str(commit["id"]),
           _pack_str(commit["version"]),
           _U16.pack(len(parents))]
    for p in parents:
        out.append(_pack_str(p))
    out.append(_U32.pack(len(chunk_ids)))
    for c in chunk_ids:
        out.append(_pack_str(c))
    eb = json.dumps(extra, separators=(",", ":")).encode() if extra else b""
    out.append(_U32.pack(len(eb)))
    out.append(eb)
    return b"".join(out)


def encode_ref(name: str, commit_id: Optional[str], ts: float = 0.0) -> bytes:
    """Ref update → record payload. ``commit_id=None`` deletes the ref."""
    return (bytes((REC_REF,)) + _F64.pack(float(ts))
            + _pack_str(name) + _pack_str(commit_id or ""))


def encode_discard(commit_id: str) -> bytes:
    """Recovery marker: ``commit_id`` was a pending fork, now discarded."""
    return bytes((REC_DISCARD,)) + _pack_str(commit_id)


def decode_record(payload: bytes) -> dict:
    """Record payload → tagged dict (``t`` = commit | ref | discard)."""
    kind = payload[0]
    if kind == REC_COMMIT:
        base_seq, ts = _COMMIT_FIXED.unpack_from(payload, 1)
        off = 1 + _COMMIT_FIXED.size
        cid, off = _read_str(payload, off)
        version, off = _read_str(payload, off)
        (np_,) = _U16.unpack_from(payload, off)
        off += 2
        parents = []
        for _ in range(np_):
            p, off = _read_str(payload, off)
            parents.append(p)
        (nc,) = _U32.unpack_from(payload, off)
        off += 4
        chunk_ids = []
        for _ in range(nc):
            c, off = _read_str(payload, off)
            chunk_ids.append(c)
        (ne,) = _U32.unpack_from(payload, off)
        off += 4
        extra = (json.loads(payload[off:off + ne].decode()) if ne else {})
        return {"t": "commit", "id": cid, "version": version,
                "base_seq": base_seq, "parents": parents,
                "chunk_ids": chunk_ids, "ts": ts, "extra": extra}
    if kind == REC_REF:
        (ts,) = _F64.unpack_from(payload, 1)
        off = 1 + 8
        name, off = _read_str(payload, off)
        target, off = _read_str(payload, off)
        return {"t": "ref", "name": name, "commit": target or None, "ts": ts}
    if kind == REC_DISCARD:
        cid, _ = _read_str(payload, 1)
        return {"t": "discard", "commit": cid}
    raise ValueError(f"unknown refgraph record kind {kind}")


def frame_record(payload: bytes) -> bytes:
    """Payload → ``u32 len | u32 crc32 | payload`` on-disk frame."""
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


def scan_records(buf: bytes) -> tuple[list[dict], int]:
    """Decode the clean prefix of a history file.

    Returns ``(records, clean_end)``: every record that framed and
    CRC-checked, and the byte offset the clean prefix ends at. A torn
    tail — short frame, short payload, CRC mismatch, or a payload that
    fails structural decode — terminates the scan without raising;
    ``clean_end`` is where an appender should resume (after truncating
    the tail).
    """
    records: list[dict] = []
    off = 0
    n = len(buf)
    while off + _FRAME.size <= n:
        plen, crc = _FRAME.unpack_from(buf, off)
        start = off + _FRAME.size
        end = start + plen
        if plen > n or end > n:          # torn: length ran past EOF
            break
        payload = buf[start:end]
        if zlib.crc32(payload) != crc:   # torn or corrupt: drop the tail
            break
        try:
            records.append(decode_record(payload))
        except Exception:
            break
        off = end
    return records, off


class RefLog:
    """Flocked append-only per-doc history file of framed records.

    Appends hold an ``flock`` (best effort — degrades to plain append
    where ``fcntl`` is unavailable) and truncate any torn tail left by
    a previous crash before extending, so the file always grows from a
    clean prefix. Loading tolerates a torn tail by construction.
    """

    def __init__(self, path: str):
        self.path = path

    def load(self) -> list[dict]:
        try:
            with open(self.path, "rb") as f:
                buf = f.read()
        except FileNotFoundError:
            return []
        records, _ = scan_records(buf)
        return records

    def append(self, *payloads: bytes) -> None:
        data = b"".join(frame_record(p) for p in payloads)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(self.path, "ab") as f:
            try:
                import fcntl
                fcntl.flock(f.fileno(), fcntl.LOCK_EX)
            except Exception:
                pass
            try:
                # heal a torn tail before extending past it
                f.seek(0, os.SEEK_END)
                size = f.tell()
                if size:
                    with open(self.path, "rb") as rf:
                        _, clean = scan_records(rf.read())
                    if clean != size:
                        f.truncate(clean)
                        f.seek(0, os.SEEK_END)
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            finally:
                try:
                    import fcntl
                    fcntl.flock(f.fileno(), fcntl.LOCK_UN)
                except Exception:
                    pass

    def truncate_at(self, size: int) -> None:
        """Chop the file to ``size`` bytes (chaos/test helper: tear the
        tail mid-record the way a crash would)."""
        with open(self.path, "r+b") as f:
            f.truncate(size)


def replay_records(records: list[dict]) -> tuple[dict, dict, set]:
    """Fold a record stream into ``(commits, refs, discarded)``.

    ``commits`` maps commit id → commit dict, ``refs`` maps ref name →
    commit id, ``discarded`` is the set of commit ids recovery chose to
    abandon (their records stay in the file; the marker wins).
    """
    commits: dict[str, dict] = {}
    refs: dict[str, str] = {}
    discarded: set = set()
    for rec in records:
        t = rec["t"]
        if t == "commit":
            commits[rec["id"]] = {k: rec[k] for k in
                                  ("id", "version", "base_seq", "parents",
                                   "chunk_ids", "ts", "extra")}
        elif t == "ref":
            if rec["commit"] is None:
                refs.pop(rec["name"], None)
            else:
                refs[rec["name"]] = rec["commit"]
        elif t == "discard":
            discarded.add(rec["commit"])
    return commits, refs, discarded


def commit_to_json(commit: dict) -> dict:
    """Commit dict → JSON-safe dict for RPC replies (stable key order)."""
    return {"id": commit["id"], "version": commit["version"],
            "base_seq": commit["base_seq"], "parents": list(commit["parents"]),
            "chunk_ids": list(commit["chunk_ids"]), "ts": commit["ts"],
            "extra": dict(commit.get("extra") or {})}


__all__ = [
    "REC_COMMIT", "REC_REF", "REC_DISCARD",
    "encode_commit", "encode_ref", "encode_discard", "decode_record",
    "frame_record", "scan_records", "replay_records", "RefLog",
    "commit_to_json",
]
