"""Binary wire codec for the HOT frames of the socket protocol.

Ref: the reference ships every socket payload as JSON over socket.io
(driver-base/src/documentDeltaConnection.ts:53, alfred index.ts:310);
at the round-3 measured knee the front end spent its whole budget in
per-frame ``json.loads``/``dumps`` (submit→deli p99 5.3 ms of 5.9 total).
SURVEY §2.9 prescribes a binary front end for exactly this reason. This
module is the TPU-first answer: the two frames that carry the op volume
(client submit boxcars and sequenced broadcast batches) get a
struct-packed encoding; everything else (connect, signals, storage RPCs)
stays JSON.

Frame discrimination needs no negotiation on the READ side: JSON bodies
start with ``{`` (0x7B), binary bodies with MAGIC (0x01). The 4-byte
length header is shared with the JSON framing (front_end.py docstring).

Layout (all integers big-endian):

    body   := MAGIC ftype hdr(ftype) batch
    MAGIC  := 0x01
    ftype  := 1 submit | 2 ops | 3 fsubmit | 4 fops
    hdr    := ""                       (submit, ops)
            | u32 sid                  (fsubmit)
            | u16 len + utf8 topic     (fops)
    batch  := pool recs
    pool   := u16 n; n × (u16 len + utf8)     -- interned strings
    recs   := u16 n; n × rec

The batch section is IDENTICAL across the four frame types — that is the
load-bearing property: a gateway converts a client ``submit`` into an
upstream ``fsubmit`` by prepending 6 bytes to the received body, and a
core ``fops`` into a client ``ops`` by slicing the topic header off,
relaying op payloads it never decodes (gateway.py).

rec (submit: DocumentMessage):

    i32 cseq, i32 rseq, traces, u8 kind, payload(kind)

rec (ops: SequencedDocumentMessage):

    u16 client_id_idx (0xFFFF = None), i64 seq, i64 msn,
    i32 cseq, i32 rseq, f64 timestamp, traces, u8 kind, payload(kind)

    traces := u8 n; n × (u16 svc_idx, u16 act_idx, f64 ts)

kind encodes the merge-tree chanop fast path — the envelope
``{"kind": "chanop", "address": ds, "contents": {"address": ch,
"contents": op}}`` (runtime/datastore.py wire shape) collapses to
interned addresses + fixed fields:

    0 insert   := u16 ds_idx, u16 ch_idx, u32 pos, u16 len + utf8 text
    1 remove   := u16 ds_idx, u16 ch_idx, u32 start, u32 end
    2 annotate := u16 ds_idx, u16 ch_idx, u32 start, u32 end,
                  u16 len + utf8 props-JSON
    255 generic:= u32 len + utf8 JSON of the non-fixed message fields
                  ({type, contents, metadata[, origin]}) — ANY message
                  round-trips; the fast kinds are an optimization, not a
                  constraint (test_binwire fuzzes both against the JSON
                  codec for equality).
"""

from __future__ import annotations

import json
import struct
from typing import Optional

from .messages import (
    DocumentMessage,
    MessageType,
    SequencedDocumentMessage,
    TraceHop,
)

MAGIC = 0x01
FT_SUBMIT = 1
FT_OPS = 2
FT_FSUBMIT = 3
FT_FOPS = 4

_U16 = struct.Struct(">H")
_U32 = struct.Struct(">I")
_DOC_FIXED = struct.Struct(">ii")           # cseq, rseq
_SEQ_FIXED = struct.Struct(">Hqqiid")       # cid_idx, seq, msn, cseq, rseq, ts
_TRACE = struct.Struct(">HHd")              # svc_idx, act_idx, ts
_INS_HDR = struct.Struct(">HHI")            # ds, ch, pos
_SPAN = struct.Struct(">HHII")              # ds, ch, start, end
_FSUB_HDR = struct.Struct(">BBI")           # magic, ftype, sid

_NONE_IDX = 0xFFFF
_MAX_U32 = 0xFFFFFFFF

_OP_TYPE = MessageType.OPERATION


class _Pool:
    """Build-side string interner for the frame's string pool."""

    __slots__ = ("idx", "items")

    def __init__(self):
        self.idx: dict[str, int] = {}
        self.items: list[bytes] = []

    def add(self, s: str) -> int:
        i = self.idx.get(s)
        if i is None:
            i = len(self.items)
            if i >= _NONE_IDX:
                raise ValueError("string pool overflow")
            self.idx[s] = i
            self.items.append(s.encode())
        return i

    def dump(self) -> bytes:
        out = [_U16.pack(len(self.items))]
        for b in self.items:
            out.append(_U16.pack(len(b)))
            out.append(b)
        return b"".join(out)


def _chanop_parts(contents) -> Optional[tuple]:
    """(ds, ch, op) if contents is a plain chanop envelope, else None."""
    if type(contents) is not dict or contents.get("kind") != "chanop":
        return None
    ds = contents.get("address")
    inner = contents.get("contents")
    if (type(ds) is not str or type(inner) is not dict
            or len(contents) != 3 or len(inner) != 2):
        return None
    ch = inner.get("address")
    op = inner.get("contents")
    if type(ch) is not str or type(op) is not dict:
        return None
    return ds, ch, op


def _u32_ok(*vals) -> bool:
    for v in vals:
        if type(v) is not int or v < 0 or v > _MAX_U32:
            return False
    return True


def _encode_payload(pool: _Pool, out: list, type_, contents, metadata,
                    origin=None) -> None:
    """Append ``u8 kind + payload`` for one message's variable part."""
    if type_ is _OP_TYPE and metadata is None and origin is None:
        parts = _chanop_parts(contents)
        if parts is not None:
            ds, ch, op = parts
            t = op.get("type")
            if t == 0 and len(op) == 3:
                text = op.get("text")
                pos = op.get("pos")
                if type(text) is str and _u32_ok(pos):
                    tb = text.encode()
                    if len(tb) <= 0xFFFF:
                        out.append(b"\x00")
                        out.append(_INS_HDR.pack(pool.add(ds), pool.add(ch),
                                                 pos))
                        out.append(_U16.pack(len(tb)))
                        out.append(tb)
                        return
            elif t == 1 and len(op) == 3:
                start, end = op.get("start"), op.get("end")
                if _u32_ok(start, end):
                    out.append(b"\x01")
                    out.append(_SPAN.pack(pool.add(ds), pool.add(ch),
                                          start, end))
                    return
            elif t == 2 and len(op) == 4 and type(op.get("props")) is dict:
                start, end = op.get("start"), op.get("end")
                if _u32_ok(start, end):
                    pb = json.dumps(op["props"],
                                    separators=(",", ":")).encode()
                    if len(pb) <= 0xFFFF:
                        out.append(b"\x02")
                        out.append(_SPAN.pack(pool.add(ds), pool.add(ch),
                                              start, end))
                        out.append(_U16.pack(len(pb)))
                        out.append(pb)
                        return
    # generic fallback: the non-fixed fields as JSON
    d = {"type": type_, "contents": contents, "metadata": metadata}
    if origin is not None:
        d["origin"] = origin
    gb = json.dumps(d, separators=(",", ":")).encode()
    out.append(b"\xff")
    out.append(_U32.pack(len(gb)))
    out.append(gb)


def _encode_traces(pool: _Pool, out: list, traces) -> None:
    n = len(traces)
    if n > 0xFF:  # absurd, but stay correct
        traces = traces[-0xFF:]
        n = 0xFF
    out.append(bytes((n,)))
    for t in traces:
        out.append(_TRACE.pack(pool.add(t.service), pool.add(t.action),
                               t.timestamp))


def encode_submit(ops: list[DocumentMessage], *, sid: Optional[int] = None,
                  ) -> bytes:
    """Encode a submit boxcar body (``fsubmit`` when ``sid`` is given)."""
    pool = _Pool()
    recs: list = [_U16.pack(len(ops))]
    for m in ops:
        recs.append(_DOC_FIXED.pack(m.client_sequence_number,
                                    m.reference_sequence_number))
        _encode_traces(pool, recs, m.traces)
        _encode_payload(pool, recs, m.type, m.contents, m.metadata)
    hdr = (bytes((MAGIC, FT_SUBMIT)) if sid is None
           else _FSUB_HDR.pack(MAGIC, FT_FSUBMIT, sid))
    return hdr + pool.dump() + b"".join(recs)


def encode_ops(msgs: list[SequencedDocumentMessage], *,
               topic: Optional[str] = None) -> bytes:
    """Encode a sequenced broadcast batch body (``fops`` with a topic)."""
    pool = _Pool()
    recs: list = [_U16.pack(len(msgs))]
    for m in msgs:
        cid = m.client_id
        recs.append(_SEQ_FIXED.pack(
            _NONE_IDX if cid is None else pool.add(cid),
            m.sequence_number, m.minimum_sequence_number,
            m.client_sequence_number, m.reference_sequence_number,
            m.timestamp))
        _encode_traces(pool, recs, m.traces)
        _encode_payload(pool, recs, m.type, m.contents, m.metadata, m.origin)
    if topic is None:
        hdr = bytes((MAGIC, FT_OPS))
    else:
        tb = topic.encode()
        hdr = bytes((MAGIC, FT_FOPS)) + _U16.pack(len(tb)) + tb
    return hdr + pool.dump() + b"".join(recs)


# ---------------------------------------------------------------- decoding


def _read_pool(body: bytes, off: int) -> tuple[list[str], int]:
    (n,) = _U16.unpack_from(body, off)
    off += 2
    pool = []
    for _ in range(n):
        (ln,) = _U16.unpack_from(body, off)
        off += 2
        pool.append(body[off:off + ln].decode())
        off += ln
    return pool, off


def _read_traces(body: bytes, off: int, pool: list[str]
                 ) -> tuple[list[TraceHop], int]:
    n = body[off]
    off += 1
    traces = []
    for _ in range(n):
        svc, act, ts = _TRACE.unpack_from(body, off)
        off += _TRACE.size
        traces.append(TraceHop(service=pool[svc], action=pool[act],
                               timestamp=ts))
    return traces, off


def _read_payload(body: bytes, off: int, pool: list[str]) -> tuple:
    """Returns (type, contents, metadata, origin, new_off)."""
    kind = body[off]
    off += 1
    if kind == 0:
        ds, ch, pos = _INS_HDR.unpack_from(body, off)
        off += _INS_HDR.size
        (ln,) = _U16.unpack_from(body, off)
        off += 2
        text = body[off:off + ln].decode()
        off += ln
        op = {"type": 0, "pos": pos, "text": text}
    elif kind == 1:
        ds, ch, start, end = _SPAN.unpack_from(body, off)
        off += _SPAN.size
        op = {"type": 1, "start": start, "end": end}
    elif kind == 2:
        ds, ch, start, end = _SPAN.unpack_from(body, off)
        off += _SPAN.size
        (ln,) = _U16.unpack_from(body, off)
        off += 2
        op = {"type": 2, "start": start, "end": end,
              "props": json.loads(body[off:off + ln])}
        off += ln
    elif kind == 0xFF:
        (ln,) = _U32.unpack_from(body, off)
        off += 4
        d = json.loads(body[off:off + ln])
        off += ln
        return (MessageType(d["type"]), d.get("contents"),
                d.get("metadata"), d.get("origin"), off)
    else:
        raise ValueError(f"unknown binwire payload kind {kind}")
    contents = {"kind": "chanop", "address": pool[ds],
                "contents": {"address": pool[ch], "contents": op}}
    return _OP_TYPE, contents, None, None, off


def decode_submit(body: bytes, with_spans: bool = False):
    """Decode a submit/fsubmit body → (sid or None, ops).

    With ``with_spans`` additionally returns a splice context the
    broadcast encoder can reuse (see :func:`encode_ops_spliced`):
    ``(sid, ops, spans_by_contents_id, pool_entries_blob, npool)`` —
    spans are the raw payload bytes (kind byte included) keyed by
    ``id(op.contents)``, valid while the decoded contents objects live."""
    ftype = body[1]
    if ftype == FT_FSUBMIT:
        (sid,) = _U32.unpack_from(body, 2)
        off = _FSUB_HDR.size
    else:
        sid, off = None, 2
    pool_start = off + 2
    pool, off = _read_pool(body, off)
    pool_blob = body[pool_start:off]
    (n,) = _U16.unpack_from(body, off)
    off += 2
    ops = []
    spans: dict[int, bytes] = {}
    for _ in range(n):
        cseq, rseq = _DOC_FIXED.unpack_from(body, off)
        off += _DOC_FIXED.size
        traces, off = _read_traces(body, off, pool)
        payload_start = off
        type_, contents, metadata, _, off = _read_payload(body, off, pool)
        op = DocumentMessage(
            client_sequence_number=cseq, reference_sequence_number=rseq,
            type=type_, contents=contents, metadata=metadata, traces=traces)
        ops.append(op)
        if with_spans and type(contents) is dict:
            # identity-keyed: safe ONLY for dicts — json.loads returns a
            # fresh dict per record (unique id while the ops are alive),
            # whereas interned payloads (small ints, bools, str) would
            # collide across records and splice the wrong bytes
            spans[id(contents)] = body[payload_start:off]
    if with_spans:
        return sid, ops, spans, pool_blob, len(pool)
    return sid, ops


def decode_ops(body: bytes) -> tuple[Optional[str],
                                     list[SequencedDocumentMessage]]:
    """Decode an ops/fops body → (topic or None, msgs)."""
    ftype = body[1]
    if ftype == FT_FOPS:
        (tl,) = _U16.unpack_from(body, 2)
        topic = body[4:4 + tl].decode()
        off = 4 + tl
    else:
        topic, off = None, 2
    pool, off = _read_pool(body, off)
    (n,) = _U16.unpack_from(body, off)
    off += 2
    msgs = []
    for _ in range(n):
        cid_idx, seq, msn, cseq, rseq, ts = _SEQ_FIXED.unpack_from(body, off)
        off += _SEQ_FIXED.size
        traces, off = _read_traces(body, off, pool)
        type_, contents, metadata, origin, off = _read_payload(body, off, pool)
        msgs.append(SequencedDocumentMessage(
            client_id=None if cid_idx == _NONE_IDX else pool[cid_idx],
            sequence_number=seq, minimum_sequence_number=msn,
            client_sequence_number=cseq, reference_sequence_number=rseq,
            type=type_, contents=contents, metadata=metadata, origin=origin,
            timestamp=ts, traces=traces))
    return topic, msgs


def encode_ops_spliced(msgs: list[SequencedDocumentMessage],
                       spans: dict[int, bytes], pool_blob: bytes,
                       npool: int, *,
                       topic: Optional[str] = None) -> Optional[bytes]:
    """Encode a broadcast batch by SPLICING the submitted payload bytes.

    The deli fast lane emits sequenced messages whose ``contents`` are
    the very objects the submit decode produced, so the broadcast frame
    can reuse the submit frame's payload bytes and string pool verbatim:
    per op only the fixed header and trace hops are packed fresh, and
    the payload — the bulk of the record — is a bytes copy. Returns
    None when any message's contents is not from the splice context
    (scalar-lane fallback, system messages): the caller then uses
    :func:`encode_ops`.
    """
    extra = _Pool()
    recs: list = [_U16.pack(len(msgs))]
    try:
        for m in msgs:
            span = spans.get(id(m.contents))
            if span is None or m.origin is not None:
                return None
            cid = m.client_id
            recs.append(_SEQ_FIXED.pack(
                _NONE_IDX if cid is None else npool + extra.add(cid),
                m.sequence_number, m.minimum_sequence_number,
                m.client_sequence_number, m.reference_sequence_number,
                m.timestamp))
            traces = m.traces
            n = len(traces)
            if n > 0xFF:
                traces = traces[-0xFF:]
                n = 0xFF
            recs.append(bytes((n,)))
            for t in traces:
                recs.append(_TRACE.pack(npool + extra.add(t.service),
                                        npool + extra.add(t.action),
                                        t.timestamp))
            recs.append(span)
        total = npool + len(extra.items)
        if total >= _NONE_IDX:
            return None
    except struct.error:
        return None
    if topic is None:
        hdr = bytes((MAGIC, FT_OPS))
    else:
        tb = topic.encode()
        hdr = bytes((MAGIC, FT_FOPS)) + _U16.pack(len(tb)) + tb
    pool_out = [_U16.pack(total), pool_blob]
    for b in extra.items:
        pool_out.append(_U16.pack(len(b)))
        pool_out.append(b)
    return hdr + b"".join(pool_out) + b"".join(recs)


def scan_ops(body: bytes):
    """Lightweight walk of an ops/fops body for load observers.

    Yields one tuple per record WITHOUT constructing message objects or
    contents dicts — the load worker's broadcast observer only needs op
    identity and the visible-length delta, and at the measured knee the
    full decode (dataclass + 3 nested dicts per op, times every
    subscriber) was the workers' largest CPU item:

        (client_id | None, seq, cseq, deli_ts | None, delta)

    ``delta`` is the op's visible-length change: +chars for an insert
    (ASCII payloads: byte length == char length — the synthetic load
    generator emits ASCII-only text), -span for a remove, 0 otherwise
    (annotate/generic). ``deli_ts`` is the last deli/sequence trace hop
    timestamp when the record carries one.
    """
    ftype = body[1]
    if ftype == FT_FOPS:
        (tl,) = _U16.unpack_from(body, 2)
        off = 4 + tl
    else:
        off = 2
    pool, off = _read_pool(body, off)
    deli_idx = None
    for i, s in enumerate(pool):
        if s == "deli":
            deli_idx = i
            break
    (n,) = _U16.unpack_from(body, off)
    off += 2
    for _ in range(n):
        cid_idx, seq, msn, cseq, rseq, ts = _SEQ_FIXED.unpack_from(body, off)
        off += _SEQ_FIXED.size
        ntr = body[off]
        off += 1
        deli_ts = None
        for _t in range(ntr):
            svc, act, hop_ts = _TRACE.unpack_from(body, off)
            off += _TRACE.size
            if svc == deli_idx:
                deli_ts = hop_ts
        kind = body[off]
        off += 1
        delta = 0
        if kind == 0:
            off += _INS_HDR.size
            (ln,) = _U16.unpack_from(body, off)
            off += 2 + ln
            delta = ln
        elif kind == 1:
            _, _, start, end = _SPAN.unpack_from(body, off)
            off += _SPAN.size
            delta = start - end
        elif kind == 2:
            off += _SPAN.size
            (ln,) = _U16.unpack_from(body, off)
            off += 2 + ln
        elif kind == 0xFF:
            (ln,) = _U32.unpack_from(body, off)
            off += 4 + ln
        else:
            raise ValueError(f"unknown binwire payload kind {kind}")
        yield (None if cid_idx == _NONE_IDX else pool[cid_idx],
               seq, cseq, deli_ts, delta)


# --------------------------------------------------- gateway byte rewrites
# The relay operations gateway.py performs WITHOUT decoding op payloads.


def submit_to_fsubmit(body: bytes, sid: int) -> bytes:
    """Rewrite a client ``submit`` body into an upstream ``fsubmit``."""
    return _FSUB_HDR.pack(MAGIC, FT_FSUBMIT, sid) + body[2:]


def fops_strip_topic(body: bytes) -> tuple[str, bytes]:
    """Split an ``fops`` body → (topic, client-facing ``ops`` body)."""
    (tl,) = _U16.unpack_from(body, 2)
    topic = body[4:4 + tl].decode()
    return topic, bytes((MAGIC, FT_OPS)) + body[4 + tl:]


def is_binary(body: bytes) -> bool:
    return bool(body) and body[0] == MAGIC


def frame(body: bytes) -> bytes:
    """Prepend the shared 4-byte length header."""
    return len(body).to_bytes(4, "big") + body
