"""Binary wire codec for the HOT frames of the socket protocol.

Ref: the reference ships every socket payload as JSON over socket.io
(driver-base/src/documentDeltaConnection.ts:53, alfred index.ts:310);
at the round-3 measured knee the front end spent its whole budget in
per-frame ``json.loads``/``dumps`` (submit→deli p99 5.3 ms of 5.9 total).
SURVEY §2.9 prescribes a binary front end for exactly this reason. This
module is the TPU-first answer: the two frames that carry the op volume
(client submit boxcars and sequenced broadcast batches) get a
struct-packed encoding; everything else (connect, signals, storage RPCs)
stays JSON.

Frame discrimination needs no negotiation on the READ side: JSON bodies
start with ``{`` (0x7B), binary bodies with MAGIC (0x01). The 4-byte
length header is shared with the JSON framing (front_end.py docstring).

Layout (all integers big-endian):

    body   := MAGIC ftype hdr(ftype) batch
    MAGIC  := 0x01
    ftype  := 1 submit | 2 ops | 3 fsubmit | 4 fops
            | 5 cols_submit | 6 cols_fsubmit | 7 cols_ops | 8 cols_fops
    hdr    := ""                       (submit, ops, cols_submit, cols_ops)
            | u32 sid                  (fsubmit, cols_fsubmit)
            | u16 len + utf8 topic     (fops, cols_fops)
    batch  := pool recs
    pool   := u16 n; n × (u16 len + utf8)     -- interned strings
    recs   := u16 n; n × rec

The batch section is IDENTICAL across the four frame types — that is the
load-bearing property: a gateway converts a client ``submit`` into an
upstream ``fsubmit`` by prepending 6 bytes to the received body, and a
core ``fops`` into a client ``ops`` by slicing the topic header off,
relaying op payloads it never decodes (gateway.py).

rec (submit: DocumentMessage):

    i32 cseq, i32 rseq, traces, u8 kind, payload(kind)

rec (ops: SequencedDocumentMessage):

    u16 client_id_idx (0xFFFF = None), i64 seq, i64 msn,
    i32 cseq, i32 rseq, f64 timestamp, traces, u8 kind, payload(kind)

    traces := u8 n; n × (u16 svc_idx, u16 act_idx, f64 ts)

kind encodes the merge-tree chanop fast path — the envelope
``{"kind": "chanop", "address": ds, "contents": {"address": ch,
"contents": op}}`` (runtime/datastore.py wire shape) collapses to
interned addresses + fixed fields:

    0 insert   := u16 ds_idx, u16 ch_idx, u32 pos, u16 len + utf8 text
    1 remove   := u16 ds_idx, u16 ch_idx, u32 start, u32 end
    2 annotate := u16 ds_idx, u16 ch_idx, u32 start, u32 end,
                  u16 len + utf8 props-JSON
    255 generic:= u32 len + utf8 JSON of the non-fixed message fields
                  ({type, contents, metadata[, origin]}) — ANY message
                  round-trips; the fast kinds are an optimization, not a
                  constraint (test_binwire fuzzes both against the JSON
                  codec for equality).
"""

from __future__ import annotations

import json
import struct
from typing import Optional

import numpy as np

from ..utils.telemetry import HOP_SERVICE_ACTION
from .messages import (
    DocumentMessage,
    MessageType,
    SequencedDocumentMessage,
    Signal,
    TraceHop,
)

MAGIC = 0x01
FT_SUBMIT = 1
FT_OPS = 2
FT_FSUBMIT = 3
FT_FOPS = 4
FT_COLS_SUBMIT = 5
FT_COLS_FSUBMIT = 6
FT_COLS_OPS = 7
FT_COLS_FOPS = 8
FT_COLS_DELTAS = 9
FT_COLS_SNAP = 10
FT_PRESENCE = 11
FT_FPRESENCE = 12
FT_HISTORY = 13

_U16 = struct.Struct(">H")
_U32 = struct.Struct(">I")
_DOC_FIXED = struct.Struct(">ii")           # cseq, rseq
_SEQ_FIXED = struct.Struct(">Hqqiid")       # cid_idx, seq, msn, cseq, rseq, ts
_TRACE = struct.Struct(">HHd")              # svc_idx, act_idx, ts
_INS_HDR = struct.Struct(">HHI")            # ds, ch, pos
_SPAN = struct.Struct(">HHII")              # ds, ch, start, end
_FSUB_HDR = struct.Struct(">BBI")           # magic, ftype, sid
_HOP = struct.Struct(">Bd")                 # hoptail entry: hop id, unix ts

_NONE_IDX = 0xFFFF
_MAX_U32 = 0xFFFFFFFF

_OP_TYPE = MessageType.OPERATION


class _Pool:
    """Build-side string interner for the frame's string pool."""

    __slots__ = ("idx", "items")

    def __init__(self):
        self.idx: dict[str, int] = {}
        self.items: list[bytes] = []

    def add(self, s: str) -> int:
        i = self.idx.get(s)
        if i is None:
            i = len(self.items)
            if i >= _NONE_IDX:
                raise ValueError("string pool overflow")
            self.idx[s] = i
            self.items.append(s.encode())
        return i

    def dump(self) -> bytes:
        out = [_U16.pack(len(self.items))]
        for b in self.items:
            out.append(_U16.pack(len(b)))
            out.append(b)
        return b"".join(out)


def _chanop_parts(contents) -> Optional[tuple]:
    """(ds, ch, op) if contents is a plain chanop envelope, else None."""
    if type(contents) is not dict or contents.get("kind") != "chanop":
        return None
    ds = contents.get("address")
    inner = contents.get("contents")
    if (type(ds) is not str or type(inner) is not dict
            or len(contents) != 3 or len(inner) != 2):
        return None
    ch = inner.get("address")
    op = inner.get("contents")
    if type(ch) is not str or type(op) is not dict:
        return None
    return ds, ch, op


def _u32_ok(*vals) -> bool:
    for v in vals:
        if type(v) is not int or v < 0 or v > _MAX_U32:
            return False
    return True


def _encode_payload(pool: _Pool, out: list, type_, contents, metadata,
                    origin=None) -> None:
    """Append ``u8 kind + payload`` for one message's variable part."""
    if type_ is _OP_TYPE and metadata is None and origin is None:
        parts = _chanop_parts(contents)
        if parts is not None:
            ds, ch, op = parts
            t = op.get("type")
            if t == 0 and len(op) == 3:
                text = op.get("text")
                pos = op.get("pos")
                if type(text) is str and _u32_ok(pos):
                    tb = text.encode()
                    if len(tb) <= 0xFFFF:
                        out.append(b"\x00")
                        out.append(_INS_HDR.pack(pool.add(ds), pool.add(ch),
                                                 pos))
                        out.append(_U16.pack(len(tb)))
                        out.append(tb)
                        return
            elif t == 1 and len(op) == 3:
                start, end = op.get("start"), op.get("end")
                if _u32_ok(start, end):
                    out.append(b"\x01")
                    out.append(_SPAN.pack(pool.add(ds), pool.add(ch),
                                          start, end))
                    return
            elif t == 2 and len(op) == 4 and type(op.get("props")) is dict:
                start, end = op.get("start"), op.get("end")
                if _u32_ok(start, end):
                    pb = json.dumps(op["props"],
                                    separators=(",", ":")).encode()
                    if len(pb) <= 0xFFFF:
                        out.append(b"\x02")
                        out.append(_SPAN.pack(pool.add(ds), pool.add(ch),
                                              start, end))
                        out.append(_U16.pack(len(pb)))
                        out.append(pb)
                        return
    # generic fallback: the non-fixed fields as JSON
    d = {"type": type_, "contents": contents, "metadata": metadata}
    if origin is not None:
        d["origin"] = origin
    gb = json.dumps(d, separators=(",", ":")).encode()
    out.append(b"\xff")
    out.append(_U32.pack(len(gb)))
    out.append(gb)


def _encode_traces(pool: _Pool, out: list, traces) -> None:
    n = len(traces)
    if n > 0xFF:  # absurd, but stay correct
        traces = traces[-0xFF:]
        n = 0xFF
    out.append(bytes((n,)))
    for t in traces:
        out.append(_TRACE.pack(pool.add(t.service), pool.add(t.action),
                               t.timestamp))


def encode_submit(ops: list[DocumentMessage], *, sid: Optional[int] = None,
                  ) -> bytes:
    """Encode a submit boxcar body (``fsubmit`` when ``sid`` is given)."""
    pool = _Pool()
    recs: list = [_U16.pack(len(ops))]
    for m in ops:
        recs.append(_DOC_FIXED.pack(m.client_sequence_number,
                                    m.reference_sequence_number))
        _encode_traces(pool, recs, m.traces)
        _encode_payload(pool, recs, m.type, m.contents, m.metadata)
    hdr = (bytes((MAGIC, FT_SUBMIT)) if sid is None
           else _FSUB_HDR.pack(MAGIC, FT_FSUBMIT, sid))
    return hdr + pool.dump() + b"".join(recs)


def encode_ops(msgs: list[SequencedDocumentMessage], *,
               topic: Optional[str] = None) -> bytes:
    """Encode a sequenced broadcast batch body (``fops`` with a topic)."""
    pool = _Pool()
    recs: list = [_U16.pack(len(msgs))]
    for m in msgs:
        cid = m.client_id
        recs.append(_SEQ_FIXED.pack(
            _NONE_IDX if cid is None else pool.add(cid),
            m.sequence_number, m.minimum_sequence_number,
            m.client_sequence_number, m.reference_sequence_number,
            m.timestamp))
        _encode_traces(pool, recs, m.traces)
        _encode_payload(pool, recs, m.type, m.contents, m.metadata, m.origin)
    if topic is None:
        hdr = bytes((MAGIC, FT_OPS))
    else:
        tb = topic.encode()
        hdr = bytes((MAGIC, FT_FOPS)) + _U16.pack(len(tb)) + tb
    return hdr + pool.dump() + b"".join(recs)


# ---------------------------------------------------------------- decoding


def _read_pool(body: bytes, off: int) -> tuple[list[str], int]:
    (n,) = _U16.unpack_from(body, off)
    off += 2
    pool = []
    for _ in range(n):
        (ln,) = _U16.unpack_from(body, off)
        off += 2
        pool.append(body[off:off + ln].decode())
        off += ln
    return pool, off


def _read_traces(body: bytes, off: int, pool: list[str]
                 ) -> tuple[list[TraceHop], int]:
    n = body[off]
    off += 1
    traces = []
    for _ in range(n):
        svc, act, ts = _TRACE.unpack_from(body, off)
        off += _TRACE.size
        traces.append(TraceHop(service=pool[svc], action=pool[act],
                               timestamp=ts))
    return traces, off


def _read_payload(body: bytes, off: int, pool: list[str]) -> tuple:
    """Returns (type, contents, metadata, origin, new_off)."""
    kind = body[off]
    off += 1
    if kind == 0:
        ds, ch, pos = _INS_HDR.unpack_from(body, off)
        off += _INS_HDR.size
        (ln,) = _U16.unpack_from(body, off)
        off += 2
        text = body[off:off + ln].decode()
        off += ln
        op = {"type": 0, "pos": pos, "text": text}
    elif kind == 1:
        ds, ch, start, end = _SPAN.unpack_from(body, off)
        off += _SPAN.size
        op = {"type": 1, "start": start, "end": end}
    elif kind == 2:
        ds, ch, start, end = _SPAN.unpack_from(body, off)
        off += _SPAN.size
        (ln,) = _U16.unpack_from(body, off)
        off += 2
        op = {"type": 2, "start": start, "end": end,
              "props": json.loads(body[off:off + ln])}
        off += ln
    elif kind == 0xFF:
        (ln,) = _U32.unpack_from(body, off)
        off += 4
        d = json.loads(body[off:off + ln])
        off += ln
        return (MessageType(d["type"]), d.get("contents"),
                d.get("metadata"), d.get("origin"), off)
    else:
        raise ValueError(f"unknown binwire payload kind {kind}")
    contents = {"kind": "chanop", "address": pool[ds],
                "contents": {"address": pool[ch], "contents": op}}
    return _OP_TYPE, contents, None, None, off


def decode_submit(body: bytes, with_spans: bool = False):
    """Decode a submit/fsubmit body → (sid or None, ops).

    With ``with_spans`` additionally returns a splice context the
    broadcast encoder can reuse (see :func:`encode_ops_spliced`):
    ``(sid, ops, spans_by_contents_id, pool_entries_blob, npool)`` —
    spans are the raw payload bytes (kind byte included) keyed by
    ``id(op.contents)``, valid while the decoded contents objects live."""
    ftype = body[1]
    if ftype == FT_FSUBMIT:
        (sid,) = _U32.unpack_from(body, 2)
        off = _FSUB_HDR.size
    else:
        sid, off = None, 2
    pool_start = off + 2
    pool, off = _read_pool(body, off)
    pool_blob = body[pool_start:off]
    (n,) = _U16.unpack_from(body, off)
    off += 2
    ops = []
    spans: dict[int, bytes] = {}
    for _ in range(n):
        cseq, rseq = _DOC_FIXED.unpack_from(body, off)
        off += _DOC_FIXED.size
        traces, off = _read_traces(body, off, pool)
        payload_start = off
        type_, contents, metadata, _, off = _read_payload(body, off, pool)
        op = DocumentMessage(
            client_sequence_number=cseq, reference_sequence_number=rseq,
            type=type_, contents=contents, metadata=metadata, traces=traces)
        ops.append(op)
        if with_spans and type(contents) is dict:
            # identity-keyed: safe ONLY for dicts — json.loads returns a
            # fresh dict per record (unique id while the ops are alive),
            # whereas interned payloads (small ints, bools, str) would
            # collide across records and splice the wrong bytes
            spans[id(contents)] = body[payload_start:off]
    if with_spans:
        return sid, ops, spans, pool_blob, len(pool)
    return sid, ops


def decode_ops(body: bytes) -> tuple[Optional[str],
                                     list[SequencedDocumentMessage]]:
    """Decode an ops/fops body → (topic or None, msgs)."""
    ftype = body[1]
    if ftype == FT_COLS_OPS or ftype == FT_COLS_FOPS:
        return decode_cols_ops(body)
    if ftype == FT_FOPS:
        (tl,) = _U16.unpack_from(body, 2)
        topic = body[4:4 + tl].decode()
        off = 4 + tl
    else:
        topic, off = None, 2
    pool, off = _read_pool(body, off)
    (n,) = _U16.unpack_from(body, off)
    off += 2
    msgs = []
    for _ in range(n):
        cid_idx, seq, msn, cseq, rseq, ts = _SEQ_FIXED.unpack_from(body, off)
        off += _SEQ_FIXED.size
        traces, off = _read_traces(body, off, pool)
        type_, contents, metadata, origin, off = _read_payload(body, off, pool)
        msgs.append(SequencedDocumentMessage(
            client_id=None if cid_idx == _NONE_IDX else pool[cid_idx],
            sequence_number=seq, minimum_sequence_number=msn,
            client_sequence_number=cseq, reference_sequence_number=rseq,
            type=type_, contents=contents, metadata=metadata, origin=origin,
            timestamp=ts, traces=traces))
    return topic, msgs


def encode_ops_spliced(msgs: list[SequencedDocumentMessage],
                       spans: dict[int, bytes], pool_blob: bytes,
                       npool: int, *,
                       topic: Optional[str] = None) -> Optional[bytes]:
    """Encode a broadcast batch by SPLICING the submitted payload bytes.

    The deli fast lane emits sequenced messages whose ``contents`` are
    the very objects the submit decode produced, so the broadcast frame
    can reuse the submit frame's payload bytes and string pool verbatim:
    per op only the fixed header and trace hops are packed fresh, and
    the payload — the bulk of the record — is a bytes copy. Returns
    None when any message's contents is not from the splice context
    (scalar-lane fallback, system messages): the caller then uses
    :func:`encode_ops`.
    """
    extra = _Pool()
    recs: list = [_U16.pack(len(msgs))]
    try:
        for m in msgs:
            span = spans.get(id(m.contents))
            if span is None or m.origin is not None:
                return None
            cid = m.client_id
            recs.append(_SEQ_FIXED.pack(
                _NONE_IDX if cid is None else npool + extra.add(cid),
                m.sequence_number, m.minimum_sequence_number,
                m.client_sequence_number, m.reference_sequence_number,
                m.timestamp))
            traces = m.traces
            n = len(traces)
            if n > 0xFF:
                traces = traces[-0xFF:]
                n = 0xFF
            recs.append(bytes((n,)))
            for t in traces:
                recs.append(_TRACE.pack(npool + extra.add(t.service),
                                        npool + extra.add(t.action),
                                        t.timestamp))
            recs.append(span)
        total = npool + len(extra.items)
        if total >= _NONE_IDX:
            return None
    except struct.error:
        return None
    if topic is None:
        hdr = bytes((MAGIC, FT_OPS))
    else:
        tb = topic.encode()
        hdr = bytes((MAGIC, FT_FOPS)) + _U16.pack(len(tb)) + tb
    pool_out = [_U16.pack(total), pool_blob]
    for b in extra.items:
        pool_out.append(_U16.pack(len(b)))
        pool_out.append(b)
    return hdr + b"".join(pool_out) + b"".join(recs)


def scan_ops(body: bytes):
    """Lightweight walk of an ops/fops body for load observers.

    Yields one tuple per record WITHOUT constructing message objects or
    contents dicts — the load worker's broadcast observer only needs op
    identity and the visible-length delta, and at the measured knee the
    full decode (dataclass + 3 nested dicts per op, times every
    subscriber) was the workers' largest CPU item:

        (client_id | None, seq, cseq, deli_ts | None, delta)

    ``delta`` is the op's visible-length change: +chars for an insert
    (ASCII payloads: byte length == char length — the synthetic load
    generator emits ASCII-only text), -span for a remove, 0 otherwise
    (annotate/generic). ``deli_ts`` is the last deli/sequence trace hop
    timestamp when the record carries one.

    Columnar batches (FT_COLS_OPS/FOPS) carry no per-record traces: the
    stamp timestamp IS the deli ticket time, so every record yields it
    as ``deli_ts`` — the hop split stays honest without trace bytes.
    """
    ftype = body[1]
    if ftype == FT_COLS_OPS or ftype == FT_COLS_FOPS:
        _, cid, base_seq, ts, sc, _msns, _hops = _read_cols_stamp(body)
        kind = sc.kind
        delta = np.where(
            kind == 0, np.diff(sc.text_off),
            np.where(kind == 1, sc.a - sc.b, 0)).tolist()
        for i, cseq in enumerate(sc.cseq.tolist()):
            yield cid, base_seq + i, cseq, ts, delta[i]
        return
    if ftype == FT_FOPS:
        (tl,) = _U16.unpack_from(body, 2)
        off = 4 + tl
    else:
        off = 2
    pool, off = _read_pool(body, off)
    deli_idx = None
    for i, s in enumerate(pool):
        if s == "deli":
            deli_idx = i
            break
    (n,) = _U16.unpack_from(body, off)
    off += 2
    for _ in range(n):
        cid_idx, seq, msn, cseq, rseq, ts = _SEQ_FIXED.unpack_from(body, off)
        off += _SEQ_FIXED.size
        ntr = body[off]
        off += 1
        deli_ts = None
        for _t in range(ntr):
            svc, act, hop_ts = _TRACE.unpack_from(body, off)
            off += _TRACE.size
            if svc == deli_idx:
                deli_ts = hop_ts
        kind = body[off]
        off += 1
        delta = 0
        if kind == 0:
            off += _INS_HDR.size
            (ln,) = _U16.unpack_from(body, off)
            off += 2 + ln
            delta = ln
        elif kind == 1:
            _, _, start, end = _SPAN.unpack_from(body, off)
            off += _SPAN.size
            delta = start - end
        elif kind == 2:
            off += _SPAN.size
            (ln,) = _U16.unpack_from(body, off)
            off += 2 + ln
        elif kind == 0xFF:
            (ln,) = _U32.unpack_from(body, off)
            off += 4 + ln
        else:
            raise ValueError(f"unknown binwire payload kind {kind}")
        yield (None if cid_idx == _NONE_IDX else pool[cid_idx],
               seq, cseq, deli_ts, delta)


# ------------------------------------------------------------- columnar
# Fixed-stride column frames: the zero-materialization ingress path.
#
# The rec-oriented frames above are variable-length per record, so the
# server must walk them op by op. The columnar family carries the SAME
# boxcar as packed SoA columns that ``np.frombuffer`` views in O(1),
# feeding deli's array lane without ever materializing per-op objects.
# A submit boxcar is columnar-eligible when every op is a canonical
# same-channel chanop (insert/remove/annotate, no metadata/traces) —
# exactly the shape the merge-tree runtime emits; anything else rides
# the rec frames unchanged.
#
# Layout (the column section is LITTLE-endian — a deliberate deviation
# from the big-endian rec frames so the columns are numpy-native views
# on LE hosts; outer headers stay big-endian so the gateway's 6-byte
# fsubmit prepend and u16-topic fops strip work byte-identically across
# both families):
#
#     body := MAGIC ftype hdr(ftype) section
#     ftype := 5 cols_submit | 6 cols_fsubmit | 7 cols_ops | 8 cols_fops
#     hdr   := ""                    (cols_submit, cols_ops)
#            | u32 sid               (cols_fsubmit, big-endian)
#            | u16 len + utf8 topic  (cols_fops, big-endian)
#     section(submit) := cols
#     section(ops)    := stamp cols n×i64 msns
#     stamp := u16 cid_len + utf8 client_id, i64 base_seq, f64 timestamp
#     cols  := u16 n, u16 ds_len + utf8, u16 ch_len + utf8,
#              n×u8 kind, n×i32 a, n×i32 b, n×i32 cseq, n×i32 rseq,
#              (n+1)×i32 text_off, u32 tlen + utf8 text,
#              u32 plen + utf8 props-JSON (plen 0 = no annotate props)
#
# ``a``/``b`` are pos/0 for inserts, start/end for removes/annotates;
# ``text_off`` are cumulative CHARACTER offsets into ``text`` (insert i
# owns text[text_off[i]:text_off[i+1]]). Record i's sequence number in a
# stamped frame is base_seq + i; the stamp timestamp is deli's ticket
# time for the whole batch (replaces per-record trace hops).
#
# Every cols-family body additionally ends in a hop trailer:
#
#     hoptail := k × (u8 hop_id, f64 ts)  u8 k      (big-endian)
#
# The count byte comes LAST so a relay tier appends its hop WITHOUT
# parsing any frame content: read body[-1], splice 9 bytes before it,
# bump the count (append_hop). Unsampled frames carry k = 0 — a single
# NUL byte — so the disarmed hot-path cost is one byte per frame. Hop
# ids index utils.telemetry.HOPS (the taxonomy's single source of
# truth). The trailer sits OUTSIDE the ``cols`` section, so the deli
# stamp splice and the encode-once fan-out caches never touch it.
#
# The load-bearing property: deli stamping is a byte SPLICE — the ops
# frame embeds the submit frame's ``cols`` bytes VERBATIM between the
# stamp and the appended msns, so the broadcast fan-out re-encodes
# nothing (see stamp_cols_ops and front_end._push_abatch).


class SubmitColumns:
    """Decoded column view of a columnar submit boxcar.

    The array fields are zero-copy ``np.frombuffer`` views into the
    received frame; ``cols`` is the raw column section (the splice
    input for :func:`stamp_cols_ops`).
    """

    __slots__ = ("ds_id", "channel_id", "kind", "a", "b", "cseq", "rseq",
                 "text", "text_off", "props", "cols")

    def __init__(self, ds_id, channel_id, kind, a, b, cseq, rseq,
                 text, text_off, props, cols):
        self.ds_id = ds_id
        self.channel_id = channel_id
        self.kind = kind
        self.a = a
        self.b = b
        self.cseq = cseq
        self.rseq = rseq
        self.text = text
        self.text_off = text_off
        self.props = props
        self.cols = cols

    @property
    def n(self) -> int:
        return len(self.kind)


def _i32_ok(*vals) -> bool:
    for v in vals:
        if type(v) is not int or v < 0 or v > 0x7FFFFFFF:
            return False
    return True


def encode_cols(ds_id: str, channel_id: str, kind, a, b, cseq, rseq,
                text: str, text_off, props) -> bytes:
    """Pack column arrays into the shared ``cols`` section."""
    n = len(kind)
    if not 0 < n <= 0xFFFF:
        raise ValueError(f"columnar boxcar size {n} out of range")
    dsb = ds_id.encode()
    chb = channel_id.encode()
    if len(dsb) > 0xFFFF or len(chb) > 0xFFFF:
        raise ValueError("address too long for columnar frame")
    tb = text.encode()
    pb = (b"" if props is None
          else json.dumps(props, separators=(",", ":")).encode())
    return b"".join((
        n.to_bytes(2, "little"),
        len(dsb).to_bytes(2, "little"), dsb,
        len(chb).to_bytes(2, "little"), chb,
        np.ascontiguousarray(kind, np.int8).tobytes(),
        np.ascontiguousarray(a, "<i4").tobytes(),
        np.ascontiguousarray(b, "<i4").tobytes(),
        np.ascontiguousarray(cseq, "<i4").tobytes(),
        np.ascontiguousarray(rseq, "<i4").tobytes(),
        np.ascontiguousarray(text_off, "<i4").tobytes(),
        len(tb).to_bytes(4, "little"), tb,
        len(pb).to_bytes(4, "little"), pb,
    ))


def _read_cols(body: bytes, off: int) -> tuple[SubmitColumns, int]:
    start = off
    n = int.from_bytes(body[off:off + 2], "little")
    off += 2
    if n == 0:
        raise ValueError("empty columnar boxcar")
    ln = int.from_bytes(body[off:off + 2], "little")
    off += 2
    ds = body[off:off + ln].decode()
    off += ln
    ln = int.from_bytes(body[off:off + 2], "little")
    off += 2
    ch = body[off:off + ln].decode()
    off += ln
    kind = np.frombuffer(body, np.int8, n, off)
    off += n
    a = np.frombuffer(body, "<i4", n, off)
    off += 4 * n
    b = np.frombuffer(body, "<i4", n, off)
    off += 4 * n
    cseq = np.frombuffer(body, "<i4", n, off)
    off += 4 * n
    rseq = np.frombuffer(body, "<i4", n, off)
    off += 4 * n
    text_off = np.frombuffer(body, "<i4", n + 1, off)
    off += 4 * (n + 1)
    tlen = int.from_bytes(body[off:off + 4], "little")
    off += 4
    text = body[off:off + tlen].decode()
    off += tlen
    plen = int.from_bytes(body[off:off + 4], "little")
    off += 4
    props = json.loads(body[off:off + plen]) if plen else None
    off += plen
    if off > len(body):
        raise ValueError("truncated columnar frame")
    return SubmitColumns(ds, ch, kind, a, b, cseq, rseq, text, text_off,
                         props, body[start:off]), off


def _hoptail(hops) -> bytes:
    """Pack an ordered [(hop_id, ts), ...] list as the trailing hoptail."""
    if not hops:
        return b"\x00"
    hops = hops[-0xFF:]
    return b"".join(_HOP.pack(int(h), float(t)) for h, t in hops) \
        + bytes((len(hops),))


def append_hop(body: bytes, hop_id: int, ts: float) -> bytes:
    """Splice one hop into a cols-family body's trailing hoptail.

    The relay-tier stamp: no frame content is parsed — the count byte
    at body[-1] moves back 9 bytes and increments. Full tails (255
    hops) drop the stamp rather than corrupt the frame.
    """
    k = body[-1]
    if k >= 0xFF:
        return body
    return b"".join((body[:-1], _HOP.pack(hop_id, ts), bytes((k + 1,))))


def read_hoptail(body: bytes, end: Optional[int] = None):
    """Parse the trailing hoptail → [(hop_id, ts), ...] in stamp order.

    ``end`` — the content end offset, when the caller just parsed the
    body — validates the trailer exactly. Without it the count byte is
    trusted but bounds-checked; inconsistent tails (frames predating
    the trailer in durable replays, chaos truncation) yield [] rather
    than raising.
    """
    if not body:
        return []
    k = body[-1]
    tail = 1 + k * _HOP.size
    if end is not None and len(body) - end != tail:
        return []
    off = len(body) - tail
    if off < 2:
        return []
    return [_HOP.unpack_from(body, off + i * _HOP.size) for i in range(k)]


def hops_to_traces(hops) -> list[TraceHop]:
    """Materialize hoptail entries as TraceHop objects (rec-frame shape)."""
    return [TraceHop(service=HOP_SERVICE_ACTION[h][0],
                     action=HOP_SERVICE_ACTION[h][1], timestamp=t)
            for h, t in hops if 0 <= h < len(HOP_SERVICE_ACTION)]


def encode_submit_columns(ops: list[DocumentMessage], *,
                          sid: Optional[int] = None) -> Optional[bytes]:
    """Encode a submit boxcar as a columnar frame, or None if ineligible.

    Eligibility mirrors :func:`_encode_payload`'s fast-kind strictness
    (canonical chanop dicts, i32-range positions, no metadata) plus the
    columnar constraints: one (ds, channel) per boxcar and no trace
    hops (the stamp timestamp replaces them). Callers fall back to
    :func:`encode_submit` on None — the rec path round-trips anything.
    """
    n = len(ops)
    if not 0 < n <= 0xFFFF:
        return None
    ds_id = ch_id = None
    kinds: list[int] = []
    av: list[int] = []
    bv: list[int] = []
    cs: list[int] = []
    rs: list[int] = []
    toff: list[int] = [0]
    texts: list[str] = []
    prs: list = []
    for m in ops:
        if m.type is not _OP_TYPE or m.metadata is not None or m.traces:
            return None
        parts = _chanop_parts(m.contents)
        if parts is None:
            return None
        ds, ch, op = parts
        if ds_id is None:
            ds_id, ch_id = ds, ch
        elif ds != ds_id or ch != ch_id:
            return None
        t = op.get("type")
        pr = None
        if t == 0 and len(op) == 3:
            pos, text = op.get("pos"), op.get("text")
            if type(text) is not str or not _i32_ok(pos):
                return None
            kinds.append(0)
            av.append(pos)
            bv.append(0)
            texts.append(text)
            toff.append(toff[-1] + len(text))
        elif t == 1 and len(op) == 3:
            start, end = op.get("start"), op.get("end")
            if not _i32_ok(start, end):
                return None
            kinds.append(1)
            av.append(start)
            bv.append(end)
            toff.append(toff[-1])
        elif t == 2 and len(op) == 4 and type(op.get("props")) is dict:
            start, end = op.get("start"), op.get("end")
            if not _i32_ok(start, end):
                return None
            kinds.append(2)
            av.append(start)
            bv.append(end)
            toff.append(toff[-1])
            pr = op["props"]
        else:
            return None
        prs.append(pr)
        cs.append(m.client_sequence_number)
        rs.append(m.reference_sequence_number)
    props = prs if any(p is not None for p in prs) else None
    try:
        cols = encode_cols(ds_id, ch_id, kinds, av, bv, cs, rs,
                           "".join(texts), toff, props)
    except (ValueError, OverflowError, TypeError):
        return None
    hdr = (bytes((MAGIC, FT_COLS_SUBMIT)) if sid is None
           else _FSUB_HDR.pack(MAGIC, FT_COLS_FSUBMIT, sid))
    return hdr + cols + b"\x00"


def decode_submit_columns(body: bytes, *, with_hops: bool = False):
    """Decode a cols_submit/cols_fsubmit body → (sid or None, columns).

    ``with_hops=True`` appends the parsed hoptail as a third element.
    """
    ftype = body[1]
    if ftype == FT_COLS_FSUBMIT:
        (sid,) = _U32.unpack_from(body, 2)
        off = _FSUB_HDR.size
    elif ftype == FT_COLS_SUBMIT:
        sid, off = None, 2
    else:
        raise ValueError(f"not a columnar submit frame (ftype {ftype})")
    sc, end = _read_cols(body, off)
    if with_hops:
        return sid, sc, read_hoptail(body, end)
    return sid, sc


def _cols_contents(sc: SubmitColumns, kind, a, b, toff, i: int) -> dict:
    k = kind[i]
    if k == 0:
        op = {"type": 0, "pos": a[i],
              "text": sc.text[toff[i]:toff[i + 1]]}
    elif k == 1:
        op = {"type": 1, "start": a[i], "end": b[i]}
    elif k == 2:
        op = {"type": 2, "start": a[i], "end": b[i],
              "props": sc.props[i] if sc.props else {}}
    else:
        raise ValueError(f"unknown columnar op kind {k}")
    return {"kind": "chanop", "address": sc.ds_id,
            "contents": {"address": sc.channel_id, "contents": op}}


def cols_to_ops(sc: SubmitColumns) -> list[DocumentMessage]:
    """Materialize per-op DocumentMessages (scalar-fallback path)."""
    kind = sc.kind.tolist() if hasattr(sc.kind, "tolist") else sc.kind
    a = sc.a.tolist()
    b = sc.b.tolist()
    cs = sc.cseq.tolist()
    rs = sc.rseq.tolist()
    toff = sc.text_off.tolist()
    return [DocumentMessage(
        client_sequence_number=cs[i], reference_sequence_number=rs[i],
        type=_OP_TYPE, contents=_cols_contents(sc, kind, a, b, toff, i))
        for i in range(len(kind))]


def stamp_cols_ops(cols: bytes, client_id: str, base_seq: int, msns,
                   timestamp: float, *, topic: Optional[str] = None,
                   hops=None) -> bytes:
    """Build a cols_ops/cols_fops body by SPLICING the submit's columns.

    ``cols`` is the column section exactly as received (SubmitColumns.
    cols); only the stamp header, the msn tail, and the hoptail are
    packed fresh — this is deli's sequence/msn stamping as a vectorized
    byte splice. ``hops`` is the accumulated [(hop_id, ts), ...] list
    carried from the submit frame through the tiers (empty/None on
    unsampled batches: the tail is a single NUL byte).
    """
    cid = client_id.encode()
    if topic is None:
        hdr = bytes((MAGIC, FT_COLS_OPS))
    else:
        tb = topic.encode()
        hdr = bytes((MAGIC, FT_COLS_FOPS)) + _U16.pack(len(tb)) + tb
    return b"".join((
        hdr,
        len(cid).to_bytes(2, "little"), cid,
        int(base_seq).to_bytes(8, "little", signed=True),
        np.array([timestamp], "<f8").tobytes(),
        cols,
        np.ascontiguousarray(msns, "<i8").tobytes(),
        _hoptail(hops),
    ))


def _read_cols_stamp(body: bytes):
    """Parse a stamped columnar body → (topic, cid, base_seq, ts, sc,
    msns, hops)."""
    ftype = body[1]
    if ftype == FT_COLS_FOPS:
        (tl,) = _U16.unpack_from(body, 2)
        topic = body[4:4 + tl].decode()
        off = 4 + tl
    elif ftype == FT_COLS_OPS:
        topic, off = None, 2
    else:
        raise ValueError(f"not a columnar ops frame (ftype {ftype})")
    cl = int.from_bytes(body[off:off + 2], "little")
    off += 2
    cid = body[off:off + cl].decode()
    off += cl
    base_seq = int.from_bytes(body[off:off + 8], "little", signed=True)
    off += 8
    ts = float(np.frombuffer(body, "<f8", 1, off)[0])
    off += 8
    sc, off = _read_cols(body, off)
    msns = np.frombuffer(body, "<i8", sc.n, off)
    hops = read_hoptail(body, off + 8 * sc.n)
    return topic, cid, base_seq, ts, sc, msns, hops


def decode_cols_ops(body: bytes) -> tuple[Optional[str],
                                          list[SequencedDocumentMessage]]:
    """Materialize a stamped columnar batch as sequenced messages.

    The compatibility path for rec-frame consumers (driver read loop,
    legacy JSON fan-out): hot subscribers consume the frame bytes or
    the SequencedArrayBatch directly and never call this.
    """
    topic, cid, base_seq, ts, sc, msns, hops = _read_cols_stamp(body)
    kind = sc.kind.tolist()
    a = sc.a.tolist()
    b = sc.b.tolist()
    cs = sc.cseq.tolist()
    rs = sc.rseq.tolist()
    toff = sc.text_off.tolist()
    mlist = msns.tolist()
    msgs = [SequencedDocumentMessage(
        client_id=cid, sequence_number=base_seq + i,
        minimum_sequence_number=mlist[i],
        client_sequence_number=cs[i], reference_sequence_number=rs[i],
        type=_OP_TYPE, contents=_cols_contents(sc, kind, a, b, toff, i),
        timestamp=ts)
        for i in range(len(kind))]
    if hops:
        # frame-level hops ride the LAST record, mirroring the client
        # convention of stamping the final op of a sampled boxcar
        msgs[-1].traces = hops_to_traces(hops)
    return topic, msgs


# ------------------------------------------------ durable segment blocks
# The storage tier (service/segment_store.py) persists each sequenced
# boxcar as ONE column block whose payload is, byte for byte, the
# FT_COLS_OPS stamp section:
#
#     block := f64 boxcar_ts (LE)            -- submit-time client stamp
#              u16 cid_len + cid
#              i64 base_seq (LE)
#              f64 deli_ts (LE)
#              cols section (encode_cols)
#              n x i64 msns (LE)
#
# so backfill serving is a byte slice — prepend the 2-byte header, append
# the 1-byte unsampled hoptail, and a binary client receives the same
# stamped column bytes the broadcast fan-out shipped, with zero re-encode.
# The leading boxcar_ts is the only field outside the wire stamp (the
# boxcar's own submit timestamp survives log round-trips); slicing it off
# is the whole cost of serving.

SEG_COLS = 1   # columnar block: payload as above
SEG_JSON = 2   # legacy compat shim: payload is an opaque encoded record


def encode_seg_block(cols: bytes, client_id: str, base_seq: int, msns,
                     timestamp: float, boxcar_ts: float) -> bytes:
    """Pack one sequenced boxcar as a durable SEG_COLS block payload."""
    cid = client_id.encode()
    return b"".join((
        np.array([boxcar_ts], "<f8").tobytes(),
        len(cid).to_bytes(2, "little"), cid,
        int(base_seq).to_bytes(8, "little", signed=True),
        np.array([timestamp], "<f8").tobytes(),
        cols,
        np.ascontiguousarray(msns, "<i8").tobytes(),
    ))


def read_seg_block(payload: bytes):
    """Parse a SEG_COLS payload → (boxcar_ts, cid, base_seq, ts, sc,
    msns); the storage-side recovery decode (one np.frombuffer per
    column, no per-op unpacking)."""
    boxcar_ts = float(np.frombuffer(payload, "<f8", 1, 0)[0])
    off = 8
    cl = int.from_bytes(payload[off:off + 2], "little")
    off += 2
    cid = payload[off:off + cl].decode()
    off += cl
    base_seq = int.from_bytes(payload[off:off + 8], "little", signed=True)
    off += 8
    ts = float(np.frombuffer(payload, "<f8", 1, off)[0])
    off += 8
    sc, off = _read_cols(payload, off)
    msns = np.frombuffer(payload, "<i8", sc.n, off)
    return boxcar_ts, cid, base_seq, ts, sc, msns


def seg_block_wire_body(payload: bytes) -> bytes:
    """SEG_COLS payload → a complete FT_COLS_OPS body (unsampled
    hoptail): the zero-re-encode backfill serving slice."""
    return bytes((MAGIC, FT_COLS_OPS)) + payload[8:] + b"\x00"


def cols_deltas_body(rid: int, payload: bytes) -> bytes:
    """SEG_COLS payload → one FT_COLS_DELTAS backfill push body, tagged
    with the u32 request id so the client routes it to the right
    get_deltas_cols call. No hoptail: backfill is replay, not live."""
    return (bytes((MAGIC, FT_COLS_DELTAS)) + rid.to_bytes(4, "big")
            + payload[8:])


def read_cols_deltas(body: bytes):
    """FT_COLS_DELTAS body → (rid, sequenced messages)."""
    rid = int.from_bytes(body[2:6], "big")
    _, msgs = decode_cols_ops(bytes((MAGIC, FT_COLS_OPS)) + body[6:]
                              + b"\x00")
    return rid, msgs


def snap_chunk_body(rid: int, chunk_hash: str, chunk: bytes) -> bytes:
    """Snapcols chunk → one FT_COLS_SNAP push body, tagged with the u32
    request id (routing, like FT_COLS_DELTAS) and the content hash (the
    client's dedupe key). The chunk bytes ride verbatim — the serving
    cache frames each chunk exactly once per (doc, version)."""
    h = chunk_hash.encode("ascii")
    return (bytes((MAGIC, FT_COLS_SNAP)) + rid.to_bytes(4, "big")
            + _U16.pack(len(h)) + h + chunk)


def read_snap_chunk(body: bytes):
    """FT_COLS_SNAP body → (rid, chunk_hash, chunk bytes)."""
    rid = int.from_bytes(body[2:6], "big")
    (hl,) = _U16.unpack_from(body, 6)
    return rid, body[8:8 + hl].decode("ascii"), body[8 + hl:]


def encode_history_commit(rid: int, commit: dict) -> bytes:
    """History commit dict → one FT_HISTORY push body, tagged with the
    u32 request id (routing, like FT_COLS_SNAP). The commit rides as a
    framed refgraph record so the wire exercises the same crc'd codec
    the per-doc ref file persists."""
    from .refgraph import encode_commit, frame_record
    return (bytes((MAGIC, FT_HISTORY)) + rid.to_bytes(4, "big")
            + frame_record(encode_commit(commit)))


def decode_history_commit(body: bytes):
    """FT_HISTORY body → (rid, commit dict). Raises on a torn record —
    the wire is a reliable stream, unlike the ref file's tail."""
    from .refgraph import scan_records
    rid = int.from_bytes(body[2:6], "big")
    records, clean = scan_records(body[6:])
    if len(records) != 1 or clean != len(body) - 6:
        raise ValueError("malformed FT_HISTORY body")
    rec = records[0]
    if rec.get("t") != "commit":
        raise ValueError("FT_HISTORY body is not a commit record")
    rec.pop("t", None)
    return rid, rec


# --------------------------------------------------- gateway byte rewrites
# The relay operations gateway.py performs WITHOUT decoding op payloads.


def submit_to_fsubmit(body: bytes, sid: int) -> bytes:
    """Rewrite a client ``submit`` body into an upstream ``fsubmit``."""
    ft = FT_COLS_FSUBMIT if body[1] == FT_COLS_SUBMIT else FT_FSUBMIT
    return _FSUB_HDR.pack(MAGIC, ft, sid) + body[2:]


def fsubmit_sid(body: bytes) -> int:
    """The muxed session id an ``fsubmit`` body is addressed to."""
    return _U32.unpack_from(body, 2)[0]


def fsubmit_rewrite_sid(body: bytes, sid: int) -> bytes:
    """Relay-tree sid splice: re-address an ``fsubmit`` body to the
    parent tier's sid without touching the op payload bytes."""
    return body[:2] + _U32.pack(sid) + body[6:]


def fops_strip_topic(body: bytes) -> tuple[str, bytes]:
    """Split an ``fops`` body → (topic, client-facing ``ops`` body)."""
    ft = FT_COLS_OPS if body[1] == FT_COLS_FOPS else FT_OPS
    (tl,) = _U16.unpack_from(body, 2)
    topic = body[4:4 + tl].decode()
    return topic, bytes((MAGIC, ft)) + body[4 + tl:]


def fpresence_strip_topic(body: bytes) -> tuple[str, bytes]:
    """Split an ``fpresence`` body → (topic, client ``presence`` body)."""
    (tl,) = _U16.unpack_from(body, 2)
    topic = body[4:4 + tl].decode()
    return topic, bytes((MAGIC, FT_PRESENCE)) + body[4 + tl:]


# ----------------------------------------------------- presence frames
# The ephemeral lane: coalesced signal batches, never sequenced, never
# logged. Batch section is IDENTICAL between FT_PRESENCE (client form)
# and FT_FPRESENCE (backbone form, u16-len topic prefix) so a gateway
# relays presence down the tree with the same topic-slice byte splice
# as fops — zero re-encode at every level.
#
#     batch := u16 n; n × entry
#     entry := u16 cid_len (0xFFFF = None) + utf8 cid,
#              u16 type_len + utf8 type,
#              u32 content_len + utf8 content-JSON


def encode_presence(signals, topic: Optional[str] = None) -> bytes:
    """Signal batch → FT_PRESENCE body, or FT_FPRESENCE when ``topic``
    is given (the backbone form a gateway strips without decoding)."""
    out = []
    if topic is None:
        out.append(bytes((MAGIC, FT_PRESENCE)))
    else:
        t = topic.encode()
        out.append(bytes((MAGIC, FT_FPRESENCE)) + _U16.pack(len(t)) + t)
    out.append(_U16.pack(len(signals)))
    for sig in signals:
        cid = sig.client_id
        if cid is None:
            out.append(_U16.pack(_NONE_IDX))
        else:
            c = cid.encode()
            out.append(_U16.pack(len(c)))
            out.append(c)
        t = sig.type.encode()
        out.append(_U16.pack(len(t)))
        out.append(t)
        body = json.dumps(sig.content, separators=(",", ":")).encode()
        out.append(_U32.pack(len(body)))
        out.append(body)
    return b"".join(out)


def decode_presence(body: bytes):
    """FT_PRESENCE / FT_FPRESENCE body → list of Signal."""
    off = 2
    if body[1] == FT_FPRESENCE:
        (tl,) = _U16.unpack_from(body, off)
        off += 2 + tl
    (n,) = _U16.unpack_from(body, off)
    off += 2
    sigs = []
    for _ in range(n):
        (cl,) = _U16.unpack_from(body, off)
        off += 2
        if cl == _NONE_IDX:
            cid = None
        else:
            cid = body[off:off + cl].decode()
            off += cl
        (tl,) = _U16.unpack_from(body, off)
        off += 2
        typ = body[off:off + tl].decode()
        off += tl
        (bl,) = _U32.unpack_from(body, off)
        off += 4
        content = json.loads(body[off:off + bl].decode())
        off += bl
        sigs.append(Signal(client_id=cid, type=typ, content=content))
    return sigs


def is_binary(body: bytes) -> bool:
    return bool(body) and body[0] == MAGIC


def frame(body: bytes) -> bytes:
    """Prepend the shared 4-byte length header."""
    return len(body).to_bytes(4, "big") + body
