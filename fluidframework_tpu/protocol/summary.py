"""Summary (checkpoint) tree contracts.

A summary is a recursive tree: container → data stores → channels → DDS
snapshot blobs. Incremental summaries replace unchanged subtrees with a
:class:`SummaryHandle` pointing at the previously-acked summary, so only
changed state is re-uploaded.

Ref: protocol-definitions/src/summary.ts (ISummaryTree/ISummaryBlob/
ISummaryHandle/ISummaryAttachment).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import IntEnum
from typing import Union


class SummaryType(IntEnum):
    TREE = 1
    BLOB = 2
    HANDLE = 3
    ATTACHMENT = 4


@dataclass
class SummaryBlob:
    """Leaf content; bytes or utf-8 text."""

    content: bytes

    type: SummaryType = SummaryType.BLOB


@dataclass
class SummaryHandle:
    """Reference to a subtree of the previous acked summary by path.

    ``handle`` is a '/'-separated path within the parent summary
    (ref: summary.ts ISummaryHandle — handle reuse is what makes summaries
    incremental).
    """

    handle: str
    handle_type: SummaryType = SummaryType.TREE

    type: SummaryType = SummaryType.HANDLE


@dataclass
class SummaryAttachment:
    """Reference to an already-uploaded blob by content id."""

    id: str

    type: SummaryType = SummaryType.ATTACHMENT


@dataclass
class SummaryTree:
    tree: dict[str, "SummaryObject"] = field(default_factory=dict)
    unreferenced: bool = False

    type: SummaryType = SummaryType.TREE


SummaryObject = Union[SummaryTree, SummaryBlob, SummaryHandle, SummaryAttachment]


def summary_to_wire(obj: SummaryObject) -> dict:
    """JSON-safe encoding (network storage RPC carries summary trees)."""
    if isinstance(obj, SummaryTree):
        return {"__summary__": "tree",
                "tree": {k: summary_to_wire(v) for k, v in obj.tree.items()}}
    if isinstance(obj, SummaryBlob):
        return {"__summary__": "blob", "hex": obj.content.hex()}
    if isinstance(obj, SummaryHandle):
        return {"__summary__": "handle", "handle": obj.handle}
    if isinstance(obj, SummaryAttachment):
        return {"__summary__": "attachment", "id": obj.id}
    raise TypeError(f"not a summary object: {obj!r}")


def summary_from_wire(d: dict) -> SummaryObject:
    kind = d["__summary__"]
    if kind == "tree":
        return SummaryTree(
            tree={k: summary_from_wire(v) for k, v in d["tree"].items()})
    if kind == "blob":
        return SummaryBlob(content=bytes.fromhex(d["hex"]))
    if kind == "handle":
        return SummaryHandle(handle=d["handle"])
    if kind == "attachment":
        return SummaryAttachment(id=d["id"])
    raise ValueError(f"unknown summary wire kind {kind!r}")


def is_summary_wire(d) -> bool:
    return isinstance(d, dict) and "__summary__" in d


@dataclass
class SummaryProposal:
    """Body of a MessageType.SUMMARIZE op (ref: protocol.ts:198-260)."""

    handle: str  # storage handle of the uploaded summary tree
    head: str  # parent summary handle this one builds on
    message: str = ""
    parents: list[str] = field(default_factory=list)


@dataclass
class SummaryAck:
    """Body of a MessageType.SUMMARY_ACK op."""

    handle: str  # storage handle of the committed summary
    summary_proposal_seq: int  # seq of the summarize op being acked


@dataclass
class SummaryNack:
    """Body of a MessageType.SUMMARY_NACK op."""

    summary_proposal_seq: int
    error_message: str = ""
