"""The shared consensus kernel: Quorum + ProtocolOpHandler.

This exact state machine runs replicated on every client AND inside the
service's scribe lambda — it is pure deterministic logic over the sequenced
message stream, so all replicas converge.

Ref: protocol-base/src/quorum.ts:67 (Quorum), protocol-base/src/protocol.ts:50
(ProtocolOpHandler); used from container.ts:1116 (client) and
scribe/lambda.ts:71 (server).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from .consensus import ClientDetails, ProposalState, QuorumProposal, SequencedClient
from .messages import MessageType, SequencedDocumentMessage


class ProtocolError(Exception):
    """Raised when the sequenced stream violates the protocol contract."""


class Quorum:
    """Replicated membership + key/value consensus over the total order.

    Consensus rule: a proposal at seq P commits when the minimum sequence
    number reaches/passes P with no client having sequenced a rejection of it
    (unanimous-silence; ref quorum.ts:67 docstring in SURVEY.md §2.7).
    """

    def __init__(
        self,
        members: Optional[dict[str, SequencedClient]] = None,
        proposals: Optional[dict[int, QuorumProposal]] = None,
        values: Optional[dict[str, Any]] = None,
    ):
        self.members: dict[str, SequencedClient] = dict(members or {})
        # keyed by the propose op's sequence number
        self.proposals: dict[int, QuorumProposal] = dict(proposals or {})
        # committed values
        self.values: dict[str, Any] = dict(values or {})
        # event listeners
        self._listeners: dict[str, list[Callable]] = {}

    # -- events ----------------------------------------------------------
    def on(self, event: str, fn: Callable) -> None:
        self._listeners.setdefault(event, []).append(fn)

    def _emit(self, event: str, *args: Any) -> None:
        for fn in self._listeners.get(event, []):
            fn(*args)

    # -- membership ------------------------------------------------------
    def add_member(self, client_id: str, client: SequencedClient) -> None:
        self.members[client_id] = client
        self._emit("addMember", client_id, client)

    def remove_member(self, client_id: str) -> None:
        if client_id in self.members:
            del self.members[client_id]
            self._emit("removeMember", client_id)

    def get_member(self, client_id: str) -> Optional[SequencedClient]:
        return self.members.get(client_id)

    # -- proposals -------------------------------------------------------
    def add_proposal(self, key: str, value: Any, seq: int, local: bool) -> None:
        self.proposals[seq] = QuorumProposal(
            key=key, value=value, sequence_number=seq, local=local
        )
        self._emit("addProposal", self.proposals[seq])

    def reject_proposal(self, client_id: str, proposal_seq: int) -> None:
        prop = self.proposals.get(proposal_seq)
        if prop is not None and prop.state is ProposalState.PENDING:
            prop.rejections.add(client_id)

    def get(self, key: str) -> Any:
        return self.values.get(key)

    def has(self, key: str) -> bool:
        return key in self.values

    def update_minimum_sequence_number(self, min_seq: int, current_seq: int) -> None:
        """Commit/reject every pending proposal the window has passed."""
        done = []
        for seq, prop in sorted(self.proposals.items()):
            if seq > min_seq:
                break
            if prop.rejections:
                prop.state = ProposalState.REJECTED
                self._emit("rejectProposal", prop)
            else:
                prop.state = ProposalState.ACCEPTED
                prop.approval_seq = current_seq
                self.values[prop.key] = prop.value
                self._emit("approveProposal", prop)
            done.append(seq)
        for seq in done:
            del self.proposals[seq]

    # -- snapshot --------------------------------------------------------
    def snapshot(self) -> dict:
        """Serializable protocol state (ref: quorum.ts:110 snapshot)."""
        return {
            "members": {
                cid: {
                    "sequenceNumber": sc.sequence_number,
                    "client": {
                        "userId": sc.client.user_id,
                        "mode": sc.client.mode,
                        "interactive": sc.client.interactive,
                        "details": sc.client.details,
                        "scopes": sc.client.scopes,
                    },
                }
                for cid, sc in self.members.items()
            },
            "proposals": {
                str(seq): {
                    "key": p.key,
                    "value": p.value,
                    "sequenceNumber": seq,
                    "local": p.local,
                    "rejections": sorted(p.rejections),
                }
                for seq, p in self.proposals.items()
            },
            "values": dict(self.values),
        }

    @classmethod
    def load(cls, snapshot: dict) -> "Quorum":
        members = {
            cid: SequencedClient(
                client=ClientDetails(
                    user_id=m["client"].get("userId", ""),
                    mode=m["client"].get("mode", "write"),
                    interactive=m["client"].get("interactive", True),
                    details=m["client"].get("details", {}),
                    scopes=m["client"].get("scopes", []),
                ),
                sequence_number=m["sequenceNumber"],
            )
            for cid, m in snapshot.get("members", {}).items()
        }
        proposals = {
            int(seq): QuorumProposal(
                key=p["key"],
                value=p["value"],
                sequence_number=int(seq),
                local=p.get("local", False),
                rejections=set(p.get("rejections", [])),
            )
            for seq, p in snapshot.get("proposals", {}).items()
        }
        return cls(members=members, proposals=proposals, values=dict(snapshot.get("values", {})))


class ProtocolOpHandler:
    """Applies protocol-level messages to the quorum replica and tracks the
    collaboration window.

    Ref: protocol-base/src/protocol.ts:50,77 — identical logic on client
    (container boot) and server (scribe).
    """

    def __init__(
        self,
        minimum_sequence_number: int = 0,
        sequence_number: int = 0,
        quorum: Optional[Quorum] = None,
    ):
        self.minimum_sequence_number = minimum_sequence_number
        self.sequence_number = sequence_number
        self.quorum = quorum or Quorum()

    def process_message(self, message: SequencedDocumentMessage, local: bool = False) -> bool:
        """Apply one sequenced message. Returns False when the message was
        a duplicate below the head (idempotent redelivery), True when it
        was applied — callers with side effects beyond the replica (e.g.
        scribe's summarize handling) must branch on this."""
        if message.sequence_number <= self.sequence_number and message.sequence_number != 0:
            # duplicate delivery — the stream is idempotent below our head
            return False
        if message.sequence_number != self.sequence_number + 1:
            # a gap means the caller's reorder buffer failed; processing past
            # it would silently drop ops and diverge the replica (the
            # reference asserts contiguity in protocol.ts processMessage)
            raise ProtocolError(
                f"sequence gap: have {self.sequence_number}, got {message.sequence_number}"
            )
        self.sequence_number = message.sequence_number

        mtype = message.type
        if mtype == MessageType.CLIENT_JOIN:
            detail = message.contents or {}
            client = ClientDetails(
                user_id=detail.get("userId", ""),
                mode=detail.get("mode", "write"),
                interactive=detail.get("interactive", True),
                details=detail.get("details", {}),
                scopes=detail.get("scopes", []),
            )
            self.quorum.add_member(
                detail.get("clientId", message.client_id or ""),
                SequencedClient(client=client, sequence_number=message.sequence_number),
            )
        elif mtype == MessageType.CLIENT_LEAVE:
            leaving = message.contents if isinstance(message.contents, str) else (
                (message.contents or {}).get("clientId", message.client_id)
            )
            self.quorum.remove_member(leaving)
        elif mtype == MessageType.PROPOSE:
            body = message.contents or {}
            self.quorum.add_proposal(
                body.get("key"), body.get("value"), message.sequence_number, local
            )
        elif mtype == MessageType.REJECT:
            body = message.contents
            if isinstance(body, dict):
                body = body.get("sequenceNumber")
            if isinstance(body, (int, float)) and not isinstance(body, bool):
                self.quorum.reject_proposal(message.client_id or "", int(body))
            # malformed reject bodies are ignored rather than killing the
            # shared client/scribe op loop

        # advance the window and settle proposals it has passed
        if message.minimum_sequence_number > self.minimum_sequence_number:
            self.minimum_sequence_number = message.minimum_sequence_number
        self.quorum.update_minimum_sequence_number(
            self.minimum_sequence_number, self.sequence_number
        )
        return True

    def observe_operation_run(
        self, first_seq: int, last_seq: int, final_msn: int
    ) -> bool:
        """Apply a contiguous run of plain OPERATION messages in one step.

        The batched fast lane (service/deli.py boxcars) delivers runs that
        contain no membership/proposal messages, so the replica's only
        state change is the head/window advance. Settling proposals once
        with the run's final msn commits exactly the set the per-op path
        would (rejections can only arrive via REJECT messages, which never
        ride these runs). Handles replay overlap like process_message:
        a run entirely below the head is a duplicate (returns False); a
        partial overlap advances from the head.
        """
        if last_seq <= self.sequence_number:
            return False
        if first_seq > self.sequence_number + 1:
            raise ProtocolError(
                f"sequence gap: have {self.sequence_number}, run starts at {first_seq}"
            )
        self.sequence_number = last_seq
        if final_msn > self.minimum_sequence_number:
            self.minimum_sequence_number = final_msn
        self.quorum.update_minimum_sequence_number(
            self.minimum_sequence_number, self.sequence_number
        )
        return True

    def snapshot(self) -> dict:
        return {
            "minimumSequenceNumber": self.minimum_sequence_number,
            "sequenceNumber": self.sequence_number,
            "quorum": self.quorum.snapshot(),
        }

    @classmethod
    def load(cls, snapshot: dict) -> "ProtocolOpHandler":
        return cls(
            minimum_sequence_number=snapshot["minimumSequenceNumber"],
            sequence_number=snapshot["sequenceNumber"],
            quorum=Quorum.load(snapshot["quorum"]),
        )
