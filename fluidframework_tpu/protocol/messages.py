"""Operation message types — the unit of everything in the framework.

The server assigns each client-submitted :class:`DocumentMessage` a position in
a single total order per document, producing a
:class:`SequencedDocumentMessage`; all merge logic downstream is a
deterministic function of that sequenced stream.

Ref: protocol-definitions/src/protocol.ts:6-160 (MessageType,
IDocumentMessage, ISequencedDocumentMessage, INack, ITrace).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Optional


# Sequence number sentinels.
# A local, not-yet-acked op carries UNASSIGNED_SEQ; it compares as "newer than
# everything" in perspective checks (ref: merge-tree constants
# UnassignedSequenceNumber = -1, NonCollabClient etc. in
# packages/dds/merge-tree/src/constants.ts — we use explicit large/small
# sentinels that keep integer comparisons branch-free for the tensor path).
UNASSIGNED_SEQ = 2**31 - 1  # local pending op: newer than any assigned seq
UNIVERSAL_SEQ = 0  # content present from the beginning (snapshot load)


class MessageType(str, Enum):
    """Total-order message kinds (ref: protocol.ts:6-55)."""

    NOOP = "noop"
    CLIENT_JOIN = "join"
    CLIENT_LEAVE = "leave"
    PROPOSE = "propose"
    REJECT = "reject"
    ACCEPT = "accept"
    SUMMARIZE = "summarize"
    SUMMARY_ACK = "summaryAck"
    SUMMARY_NACK = "summaryNack"
    OPERATION = "op"
    NO_CLIENT = "noClient"
    CONTROL = "control"


class NackErrorType(str, Enum):
    """Why the server refused an op (ref: protocol-definitions INackContent)."""

    BAD_REQUEST = "BadRequestError"
    THROTTLING = "ThrottlingError"
    INVALID_SCOPE = "InvalidScopeError"
    LIMIT_EXCEEDED = "LimitExceededError"


@dataclass(slots=True)
class TraceHop:
    """One service hop stamped onto a message for wire-level latency tracing.

    Ref: protocol-definitions/src/protocol.ts:59-67 (ITrace); deli stamps
    start/end in lambdas/src/deli/lambda.ts.
    """

    service: str
    action: str
    timestamp: float = field(default_factory=lambda: time.time())


@dataclass(slots=True)
class DocumentMessage:
    """Client → server message (ref: protocol.ts:84-110 IDocumentMessage)."""

    client_sequence_number: int
    reference_sequence_number: int
    type: MessageType
    contents: Any = None
    metadata: Optional[dict] = None
    traces: list[TraceHop] = field(default_factory=list)


@dataclass(slots=True)
class SequencedDocumentMessage:
    """Server → client message: an op with its place in the total order.

    Ref: protocol.ts:132-160 (ISequencedDocumentMessage). Carries the assigned
    ``sequence_number``, the document-wide ``minimum_sequence_number`` (the
    collaboration-window floor: every connected client has seen at least this
    far), and echoes of the client-side numbers for dup/gap detection.
    """

    client_id: Optional[str]  # None for server-generated messages
    sequence_number: int
    minimum_sequence_number: int
    client_sequence_number: int
    reference_sequence_number: int
    type: MessageType
    contents: Any = None
    metadata: Optional[dict] = None
    origin: Optional[str] = None
    timestamp: float = 0.0
    traces: list[TraceHop] = field(default_factory=list)


@dataclass(slots=True)
class Nack:
    """Server rejection of a submitted op (ref: protocol.ts:70-82 INack)."""

    operation: Optional[DocumentMessage]
    sequence_number: int  # latest sequenced number at time of nack
    code: int
    type: NackErrorType
    message: str = ""
    retry_after_seconds: Optional[float] = None
    # admission-shed nacks: how long the client should back off before
    # resubmitting this op (jittered client-side; see driver/network.py)
    retry_after_ms: Optional[int] = None


@dataclass(slots=True)
class Signal:
    """Transient, un-sequenced message relayed to all clients.

    Ref: protocol-definitions ISignalMessage; alfred submitSignal relay
    (lambdas/src/alfred/index.ts:405).
    """

    client_id: Optional[str]
    type: str
    content: Any = None
