"""Array-lane (deli-tpu marshal) ≡ dict-lane equivalence.

The ArrayBoxcar path (service/array_batch.py) must be an OPTIMIZATION,
not a semantic fork: deli's array ticketing produces the same sequenced
stream the scalar lane produces for the equivalent dict boxcar, cold
consumers (REST backfill, late joiners, the summarizer's channel reads)
see materialized messages identical to the dict lane's, and the applier
bulk ingest converges to the same device text.
"""

from __future__ import annotations

import random

from fluidframework_tpu.service import LocalServer
from fluidframework_tpu.service.load_gen import run_inproc
from fluidframework_tpu.service.synthetic import SyntheticEditor
from fluidframework_tpu.service.tpu_applier import TpuDocumentApplier


def _drive(array_lane: bool, seed: int = 3):
    """Identical op schedule through both lanes; returns per-doc texts
    from the applier plus the pipeline stats."""
    applier = TpuDocumentApplier(max_docs=8, max_slots=128,
                                 ops_per_dispatch=8)
    stats = run_inproc(n_docs=4, clients_per_doc=2, ops_per_client=24,
                       applier=applier, flush_every=64, seed=seed,
                       batch_size=8, array_lane=array_lane)
    applier.finalize()
    texts = {d: applier.get_text("bench", f"doc{d}") for d in range(4)}
    return texts, stats, applier


def test_array_lane_converges_like_dict_lane():
    texts_a, stats_a, ap_a = _drive(True)
    texts_d, stats_d, ap_d = _drive(False)
    assert stats_a.ops_acked == stats_a.ops_submitted
    assert ap_a.host_escalations == 0
    # same rng schedule → byte-identical documents through either lane
    assert texts_a == texts_d
    assert stats_a.ops_submitted == stats_d.ops_submitted


def test_array_boxcar_equivalence_to_dict_boxcar():
    """ArrayBoxcar.to_raw_boxcar() materializes the exact DocumentMessage
    list next_ops would have produced from the same rng state."""
    rng_a, rng_b = random.Random(11), random.Random(11)
    ed_a, ed_b = SyntheticEditor(rng_a), SyntheticEditor(rng_b)
    # advance both identically first
    ed_a.length = ed_b.length = 500
    ed_a.ref_seq = ed_b.ref_seq = 7
    box = ed_a.next_boxcar(32, "t", "d", "c1")
    ops = ed_b.next_ops(32)
    raw = box.to_raw_boxcar()
    assert [m.contents for m in raw.ops] == [m.contents for m in ops]
    assert [m.client_sequence_number for m in raw.ops] \
        == [m.client_sequence_number for m in ops]
    assert [m.reference_sequence_number for m in raw.ops] \
        == [m.reference_sequence_number for m in ops]
    assert ed_a.length == ed_b.length
    assert ed_a.client_seq == ed_b.client_seq


def test_backfill_materializes_array_batches():
    """A late joiner backfilling over get_deltas sees per-op messages
    with correct seq/msn/contents even though the log stores shared
    batch objects positionally."""
    server = LocalServer()
    conn = server.connect("t", "doc")
    ed = SyntheticEditor(random.Random(5))
    for _ in range(4):
        conn.submit_array(ed.next_boxcar(8, "t", "doc", conn.client_id))
    msgs = server.get_deltas("t", "doc", 0, 10 ** 9)
    op_msgs = [m for m in msgs if m.type.value == "op"]
    assert len(op_msgs) == 32
    seqs = [m.sequence_number for m in op_msgs]
    assert seqs == sorted(seqs) and len(set(seqs)) == 32
    for m in op_msgs:
        env = m.contents
        assert env["kind"] == "chanop" and env["address"] == "default"
        assert m.minimum_sequence_number <= m.sequence_number
    # a real late-joining CLIENT converges off that backfill
    from fluidframework_tpu.driver.local import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Loader

    # first, create the channel the synthetic stream writes to, via a
    # real client, THEN stream synthetic array ops and join late
    server2 = LocalServer()
    loader = Loader(LocalDocumentServiceFactory(server2))
    c1 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "seed")
    conn2 = server2.connect("t", "doc")
    ed2 = SyntheticEditor(random.Random(6))
    ed2.ref_seq = server2._get_orderer("t", "doc").deli.sequence_number
    ed2.length = 4
    for _ in range(3):
        conn2.submit_array(ed2.next_boxcar(8, "t", "doc", conn2.client_id))
    c2 = loader.resolve("t", "doc")
    assert c2.runtime.get_data_store("default").get_channel(
        "text").get_text() == s1.get_text()
    assert len(s1.get_text()) > 4  # the array ops really landed


def test_array_lane_through_scribe_and_summary():
    """Protocol replica (scribe) advances over array runs: the msn moves
    and a quorum-dependent flow (summary ack) still works after array
    traffic."""
    server = LocalServer()
    conn = server.connect("t", "doc")
    ed = SyntheticEditor(random.Random(9))
    for _ in range(5):
        conn.submit_array(ed.next_boxcar(16, "t", "doc", conn.client_id))
        ed.ref_seq = server._get_orderer("t", "doc").deli.sequence_number
    orderer = server._get_orderer("t", "doc")
    assert orderer.scribe.protocol.sequence_number \
        == orderer.deli.sequence_number


def test_fallback_to_scalar_lane_on_gap():
    """An ArrayBoxcar violating the fast-lane preconditions (clientSeq
    gap) falls back to the scalar lane and nacks exactly like the dict
    path."""
    server = LocalServer()
    conn = server.connect("t", "doc")
    nacks = []
    conn.on_nack = nacks.append
    ed = SyntheticEditor(random.Random(1))
    box = ed.next_boxcar(4, "t", "doc", conn.client_id)
    box.cseq = box.cseq + 5  # gap: expected 1, got 6
    conn.submit_array(box)
    assert nacks and nacks[0].code == 400
