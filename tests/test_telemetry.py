"""Telemetry: logger namespacing/sinks, perf events, counters, and the
wire-trace consumer producing per-hop latency (SURVEY §5.1/§5.5).

Ref: telemetry-utils/src/logger.ts (ChildLogger :239, PerformanceEvent
:434), services/src/metricClient.ts, protocol ITrace hops.
"""

import time

from fluidframework_tpu.protocol.messages import (
    MessageType,
    SequencedDocumentMessage,
    TraceHop,
)
from fluidframework_tpu.utils import (
    BufferSink,
    Counters,
    PerformanceEvent,
    TelemetryLogger,
    TraceAggregator,
)


def test_child_logger_namespacing_and_shared_sinks():
    sink = BufferSink()
    root = TelemetryLogger("service", sinks=[sink])
    deli = root.child("deli")
    deli.info("nack", code=400)
    root.error("boom")
    assert sink.records[0]["namespace"] == "service:deli"
    assert sink.records[0]["code"] == 400
    assert sink.records[1]["category"] == "error"
    # a sink added at the root AFTER child creation reaches the child
    late = BufferSink()
    root.add_sink(late)
    deli.info("again")
    assert late.of("again")


def test_sinkless_logger_is_free_and_silent():
    log = TelemetryLogger("x")
    log.info("anything", heavy=object())  # must not raise or format


def test_performance_event_duration_and_cancel():
    sink = BufferSink()
    log = TelemetryLogger("perf", sinks=[sink])
    with log.perf("step"):
        time.sleep(0.01)
    (end,) = sink.of("step_end")
    assert end["duration_ms"] >= 8
    try:
        with log.perf("bad"):
            raise ValueError("nope")
    except ValueError:
        pass
    (cancel,) = sink.of("bad_cancel")
    assert "nope" in cancel["error"]


def test_counters_snapshot_percentiles():
    c = Counters()
    c.inc("ops", 3)
    for v in range(100):
        c.observe("lat", float(v))
    snap = c.snapshot()
    assert snap["ops"] == 3
    assert snap["lat"]["count"] == 100
    assert 45 <= snap["lat"]["p50"] <= 55


def test_counters_name_collision_surfaces_both():
    """A counter and a value series sharing a name must both survive the
    snapshot — the series reports under the key with the counter beside
    it instead of one silently clobbering the other."""
    c = Counters()
    c.inc("chaos.injected", 2)
    c.observe("chaos.injected", 1.5)
    c.observe("chaos.injected", 2.5)
    snap = c.snapshot()
    assert snap["chaos.injected"]["count"] == 2
    assert snap["chaos.injected"]["counter"] == 2
    assert snap["chaos.injected"]["p50"] in (1.5, 2.5)


def test_counters_reservoir_is_bounded_and_deterministic():
    c = Counters(max_samples=64)
    for v in range(10_000):
        c.observe("lat", float(v))
    assert len(c._values["lat"]) == 64  # bounded, not 10k
    snap = c.snapshot()
    assert snap["lat"]["count"] == 10_000  # true total, not reservoir size
    # uniform reservoir: p50 lands near the middle of the range
    assert 2_000 <= snap["lat"]["p50"] <= 8_000
    # seeded: a second identical run snapshots identically
    c2 = Counters(max_samples=64)
    for v in range(10_000):
        c2.observe("lat", float(v))
    assert c2.snapshot() == snap


def _msg(traces):
    return SequencedDocumentMessage(
        client_id="c", sequence_number=1, minimum_sequence_number=0,
        client_sequence_number=1, reference_sequence_number=0,
        type=MessageType.OPERATION, traces=traces)


def test_trace_aggregator_per_hop_split():
    agg = TraceAggregator()
    t0 = 1000.0
    agg.record(_msg([
        TraceHop("client", "submit", t0),
        TraceHop("deli", "sequence", t0 + 0.004),
    ]), ack_time=t0 + 0.010)
    rep = agg.report()
    assert abs(rep["submit_to_deli"]["p50_ms"] - 4.0) < 0.01
    assert abs(rep["deli_to_ack"]["p50_ms"] - 6.0) < 0.01


def test_trace_aggregator_missing_hops():
    """Partial stamping must not poison the split: no deli hop → nothing
    recorded; a deli hop without the client submit hop still yields the
    deli→ack leg (the server stamped it, the client didn't)."""
    agg = TraceAggregator()
    agg.record(_msg([TraceHop("client", "submit", 1000.0)]),
               ack_time=1000.5)
    assert agg.report() == {}
    agg.record(_msg([TraceHop("deli", "sequence", 1000.0)]),
               ack_time=1000.002)
    rep = agg.report()
    assert "submit_to_deli" not in rep
    assert rep["deli_to_ack"]["count"] == 1
    agg.record(_msg([]), ack_time=1001.0)  # no traces at all: a no-op
    assert agg.report()["deli_to_ack"]["count"] == 1


def test_trace_aggregator_merge_raw_and_percentiles():
    """merge_raw folds a worker's raw hop lists into the parent (the
    cross-process aggregation path) and report() percentiles span the
    merged population."""
    a, b = TraceAggregator(), TraceAggregator()
    t0 = 2000.0
    for i in range(10):
        a.record(_msg([TraceHop("client", "submit", t0),
                       TraceHop("deli", "sequence", t0 + 0.001 * (i + 1))]),
                 ack_time=t0 + 0.05)
    b.merge_raw(a.raw)
    b.merge_raw({"submit_to_deli": [100.0], "custom_hop": [7.0]})
    rep = b.report()
    assert rep["submit_to_deli"]["count"] == 11
    assert rep["custom_hop"] == {"count": 1, "p50_ms": 7.0, "p99_ms": 7.0}
    # p50 from the 1..10ms ramp; p99 pulled up by the merged outlier
    assert 4.0 <= rep["submit_to_deli"]["p50_ms"] <= 7.0
    assert rep["submit_to_deli"]["p99_ms"] == 100.0


def test_deli_stamps_ride_to_clients_and_aggregate():
    """End-to-end: submit through the real pipeline; the broadcast op
    carries client+deli+fanout hops and the aggregator splits the
    latency into every stamped leg."""
    from fluidframework_tpu.protocol.messages import DocumentMessage
    from fluidframework_tpu.service import LocalServer

    server = LocalServer()
    agg = TraceAggregator()
    conn = server.connect("t", "doc")
    acked = []
    conn.on_ops = lambda batch: [
        (agg.record(m), acked.append(m))
        for m in batch if m.client_id == conn.client_id
    ]
    conn.submit([DocumentMessage(
        client_sequence_number=1, reference_sequence_number=0,
        type=MessageType.OPERATION, contents={"x": 1},
        traces=[TraceHop("client", "submit", time.time())])])
    assert acked
    rep = agg.report()
    assert rep["submit_to_deli"]["count"] == 1
    assert rep["deli_to_fanout"]["count"] == 1
    assert rep["fanout_to_ack"]["count"] == 1


def test_deli_nacks_and_evictions_are_logged():
    from fluidframework_tpu.protocol.messages import DocumentMessage
    from fluidframework_tpu.service import LocalServer

    sink = BufferSink()
    now = [0.0]
    server = LocalServer(clock=lambda: now[0], client_timeout=10.0,
                         logger=TelemetryLogger("svc", sinks=[sink]))
    conn = server.connect("t", "doc")
    # clientSeq gap → nack, logged with the reason
    conn.submit([DocumentMessage(
        client_sequence_number=5, reference_sequence_number=0,
        type=MessageType.OPERATION, contents={})])
    (nack,) = sink.of("nack")
    assert "gap" in nack["reason"] and nack["namespace"].endswith("deli")
    # idle expiry logged
    now[0] = 100.0
    server.expire_idle_clients()
    assert sink.of("idle_client_evicted")
