"""Service summaries from device state (writeServiceSummary via the TPU
applier — the productized scribe-replay pass, BASELINE config 5).
"""

import pytest

from fluidframework_tpu.driver import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.service import LocalServer
from fluidframework_tpu.service.service_summarizer import ServiceSummarizer
from fluidframework_tpu.service.tpu_applier import (
    TpuDocumentApplier,
    channel_stream,
)


@pytest.fixture
def server():
    return LocalServer()


@pytest.fixture
def loader(server):
    return Loader(LocalDocumentServiceFactory(server))


def feed(applier, server, tenant, doc):
    for m in channel_stream(server, tenant, doc, "default", "text"):
        applier.ingest(tenant, doc, m, m.contents)


def test_boot_from_service_summary_without_client_summarizer(server, loader):
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "server-side summaries ")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    s2.insert_text(0, ">> ")
    s1.annotate_range(0, 2, {"bold": True})
    assert s1.get_text() == s2.get_text()

    applier = TpuDocumentApplier(max_docs=4, max_slots=64,
                                 ops_per_dispatch=8)
    applier.set_replay_source(lambda t, d: [])
    feed(applier, server, "t", "doc")
    svc = ServiceSummarizer(server, applier)
    version = svc.summarize_doc("t", "doc")
    assert version is not None and svc.summaries_written == 1

    # NO client ever summarized — yet a fresh client boots from the
    # service summary + tail and stays live
    c3 = loader.resolve("t", "doc")
    assert c3._base_snapshot is not None
    s3 = c3.runtime.get_data_store("default").get_channel("text")
    assert s3.get_text() == s1.get_text()
    assert s3.client.get_properties_at(0).get("bold") is True
    s3.insert_text(0, "live! ")
    assert s1.get_text() == s3.get_text() == s2.get_text()


def test_batch_service_summaries(server, loader):
    docs = [f"d{i}" for i in range(6)]
    strings = {}
    applier = TpuDocumentApplier(max_docs=8, max_slots=64,
                                 ops_per_dispatch=8)
    applier.set_replay_source(lambda t, d: [])
    for d in docs:
        c = loader.resolve("t", d)
        s = c.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s.insert_text(0, f"content of {d}")
        strings[d] = s
        feed(applier, server, "t", d)

    svc = ServiceSummarizer(server, applier)
    assert svc.summarize_all("t", docs) == len(docs)

    for d in docs:
        c = loader.resolve("t", d)
        assert c._base_snapshot is not None
        assert (c.runtime.get_data_store("default").get_channel("text")
                .get_text() == strings[d].get_text())
