"""Service summaries from device state (writeServiceSummary via the TPU
applier — the productized scribe-replay pass, BASELINE config 5).
"""

import pytest

from fluidframework_tpu.driver import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.service import LocalServer
from fluidframework_tpu.service.service_summarizer import ServiceSummarizer
from fluidframework_tpu.service.tpu_applier import (
    TpuDocumentApplier,
    channel_stream,
)


@pytest.fixture
def server():
    return LocalServer()


@pytest.fixture
def loader(server):
    return Loader(LocalDocumentServiceFactory(server))


def feed(applier, server, tenant, doc):
    for m in channel_stream(server, tenant, doc, "default", "text"):
        applier.ingest(tenant, doc, m, m.contents)


def test_boot_from_service_summary_without_client_summarizer(server, loader):
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "server-side summaries ")
    s2 = c2.runtime.get_data_store("default").get_channel("text")
    s2.insert_text(0, ">> ")
    s1.annotate_range(0, 2, {"bold": True})
    assert s1.get_text() == s2.get_text()

    applier = TpuDocumentApplier(max_docs=4, max_slots=64,
                                 ops_per_dispatch=8)
    applier.set_replay_source(lambda t, d: [])
    feed(applier, server, "t", "doc")
    svc = ServiceSummarizer(server, applier)
    version = svc.summarize_doc("t", "doc")
    assert version is not None and svc.summaries_written == 1

    # NO client ever summarized — yet a fresh client boots from the
    # service summary + tail and stays live
    c3 = loader.resolve("t", "doc")
    assert c3._base_snapshot is not None
    s3 = c3.runtime.get_data_store("default").get_channel("text")
    assert s3.get_text() == s1.get_text()
    assert s3.client.get_properties_at(0).get("bold") is True
    s3.insert_text(0, "live! ")
    assert s1.get_text() == s3.get_text() == s2.get_text()


def test_batch_service_summaries(server, loader):
    docs = [f"d{i}" for i in range(6)]
    strings = {}
    applier = TpuDocumentApplier(max_docs=8, max_slots=64,
                                 ops_per_dispatch=8)
    applier.set_replay_source(lambda t, d: [])
    for d in docs:
        c = loader.resolve("t", d)
        s = c.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s.insert_text(0, f"content of {d}")
        strings[d] = s
        feed(applier, server, "t", d)

    svc = ServiceSummarizer(server, applier)
    assert svc.summarize_all("t", docs) == len(docs)

    for d in docs:
        c = loader.resolve("t", d)
        assert c._base_snapshot is not None
        assert (c.runtime.get_data_store("default").get_channel("text")
                .get_text() == strings[d].get_text())


def test_service_summary_survives_full_process_death(tmp_path):
    """ADVICE r3: a service-written summary must commit through the
    scribe's ref-update path so it reaches the durable versions topic —
    after full process death a fresh client still boots from it."""
    from fluidframework_tpu.service.durable_log import DurableLog

    path = str(tmp_path / "svc-log")
    blobs = str(tmp_path / "blobs")  # blob durability = native chunkstore
    server = LocalServer(log=DurableLog(path), storage_dir=blobs)
    loader = Loader(LocalDocumentServiceFactory(server))
    c1 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "durable service summary")

    applier = TpuDocumentApplier(max_docs=4, max_slots=64,
                                 ops_per_dispatch=8)
    applier.set_replay_source(lambda t, d: [])
    feed(applier, server, "t", "doc")
    version = ServiceSummarizer(server, applier).summarize_doc("t", "doc")
    server.checkpoint_all()
    server.log.sync()
    server.log.close()
    del server

    server2 = LocalServer(log=DurableLog(path), storage_dir=blobs)
    # the acked version was restored from the durable topic, not lost
    scribe2 = server2._get_orderer("t", "doc").scribe
    assert scribe2.last_summary_head == version
    c2 = Loader(LocalDocumentServiceFactory(server2)).resolve("t", "doc")
    assert c2._base_snapshot is not None
    assert (c2.runtime.get_data_store("default").get_channel("text")
            .get_text() == "durable service summary")


def test_summarize_refuses_lagging_applier(server, loader):
    """Code-review r4: a service summary written from device state that
    LAGS the stream would claim coverage it doesn't have and let
    retention truncate the missing ops — the summarizer must refuse."""
    c1 = loader.resolve("t", "lagdoc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "abc")

    applier = TpuDocumentApplier(max_docs=4, max_slots=64,
                                 ops_per_dispatch=8)
    applier.set_replay_source(lambda t, d: [])
    feed(applier, server, "t", "lagdoc")
    svc = ServiceSummarizer(server, applier)

    # more ops AFTER the feed: the applier now lags the stream
    s1.insert_text(3, "def")
    with pytest.raises(RuntimeError, match="lags"):
        svc.summarize_doc("t", "lagdoc")

    # catching up makes it summarizable again
    feed(applier, server, "t", "lagdoc")
    assert svc.summarize_doc("t", "lagdoc") is not None


def test_summarize_refuses_non_modeled_content(server, loader):
    """The module-docstring contract: a doc holding channels the device
    does not model must keep client summaries — a service summary would
    drop them while retention truncates their ops."""
    c1 = loader.resolve("t", "mixdoc")
    ds = c1.runtime.create_data_store("default")
    s = ds.create_channel("text", "shared-string")
    s.insert_text(0, "text part")
    kv = ds.create_channel("kv", "shared-map")
    kv.set("k", "v")

    applier = TpuDocumentApplier(max_docs=4, max_slots=64,
                                 ops_per_dispatch=8)
    applier.set_replay_source(lambda t, d: [])
    feed(applier, server, "t", "mixdoc")
    svc = ServiceSummarizer(server, applier)
    with pytest.raises(RuntimeError, match="not model"):
        svc.summarize_doc("t", "mixdoc")

    # a second data store is refused just the same
    c2 = loader.resolve("t", "dsdoc")
    c2.runtime.create_data_store("default").create_channel(
        "text", "shared-string").insert_text(0, "x")
    c2.runtime.create_data_store("other").create_channel(
        "text", "shared-string")
    applier2 = TpuDocumentApplier(max_docs=4, max_slots=64,
                                  ops_per_dispatch=8)
    applier2.set_replay_source(lambda t, d: [])
    feed(applier2, server, "t", "dsdoc")
    with pytest.raises(RuntimeError, match="data store"):
        ServiceSummarizer(server, applier2).summarize_doc("t", "dsdoc")


def test_summarize_refuses_unproven_prefix_coverage(tmp_path):
    """Code-review r4 round 2: an applier fed only the post-truncation
    TAIL passes a max-seq check but must still be refused — its state
    does not provably contain the truncated prefix."""
    from fluidframework_tpu.config import Config
    from fluidframework_tpu.runtime.summarizer import SummaryManager

    cfg = Config().with_overrides(log_retention_ops=0)
    server = LocalServer(config=cfg)
    loader = Loader(LocalDocumentServiceFactory(server))
    c1 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "precious prefix ")
    SummaryManager(c1, max_ops=10**9).summarize_now()  # truncates the log
    s1.insert_text(0, "tail ")
    orderer = server._get_orderer("t", "doc")
    base = orderer.scriptorium.retained_base("t", "doc")
    assert base > 0

    # a FRESH applier that ingests only the retained tail
    applier = TpuDocumentApplier(max_docs=4, max_slots=64,
                                 ops_per_dispatch=8)
    applier.set_replay_source(lambda t, d: [])
    for m in channel_stream(server, "t", "doc", "default", "text",
                            from_seq=base):
        applier.ingest("t", "doc", m, m.contents)
    svc = ServiceSummarizer(server, applier)
    with pytest.raises(RuntimeError, match="not\\b.*anchored|anchored"):
        svc.summarize_doc("t", "doc")
    # and a batch pass SKIPS it instead of aborting
    assert svc.summarize_all("t", ["doc"]) == 0
    assert len(svc.refusals) == 1


def test_summarize_refuses_gapped_genesis_feed(server, loader):
    """Untruncated log, but the applier missed the doc's first channel
    op: first-seq accounting must refuse."""
    c1 = loader.resolve("t", "gapdoc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "first")
    s1.insert_text(5, " second")

    applier = TpuDocumentApplier(max_docs=4, max_slots=64,
                                 ops_per_dispatch=8)
    applier.set_replay_source(lambda t, d: [])
    msgs = list(channel_stream(server, "t", "gapdoc", "default", "text"))
    for m in msgs[1:]:  # skip the doc's first channel op
        applier.ingest("t", "gapdoc", m, m.contents)
    with pytest.raises(RuntimeError, match="incomplete"):
        ServiceSummarizer(server, applier).summarize_doc("t", "gapdoc")


def test_anchored_applier_survives_own_truncation(tmp_path):
    """The happy path across retention: a genesis-fed applier writes a
    summary (gate pass anchors it), retention truncates, and a SECOND
    service summary still commits."""
    from fluidframework_tpu.config import Config

    cfg = Config().with_overrides(log_retention_ops=0)
    server = LocalServer(config=cfg)
    loader = Loader(LocalDocumentServiceFactory(server))
    c1 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "one ")

    applier = TpuDocumentApplier(max_docs=4, max_slots=64,
                                 ops_per_dispatch=8)
    applier.set_replay_source(lambda t, d: [])
    feed(applier, server, "t", "doc")
    svc = ServiceSummarizer(server, applier)
    v1 = svc.summarize_doc("t", "doc")  # anchors + truncates
    assert server._get_orderer("t", "doc") \
        .scriptorium.retained_base("t", "doc") > 0

    s1.insert_text(0, "two ")
    orderer = server._get_orderer("t", "doc")
    base = orderer.scriptorium.retained_base("t", "doc")
    for m in channel_stream(server, "t", "doc", "default", "text",
                            from_seq=base):
        applier.ingest("t", "doc", m, m.contents)
    v2 = svc.summarize_doc("t", "doc")
    assert v2 != v1
    c2 = loader.resolve("t", "doc")
    assert (c2.runtime.get_data_store("default").get_channel("text")
            .get_text() == "two one ")


def test_summarize_refuses_restart_window_gap(tmp_path):
    """Code-review r4 round 3: a checkpoint-restored anchor is only
    trustworthy if no channel op was sequenced while the process was
    down — ops in the restart window are in the log but not in the
    restored device state."""
    from fluidframework_tpu.service.tpu_applier import (
        load_applier_checkpoint,
        save_applier_checkpoint,
    )

    server = LocalServer()
    loader = Loader(LocalDocumentServiceFactory(server))
    c1 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "before ")

    applier = TpuDocumentApplier(max_docs=4, max_slots=64,
                                 ops_per_dispatch=8)
    applier.set_replay_source(lambda t, d: [])
    feed(applier, server, "t", "doc")
    svc = ServiceSummarizer(server, applier)
    svc.summarize_doc("t", "doc")  # anchors the slot
    ckpt = str(tmp_path / "ck")
    save_applier_checkpoint(applier, ckpt)

    # "process death": ops sequenced while the applier is down
    s1.insert_text(0, "downtime ")
    applier2 = load_applier_checkpoint(ckpt, ops_per_dispatch=8)
    applier2.set_replay_source(lambda t, d: [])
    # the feed resumes LATE — only ops after another edit
    s1.insert_text(0, "late ")
    late_seq = max(m.sequence_number for m in channel_stream(
        server, "t", "doc", "default", "text"))
    for m in channel_stream(server, "t", "doc", "default", "text"):
        if m.sequence_number >= late_seq:
            applier2.ingest("t", "doc", m, m.contents)
    svc2 = ServiceSummarizer(server, applier2)
    with pytest.raises(RuntimeError, match="restart window"):
        svc2.summarize_doc("t", "doc")

    # a restore whose feed resumes cleanly (no window ops) is accepted
    applier3 = load_applier_checkpoint(ckpt, ops_per_dispatch=8)
    applier3.set_replay_source(lambda t, d: [])
    ck_seq = applier3.applied_seq("t", "doc")
    for m in channel_stream(server, "t", "doc", "default", "text"):
        if m.sequence_number > ck_seq:
            applier3.ingest("t", "doc", m, m.contents)
    v = ServiceSummarizer(server, applier3).summarize_doc("t", "doc")
    assert v is not None
    c2 = loader.resolve("t", "doc")
    assert (c2.runtime.get_data_store("default").get_channel("text")
            .get_text() == "late downtime before ")


def test_restart_window_survives_checkpoint_cycle(tmp_path):
    """A save/load cycle must NOT discharge a pending (unverified)
    restart window: checkpoint B saved while A's window is open keeps
    A's low bound, so downtime ops still trip the summarizer gate."""
    from fluidframework_tpu.service.tpu_applier import (
        load_applier_checkpoint,
        save_applier_checkpoint,
    )

    server = LocalServer()
    loader = Loader(LocalDocumentServiceFactory(server))
    c1 = loader.resolve("t", "doc")
    s1 = c1.runtime.create_data_store("default").create_channel(
        "text", "shared-string")
    s1.insert_text(0, "before ")

    applier = TpuDocumentApplier(max_docs=4, max_slots=64,
                                 ops_per_dispatch=8)
    applier.set_replay_source(lambda t, d: [])
    feed(applier, server, "t", "doc")
    svc = ServiceSummarizer(server, applier)
    svc.summarize_doc("t", "doc")  # anchors the slot
    ck_a = str(tmp_path / "a")
    save_applier_checkpoint(applier, ck_a)

    # downtime ops → restore from A with an OPEN window, feed resumes late
    s1.insert_text(0, "downtime ")
    applier2 = load_applier_checkpoint(ck_a, ops_per_dispatch=8)
    applier2.set_replay_source(lambda t, d: [])
    s1.insert_text(0, "late ")
    late_seq = max(m.sequence_number for m in channel_stream(
        server, "t", "doc", "default", "text"))
    for m in channel_stream(server, "t", "doc", "default", "text"):
        if m.sequence_number >= late_seq:
            applier2.ingest("t", "doc", m, m.contents)
    # BEFORE any summarize (which would refuse), a routine save runs
    ck_b = str(tmp_path / "b")
    save_applier_checkpoint(applier2, ck_b)

    applier3 = load_applier_checkpoint(ck_b, ops_per_dispatch=8)
    applier3.set_replay_source(lambda t, d: [])
    # feed resumes cleanly from B's applied seq — but A's window is
    # still unverified and must still be enforced
    ck_seq = applier3.applied_seq("t", "doc")
    for m in channel_stream(server, "t", "doc", "default", "text"):
        if m.sequence_number > ck_seq:
            applier3.ingest("t", "doc", m, m.contents)
    with pytest.raises(RuntimeError, match="restart window"):
        ServiceSummarizer(server, applier3).summarize_doc("t", "doc")
