"""MULTICHIP artifact schema (ISSUE 9 satellite): the v2 reader must
fold the whole r01..rNN series — new rung-bearing artifacts verbatim,
old dryrun-era {n_devices, rc, ok, skipped, tail} files normalized — and
the ci smoke's structural counter-asserts must hold under pytest's
8-virtual-device config too."""

import json
import os

import pytest

from tools.bench_multichip import read_multichip, run_smoke

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_reader_normalizes_old_dryrun_schema(tmp_path):
    old = {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
           "tail": "..."}
    p = tmp_path / "MULTICHIP_r05.json"
    p.write_text(json.dumps(old))
    got = read_multichip(str(p))
    assert got["schema"] == 2
    assert got["n_devices"] == 8
    assert got["ok"] is True
    assert got["rc"] == 0
    assert got["rungs"] == []


def test_reader_treats_old_skipped_as_not_ok(tmp_path):
    p = tmp_path / "m.json"
    p.write_text(json.dumps({"n_devices": 1, "rc": 0, "ok": True,
                             "skipped": True}))
    assert read_multichip(str(p))["ok"] is False


def test_reader_passes_v2_through(tmp_path):
    v2 = {"schema": 2, "platform": "cpu", "n_devices": 8,
          "forced_host": True,
          "rungs": [{"docs_axis": 1, "n_docs": 64, "ops_per_sec": 1.0,
                     "scaling_efficiency": 1.0,
                     "staging_ms_per_wave": 0.1,
                     "staged_bytes_per_wave": 100}],
          "local_dense_ops_per_sec": 1.0, "mesh_vs_local_1shard": 1.0,
          "ok": True, "rc": 0}
    p = tmp_path / "m.json"
    p.write_text(json.dumps(v2))
    assert read_multichip(str(p)) == v2


@pytest.mark.parametrize("rev", ["r01", "r02", "r03", "r04", "r05", "r06"])
def test_reader_loads_committed_artifact_series(rev):
    path = os.path.join(REPO, f"MULTICHIP_{rev}.json")
    if not os.path.exists(path):
        pytest.skip(f"{rev} artifact not present")
    got = read_multichip(path)
    assert got["schema"] == 2
    assert got["ok"] is True
    # the r06+ generations must carry real throughput rungs
    if rev >= "r06":
        assert len(got["rungs"]) == 4
        for r in got["rungs"]:
            assert r["ops_per_sec"] > 0
            assert 0 < r["scaling_efficiency"] <= 1.25
        assert got["mesh_vs_local_1shard"] >= 0.9  # acceptance: ≤10% tax


def test_smoke_counter_asserts_hold():
    """The ci.sh gate body, under pytest's forced 8-device config:
    staged bytes per wave scale with ACTIVE shards and the packed step
    compiles once per wave shape."""
    run_smoke()
