"""MULTICHIP artifact schema (ISSUE 9/11 satellite): the v3 reader must
fold the whole r01..rNN series — new rung-bearing artifacts verbatim, v2
(r06) rungs gaining null overlap fields, old dryrun-era {n_devices, rc,
ok, skipped, tail} files normalized — and the ci smoke's structural
counter-asserts (bytes scale with active shards, one compile per shape,
positive overlap with pipelined waves) must hold under pytest's
8-virtual-device config too."""

import json
import os

import pytest

from tools.bench_multichip import _V3_RUNG_FIELDS, read_multichip, run_smoke

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_reader_normalizes_old_dryrun_schema(tmp_path):
    old = {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
           "tail": "..."}
    p = tmp_path / "MULTICHIP_r05.json"
    p.write_text(json.dumps(old))
    got = read_multichip(str(p))
    assert got["schema"] == 3
    assert got["n_devices"] == 8
    assert got["ok"] is True
    assert got["rc"] == 0
    assert got["rungs"] == []
    assert got["overlap"] is False
    assert got["local_dense_ab"] is None


def test_reader_treats_old_skipped_as_not_ok(tmp_path):
    p = tmp_path / "m.json"
    p.write_text(json.dumps({"n_devices": 1, "rc": 0, "ok": True,
                             "skipped": True}))
    assert read_multichip(str(p))["ok"] is False


def test_reader_folds_v2_rungs_to_v3(tmp_path):
    v2 = {"schema": 2, "platform": "cpu", "n_devices": 8,
          "forced_host": True,
          "rungs": [{"docs_axis": 1, "n_docs": 64, "ops_per_sec": 1.0,
                     "scaling_efficiency": 1.0,
                     "staging_ms_per_wave": 0.1,
                     "staged_bytes_per_wave": 100}],
          "local_dense_ops_per_sec": 1.0, "mesh_vs_local_1shard": 1.0,
          "ok": True, "rc": 0}
    p = tmp_path / "m.json"
    p.write_text(json.dumps(v2))
    got = read_multichip(str(p))
    assert got["schema"] == 3
    # pre-overlap runs carry the v3 split fields as explicit unknowns
    for r in got["rungs"]:
        for f in _V3_RUNG_FIELDS:
            assert r[f] is None
        assert r["ops_per_sec"] == 1.0  # v2 data survives untouched
    assert got["overlap"] is False
    assert got["efficiency_basis"] == "wall"
    assert got["host_limited"] is True  # inherited from forced_host
    assert got["local_dense_ab"] is None


def test_reader_passes_v3_through(tmp_path):
    v3 = read_multichip(os.path.join(REPO, "MULTICHIP_r07.json")) \
        if os.path.exists(os.path.join(REPO, "MULTICHIP_r07.json")) else None
    if v3 is None:
        pytest.skip("r07 artifact not present")
    # already v3: byte-identical passthrough
    assert v3 == json.load(open(os.path.join(REPO, "MULTICHIP_r07.json")))


@pytest.mark.parametrize(
    "rev", ["r01", "r02", "r03", "r04", "r05", "r06", "r07"])
def test_reader_loads_committed_artifact_series(rev):
    path = os.path.join(REPO, f"MULTICHIP_{rev}.json")
    if not os.path.exists(path):
        pytest.skip(f"{rev} artifact not present")
    got = read_multichip(path)
    assert got["schema"] == 3
    assert got["ok"] is True
    # the r06+ generations must carry real throughput rungs
    if rev >= "r06":
        assert len(got["rungs"]) == 4
        for r in got["rungs"]:
            assert r["ops_per_sec"] > 0
            assert 0 < r["scaling_efficiency"] <= 1.25
        assert got["mesh_vs_local_1shard"] >= 0.9  # acceptance: ≤10% tax
    # the overlap-era artifact must prove the pipeline did overlap
    if rev >= "r07":
        assert got["overlap"] is True
        assert any((r["overlap_ratio"] or 0) > 0.5 for r in got["rungs"])
        for r in got["rungs"]:
            assert r["kernel_lane"] in ("xla", "pallas")
        ab = got["local_dense_ab"]
        assert ab["improvement"] is not None and ab["improvement"] > 1.0
        # an honest artifact on a forced-host bench box says so
        if got["forced_host"]:
            assert got["host_limited"] is True
            assert got["host_limited_note"]


def test_smoke_counter_asserts_hold():
    """The ci.sh gate body, under pytest's forced 8-device config:
    staged bytes per wave scale with ACTIVE shards, the packed step
    compiles once per wave shape, and pipelined waves drive
    applier.stage.overlap_ratio positive."""
    run_smoke()
