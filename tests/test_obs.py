"""Observability plane: hoptail codec, labeled metrics registry,
flight recorder, and the hop-trace path end to end (rec vs columnar).

Ref: services/src/metricClient.ts (labeled series), protocol ITrace
hops; the wire trailer and registry are ours (ARCHITECTURE.md
"Observability").
"""

import json
import random
import socket
import struct
import time

import pytest

from fluidframework_tpu.driver.network import NetworkDocumentServiceFactory
from fluidframework_tpu.obs import (
    FlightRecorder,
    MetricsRegistry,
    get_registry,
    parse_prometheus,
)
from fluidframework_tpu.protocol import binwire
from fluidframework_tpu.protocol.messages import (
    DocumentMessage,
    MessageType,
    TraceHop,
)
from fluidframework_tpu.service.front_end import NetworkFrontEnd
from fluidframework_tpu.service.local_server import LocalServer
from fluidframework_tpu.utils.telemetry import (
    HOP_ADMIT,
    HOP_DELI,
    HOP_FANOUT,
    HOP_SUBMIT,
    TraceAggregator,
    hop_pairs,
)
from tests.test_columnar import _rand_cols_ops


def wait_for(pred, timeout: float = 20.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return bool(pred())


# --------------------------------------------------------------- hoptail


def test_hoptail_append_and_read_roundtrip():
    """append_hop splices entries without parsing frame content, and
    read_hoptail returns them in stamp order with f64 bits intact."""
    body = binwire.encode_submit_columns(_rand_cols_ops(random.Random(1), 3))
    assert body[-1] == 0                    # unsampled: single NUL count
    assert binwire.read_hoptail(body) == []
    t0, t1 = 1754400000.125, 1754400000.875  # exactly representable
    stamped = binwire.append_hop(body, HOP_SUBMIT, t0)
    stamped = binwire.append_hop(stamped, HOP_ADMIT, t1)
    assert binwire.read_hoptail(stamped) == [(HOP_SUBMIT, t0),
                                             (HOP_ADMIT, t1)]
    # the original content bytes precede the tail unmodified
    assert stamped[:len(body) - 1] == body[:-1]
    # strict mode: the declared content end must account for the tail
    end = len(body) - 1
    assert binwire.read_hoptail(stamped, end=end) == [(HOP_SUBMIT, t0),
                                                      (HOP_ADMIT, t1)]
    assert binwire.read_hoptail(stamped, end=end - 1) == []
    # lenient mode on an inconsistent tail (count byte larger than the
    # frame) yields [] rather than raising — durable-replay safety
    assert binwire.read_hoptail(b"\x01\xff") == []
    assert binwire.read_hoptail(b"") == []


def test_hoptail_full_tail_drops_stamp_not_frame():
    body = binwire.encode_submit_columns(_rand_cols_ops(random.Random(2), 2))
    for i in range(0xFF):
        body = binwire.append_hop(body, HOP_SUBMIT, float(i))
    assert body[-1] == 0xFF
    assert binwire.append_hop(body, HOP_ADMIT, 1.0) == body  # capped
    assert len(binwire.read_hoptail(body)) == 0xFF


# -------------------------------------------------------------- registry


def test_registry_labels_and_prometheus_roundtrip():
    reg = MetricsRegistry()
    reg.inc("net.ingress.frames", 3, tier="frontend")
    reg.inc("net.ingress.frames", 2, tier="gateway")
    reg.set_gauge("deli.queue.depth", 7, doc="d1")
    for v in (1.0, 2.0, 3.0, 4.0):
        reg.observe("obs.hop.ms", v, pair="submit_to_admit")
    series = parse_prometheus(reg.scrape())
    frames = series["fluid_net_ingress_frames"]
    assert frames[(("tier", "frontend"),)] == 3
    assert frames[(("tier", "gateway"),)] == 2
    assert series["fluid_deli_queue_depth"][(("doc", "d1"),)] == 7
    cnt = series["fluid_obs_hop_ms_count"]
    assert cnt[(("pair", "submit_to_admit"),)] == 4
    assert series["fluid_obs_hop_ms_sum"][(("pair", "submit_to_admit"),)] \
        == 10.0
    assert series["fluid_obs_series_dropped"][()] == 0
    with pytest.raises(ValueError):
        parse_prometheus("not a metric line at all")


def test_registry_cardinality_is_bounded():
    """Past max_series distinct label sets, samples land in ONE overflow
    bucket and the spill is counted — a hostile label stream cannot grow
    the scrape without bound."""
    reg = MetricsRegistry(max_series=4)
    for i in range(10):
        reg.inc("front.conns.opened", tenant=f"t{i}")
    series = parse_prometheus(reg.scrape())
    conns = series["fluid_front_conns_opened"]
    assert len(conns) == 5  # 4 real label sets + the overflow bucket
    assert conns[(("overflow", "true"),)] == 6
    assert series["fluid_obs_series_dropped"][()] == 6


def test_tier_counters_aggregate_into_scrape():
    """Hot-path Counters instances registered under a tier label keep
    their lock-free writes; the scrape sums them per (name, tier)."""
    reg = MetricsRegistry()
    from fluidframework_tpu.utils.telemetry import Counters

    a, b = Counters(), Counters()
    reg.register_tier("deli", a)
    reg.register_tier("deli", b)
    a.inc("deli.boxcars.ticketed", 5)
    b.inc("deli.boxcars.ticketed", 7)
    a.observe("deli.ticket.ms", 2.0)
    series = parse_prometheus(reg.scrape())
    assert series["fluid_deli_boxcars_ticketed"][(("tier", "deli"),)] == 12
    assert series["fluid_deli_ticket_ms_count"][(("tier", "deli"),)] == 1


# ------------------------------------------------------- flight recorder


def test_flight_recorder_rings_and_dump(tmp_path):
    rec = FlightRecorder(dump_dir=str(tmp_path), event_ring=8,
                         frame_ring=4, max_conns=2)
    for i in range(20):
        rec.event("deli", "ticket", seq=i)
    for i in range(10):
        rec.frame("conn-a", "in", b"\x01\x05" + bytes([i]) * 20)
    rec.frame("conn-b", "out", b"\x01\x07")
    rec.frame("conn-c", "in", b"\x01\x05")  # evicts oldest-touched conn-a
    path = rec.dump("unit_test", detail="why")
    assert rec.last_dump == path
    lines = [json.loads(x) for x in open(path, encoding="utf-8")]
    header, rest = lines[0], lines[1:]
    assert header["flight"] == "unit_test" and header["detail"] == "why"
    events = [x for x in rest if x["kind"] == "event"]
    frames = [x for x in rest if x["kind"] == "frame"]
    assert [e["seq"] for e in events] == list(range(12, 20))  # ring of 8
    conns = {f["conn"] for f in frames}
    assert conns == {"conn-b", "conn-c"}  # conn-a LRU-evicted
    assert all(len(f["head"]) <= 24 for f in frames)  # digests, not bodies
    # a second dump gets its own file
    assert rec.dump("again") != path


# ----------------------------------------------- hop path, rec vs cols


@pytest.fixture
def front_end():
    fe = NetworkFrontEnd(LocalServer()).start_background()
    yield fe
    fe.stop()


def _frame(obj: dict) -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode()
    return len(body).to_bytes(4, "big") + body


def _bin_client(port: int, doc: str):
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    s.sendall(_frame({"t": "connect", "tenant": "t", "doc": doc,
                      "rid": 1, "bin": 1}))
    buf = [b""]

    def read_frame():
        while True:
            b = buf[0]
            if len(b) >= 4:
                n = int.from_bytes(b[:4], "big")
                if len(b) >= 4 + n:
                    buf[0] = b[4 + n:]
                    return b[4:4 + n]
            chunk = s.recv(65536)
            if not chunk:
                raise ConnectionError("closed")
            buf[0] += chunk
    while binwire.is_binary(read_frame()):
        pass  # drain the JSON connect reply
    return s, read_frame


def test_sampled_hops_survive_fanout_cache_verbatim(front_end):
    """A sampled columnar submit's client stamp reaches every subscriber
    BIT-IDENTICAL in the broadcast hoptail, with admit/deli/fanout
    appended in order — and the second subscriber is served the same
    bytes from the encode-once cache (satellite c1)."""
    ops = _rand_cols_ops(random.Random(27), 6)
    body = binwire.encode_submit_columns(ops)
    t_submit = time.time()
    body = binwire.append_hop(body, HOP_SUBMIT, t_submit)

    s1, read1 = _bin_client(front_end.port, "doc-hops")
    s2, read2 = _bin_client(front_end.port, "doc-hops")
    s1.sendall(binwire.frame(body))

    def next_cols(read):
        while True:
            f = read()
            if binwire.is_binary(f) and f[1] in (binwire.FT_COLS_OPS,
                                                 binwire.FT_COLS_FOPS):
                return f

    b1, b2 = next_cols(read1), next_cols(read2)
    assert b1 == b2  # encode-once fan-out: identical bytes
    # the client's stamp survives as its exact 9 wire bytes
    assert struct.pack(">Bd", HOP_SUBMIT, t_submit) in b1
    hops = binwire.read_hoptail(b1)
    assert [h for h, _ in hops] == [HOP_SUBMIT, HOP_ADMIT, HOP_DELI,
                                    HOP_FANOUT]
    assert hops[0][1] == t_submit  # verbatim through splice + cache
    ts = [t for _, t in hops]
    assert ts == sorted(ts)
    # egress observed every consecutive pair into the process registry
    series = parse_prometheus(get_registry().scrape())
    pairs = {dict(k).get("pair")
             for k in series.get("fluid_obs_hop_ms_count", {})}
    assert {"submit_to_admit", "admit_to_deli",
            "deli_to_fanout"} <= pairs
    snap = front_end.counters.snapshot
    assert wait_for(lambda: snap().get("net.fanout.cache_hits", 0) >= 1)
    s1.close()
    s2.close()


def test_aggregator_breakdown_identical_rec_vs_cols(front_end):
    """The SAME logical traffic traced through the rec path (per-op
    TraceHop records) and the columnar path (frame hoptail) must yield
    the same hop-pair breakdown from TraceAggregator (satellite c2)."""
    # --- rec path: a non-columnable op with an explicit client stamp
    factory = NetworkDocumentServiceFactory("127.0.0.1", front_end.port)
    conn = factory.create_document_service(
        "t", "doc-rec").connect_to_delta_stream()
    acked = []
    conn.on_op = lambda m: (m.client_id == conn.client_id
                            and acked.append(m))
    conn.submit([DocumentMessage(
        client_sequence_number=1, reference_sequence_number=0,
        type=MessageType.OPERATION, contents={"free": "form"},
        traces=[TraceHop("client", "submit", time.time())])])
    assert wait_for(lambda: acked)
    agg_rec = TraceAggregator()
    agg_rec.record(acked[0], ack_time=time.time())
    conn.close()

    # --- cols path: a sampled columnar boxcar over the binary wire
    body = binwire.encode_submit_columns(_rand_cols_ops(random.Random(3), 4))
    body = binwire.append_hop(body, HOP_SUBMIT, time.time())
    s, read = _bin_client(front_end.port, "doc-cols2")
    s.sendall(binwire.frame(body))
    while True:
        f = read()
        if binwire.is_binary(f) and f[1] in (binwire.FT_COLS_OPS,
                                             binwire.FT_COLS_FOPS):
            break
    s.close()
    agg_cols = TraceAggregator()
    agg_cols.record_hops(binwire.read_hoptail(f), ack_time=time.time())

    rep_rec, rep_cols = agg_rec.report(), agg_cols.report()
    assert set(rep_rec) == set(rep_cols) == {
        "submit_to_admit", "admit_to_deli", "deli_to_fanout",
        "fanout_to_ack"}
    assert all(rep_rec[k]["count"] == rep_cols[k]["count"] == 1
               for k in rep_rec)


def test_hop_pairs_keeps_last_ts_on_repeat():
    """A repeated hop id (retried relay) keeps the LAST stamp so legs
    stay non-overlapping."""
    pairs = dict(hop_pairs([(HOP_SUBMIT, 1.0), (HOP_SUBMIT, 2.0),
                            (HOP_DELI, 5.0)]))
    assert pairs == {"submit_to_deli": 3000.0}
