"""Columnar durable log: segment store, torn-tail recovery, backfill door.

The storage tier's columnar lane (PR 6) persists each sequenced boxcar
as ONE packed column block (native/oplog.cpp segment files + a 32-byte
seq-span index entry); recovery replay is vectorized frombuffer decode,
and catch-up backfill is binary search over the index plus raw
byte-range copies served to binary clients verbatim. These tests pin:

- the native segment primitives (append/read/entry, rolls, torn-tail
  truncation in both tear modes, cross-handle reopen);
- the mmap'd SegmentReader (tail validation never admits a torn block,
  range queries stay sound under deli crash-replay span regressions);
- DurableLog routing (segment lane for deltas topics, record-format
  directories stay record-format, the legacy_json deprecation counter
  scoping);
- the chaos torn seam (a ticketed deltas record SURVIVES a physical
  tear — unlike the rawops torn, where the client resubmits);
- the backfill door end to end (zero decodes server-side, retention
  boundary raising on both sides, columnar == scalar results over a
  real socket);
- the legacy _wrap/_unwrap JSON shim round-trip under adversarial
  tag-key collisions.
"""

from __future__ import annotations

import os
import random
import tempfile
import time

import numpy as np
import pytest

from fluidframework_tpu.native.oplog import NativeOpLog
from fluidframework_tpu.protocol import binwire
from fluidframework_tpu.protocol.messages import (
    DocumentMessage,
    MessageType,
    SequencedDocumentMessage,
)
from fluidframework_tpu.service.array_batch import (
    ArrayBoxcar,
    SequencedArrayBatch,
)
from fluidframework_tpu.service.durable_log import (
    DurableLog,
    _decode_value,
    _encode_value,
    _desanitize,
    _sanitize,
)
from fluidframework_tpu.service.log_compat import (
    _TAG_ESC,
    _TAG_MSG,
    decode_json_value,
    encode_json_value,
)
from fluidframework_tpu.service.segment_store import SegmentReader


def _boxcar(n=3, tenant="t0", doc="d0", client="c1", ts=12.5):
    text = "ab" * n
    text_off = np.arange(0, 2 * n + 2, 2, dtype=np.int32)[: n + 1]
    return ArrayBoxcar(
        tenant_id=tenant, document_id=doc, client_id=client,
        ds_id="root", channel_id="seq", kind=np.zeros(n, np.int8),
        a=np.arange(n, dtype=np.int32), b=np.zeros(n, np.int32),
        cseq=np.arange(1, n + 1, dtype=np.int32),
        rseq=np.zeros(n, np.int32),
        text=text, text_off=text_off, props=None, timestamp=ts)


def _abatch_record(base_seq, n=3, tenant="t0", doc="d0", ts=100.0):
    box = _boxcar(n, tenant=tenant, doc=doc)
    return {"tenant_id": tenant, "document_id": doc,
            "abatch": SequencedArrayBatch(
                boxcar=box, base_seq=base_seq,
                msns=np.arange(base_seq, base_seq + n, dtype=np.int64),
                timestamp=ts)}


def _storage_snap(log):
    return {k: v for k, v in log.counters.snapshot().items()
            if k.startswith("storage.")}


def _delta(before, after, key):
    return after.get(key, 0) - before.get(key, 0)


# ===================================================================
# native segment primitives
# ===================================================================

def test_native_seg_roundtrip_rolls_and_reopen(tmp_path):
    d = str(tmp_path)
    log = NativeOpLog(d)
    log.seg_config(256)  # tiny threshold: force rolls
    blocks = []
    seq = 1
    for i in range(12):
        payload = bytes([i]) * (60 + i)
        blocks.append((seq, seq + 2, payload))
        assert log.seg_append("s", seq, seq + 2, payload, 1) == i
        seq += 3
    assert log.seg_count("s") == 12
    segs = [f for f in os.listdir(d) if f.startswith("s.seg")
            and not f.endswith(".segidx")]
    assert len(segs) > 1, "256-byte threshold never rolled a segment"
    for i, (first, last, payload) in enumerate(blocks):
        assert log.seg_read("s", i) == payload
        e_first, e_last, _seg, _off, e_len, e_btype = log.seg_entry("s", i)
        assert (e_first, e_last, e_len, e_btype) == (
            first, last, len(payload), 1)
    log.close()
    # a fresh handle over the same directory sees every block
    log2 = NativeOpLog(d)
    assert log2.seg_count("s") == 12
    assert log2.seg_read("s", 7) == blocks[7][2]
    log2.close()


@pytest.mark.parametrize("mode", [0, 1])
def test_native_torn_tail_truncated_on_reopen(tmp_path, mode):
    """mode 0 = half the block bytes and no index entry; mode 1 = full
    block but half an index entry. Both leave ragged bytes the open-time
    recovery scan must cut; the admitted prefix is untouched and the
    next append lands cleanly after it."""
    d = str(tmp_path)
    log = NativeOpLog(d)
    good = [b"alpha" * 10, b"bravo" * 10]
    for i, p in enumerate(good):
        log.seg_append("s", 10 * i + 1, 10 * i + 5, p, 1)
    log.seg_tear("s", 21, 25, b"torn-victim" * 8, 1, mode=mode)
    log.close()

    log2 = NativeOpLog(d)
    assert log2.seg_count("s") == 2  # the torn tail was never admitted
    assert log2.seg_read("s", 0) == good[0]
    assert log2.seg_read("s", 1) == good[1]
    assert log2.seg_append("s", 21, 25, b"survivor", 1) == 2
    assert log2.seg_read("s", 2) == b"survivor"
    log2.close()


def test_segment_reader_never_admits_torn_tail(tmp_path):
    d = str(tmp_path)
    log = NativeOpLog(d)
    log.seg_append("s", 1, 3, b"first", 1)
    reader = SegmentReader(d, "s", flush=log.flush)
    assert reader.refresh() == 1
    # a torn index entry (mode 1) must stay invisible to a live tailer
    log.seg_tear("s", 4, 6, b"ragged" * 4, 1, mode=1)
    log.flush()
    assert reader.refresh() == 1
    assert reader.block(0)[3] == b"first"
    with pytest.raises(IndexError):
        reader.block(1)
    # writer recovery (next append) cuts the tail; the reader then
    # admits exactly the recovered block
    log.seg_append("s", 4, 6, b"clean", 1)
    assert reader.refresh() == 2
    assert reader.block(1) == (1, 4, 6, b"clean")
    reader.close()
    log.close()


def test_range_blocks_sound_under_replay_span_regression(tmp_path):
    """Deli crash-replay re-appends blocks whose seq spans REGRESS below
    earlier entries (at-least-once duplicates); the index query must
    still return every overlapping ordinal — plain searchsorted over the
    raw span columns is unsound here."""
    d = str(tmp_path)
    log = NativeOpLog(d)
    spans = [(1, 3), (4, 6), (7, 9), (4, 6), (10, 12)]  # [3] is a replay
    for i, (first, last) in enumerate(spans):
        log.seg_append("s", first, last, b"%d" % i, 1)
    reader = SegmentReader(d, "s", flush=log.flush)
    reader.refresh()

    def overlapping(from_seq, to_seq):
        return [i for i, (f, l) in enumerate(spans)
                if l > from_seq and f < to_seq]

    rng = random.Random(5)
    for _ in range(200):
        a = rng.randrange(-1, 14)
        b = rng.randrange(-1, 15)
        assert reader.range_blocks(a, b) == overlapping(a, b), (a, b)
    assert reader.range_blocks(3, 10) == [1, 2, 3]  # both replay copies
    reader.close()
    log.close()


# ===================================================================
# DurableLog: lanes, routing, counters
# ===================================================================

def test_sanitize_roundtrip_fuzz():
    rng = random.Random(11)
    alphabet = "ab_.d/-0"
    for _ in range(500):
        topic = "".join(rng.choice(alphabet)
                        for _ in range(rng.randrange(1, 16)))
        san = _sanitize(topic)
        assert "/" not in san
        assert _desanitize(san) == topic, (topic, san)


def test_kind3_raw_boxcar_record_roundtrip():
    box = _boxcar()
    data = _encode_value(box)
    assert data[0] == 0xFF and data[1] == 3
    out = _decode_value(data)
    assert (out.tenant_id, out.document_id, out.client_id) == (
        "t0", "d0", "c1")
    assert out.text == box.text and np.array_equal(out.a, box.a)
    assert out.wire_cols is not None  # decode keeps the column bytes


def test_durable_log_segment_roundtrip_and_recovery_replay(tmp_path):
    d = str(tmp_path)
    topic = "deltas/t0/d0"
    log = DurableLog(d, segment_bytes=2048)
    before = _storage_snap(log)
    seq = 1
    for i in range(20):
        rec = _abatch_record(seq, n=3, ts=100.0 + i)
        log.append(topic, rec)
        seq += 3
    after = _storage_snap(log)
    assert _delta(before, after, "storage.segment.appends") == 20
    assert _delta(before, after, "storage.log.legacy_json") == 0
    assert os.path.exists(os.path.join(d, _sanitize(topic) + ".segidx"))

    log._read_cache.clear()
    v = log.read(topic, 5)
    assert v["abatch"].base_seq == 16
    msgs = v["abatch"].messages()
    assert [m.sequence_number for m in msgs] == [16, 17, 18]
    log.close()

    # recovery: a fresh process sees every block and decodes on read
    log2 = DurableLog(d)
    before = _storage_snap(log2)
    assert log2.length(topic) == 20
    replayed = [log2.read(topic, i) for i in range(20)]
    assert [r["abatch"].base_seq for r in replayed] == \
        list(range(1, 60, 3))
    after = _storage_snap(log2)
    assert _delta(before, after, "storage.segment.decodes") == 20
    log2.close()


def test_record_format_directory_stays_record_lane(tmp_path):
    """A deltas directory written before the segment store existed must
    stay record-format for reads AND subsequent writes — mixing lanes
    would split the topic's order across two files."""
    d = str(tmp_path)
    topic = "deltas/t0/d0"
    old = DurableLog(d, segmented=False)
    old.append(topic, _abatch_record(1))
    old.close()
    assert not any(f.endswith(".segidx") for f in os.listdir(d))

    log = DurableLog(d)  # segmented=True default
    before = _storage_snap(log)
    assert log.length(topic) == 1
    log.append(topic, _abatch_record(4))
    assert not any(f.endswith(".segidx") for f in os.listdir(d))
    assert log.length(topic) == 2
    log._read_cache.clear()
    assert log.read(topic, 1)["abatch"].base_seq == 4
    after = _storage_snap(log)
    assert _delta(before, after, "storage.segment.appends") == 0
    assert log.delta_blocks(topic, 0, 100) is None  # scalar fallback
    log.close()


def test_legacy_json_counter_scoping(tmp_path):
    """The deprecation counter tracks the DELTAS lane only: JSON-shaped
    deltas records count (segment SEG_JSON and record-lane alike);
    binary kinds and non-deltas topics (checkpoints, rawops) don't."""
    log = DurableLog(str(tmp_path))
    before = _storage_snap(log)
    log.append("rawops/t0/d0", _boxcar())           # kind-3 binary
    log.append("checkpoints/t0/d0", {"deli": {}})   # non-deltas JSON
    after = _storage_snap(log)
    assert _delta(before, after, "storage.log.legacy_json") == 0

    log.append("deltas/t0/d0", {"weird": "record"})  # SEG_JSON shim
    after2 = _storage_snap(log)
    assert _delta(after, after2, "storage.log.legacy_json") == 1
    log._read_cache.clear()
    assert log.read("deltas/t0/d0", 0) == {"weird": "record"}
    after3 = _storage_snap(log)
    assert _delta(after2, after3, "storage.log.legacy_json") == 1
    log.close()


def test_torn_append_on_segment_lane_record_survives(tmp_path):
    """The chaos torn directive on a segment stream leaves a PHYSICAL
    ragged tail, then runs the same detect-truncate-rewrite cycle crash
    recovery runs — and the record itself survives (it is already
    ticketed; a lost seq would stall every consumer forever)."""
    d = str(tmp_path)
    topic = "deltas/t0/d0"
    log = DurableLog(d)

    pending = ["torn", "torn"]  # exercise both alternating tear modes

    def plane(point, **ctx):
        if point == "log.append" and ctx["topic"] == topic and pending:
            return pending.pop()
        return None

    log.fault_plane = plane
    before = _storage_snap(log)
    for i in range(4):
        log.append(topic, _abatch_record(1 + 3 * i))
    after = _storage_snap(log)
    assert _delta(before, after, "storage.segment.torn") == 2
    assert _delta(before, after, "storage.segment.appends") == 4
    assert log.length(topic) == 4
    log.close()

    log2 = DurableLog(d)
    assert log2.length(topic) == 4
    assert [log2.read(topic, i)["abatch"].base_seq for i in range(4)] \
        == [1, 4, 7, 10]
    log2.close()


def test_delta_blocks_zero_decode_byte_range_backfill(tmp_path):
    """The backfill door serves raw SEG_COLS payloads straight out of
    the segment mmaps: ZERO decodes server-side (counter-verified), and
    the payload bytes round-trip through the wire codec to exactly the
    covered messages. Boundary blocks may span past the range — the
    client trims."""
    topic = "deltas/t0/d0"
    log = DurableLog(str(tmp_path))
    seq = 1
    for i in range(50):
        log.append(topic, _abatch_record(seq, n=3))
        seq += 3
    before = _storage_snap(log)
    res = log.delta_blocks(topic, 10, 40)
    assert res is not None
    payloads, legacy = res
    assert legacy == []
    after = _storage_snap(log)
    assert _delta(before, after, "storage.segment.decodes") == 0
    assert _delta(before, after, "storage.backfill.byterange") == \
        len(payloads)

    # client-side decode: FT_COLS_DELTAS body -> messages; the trimmed
    # union covers exactly (10, 40) exclusive
    seqs = []
    for p in payloads:
        _rid, msgs = binwire.read_cols_deltas(
            binwire.cols_deltas_body(7, p))
        seqs.extend(m.sequence_number for m in msgs)
    assert [s for s in sorted(seqs) if 10 < s < 40] == list(range(11, 40))
    covered = set(range(11, 40))
    assert covered <= set(seqs)
    # superset only at block boundaries: nothing beyond one block away
    assert min(seqs) > 10 - 3 and max(seqs) < 40 + 3
    log.close()


def test_legacy_blocks_materialize_through_shim(tmp_path):
    """SEG_JSON blocks interleaved in the range come back as in-range
    message objects (the compat shim), alongside the raw payloads."""
    topic = "deltas/t0/d0"
    log = DurableLog(str(tmp_path))
    log.append(topic, _abatch_record(1, n=3))
    legacy_msg = SequencedDocumentMessage(
        client_id="c9", sequence_number=4, minimum_sequence_number=1,
        client_sequence_number=1, reference_sequence_number=1,
        type=MessageType.OPERATION, contents={"x": 1}, timestamp=5.0)
    log.append(topic, {"tenant_id": "t0", "document_id": "d0",
                       "message": legacy_msg})
    log.append(topic, _abatch_record(5, n=2))
    payloads, legacy = log.delta_blocks(topic, 0, 100)
    assert len(payloads) == 2
    assert [m.sequence_number for m in legacy] == [4]
    assert legacy[0] == legacy_msg
    log.close()


# ===================================================================
# retention boundary + the network backfill door
# ===================================================================

def test_local_server_backfill_retention_boundary(tmp_path):
    from fluidframework_tpu.service import LocalServer
    from fluidframework_tpu.service.scriptorium import LogTruncatedError

    server = LocalServer(log=DurableLog(str(tmp_path)))
    # drive ops through the real pipeline so the deltas topic fills
    conn = server.connect("t", "doc")
    for i in range(10):
        conn.submit([DocumentMessage(
            client_sequence_number=i + 1, reference_sequence_number=0,
            type=MessageType.OPERATION, contents={"i": i})])
    server.drain()
    orderer = server._get_orderer("t", "doc")
    orderer.scriptorium.truncate_below("t", "doc", 5)

    # from_seq == base: allowed (serves (5, to) exclusive)
    res = server.get_delta_blocks("t", "doc", 5, 100)
    assert res is not None
    _payloads, _legacy, head = res
    assert head == orderer.scriptorium.head_seq("t", "doc")
    # one below the base: explicit too-far-behind error, never a
    # silently partial range
    with pytest.raises(LogTruncatedError) as ei:
        server.get_delta_blocks("t", "doc", 4, 100)
    assert ei.value.base == 5


def test_network_backfill_door_columnar_equals_scalar(tmp_path):
    """End to end over a real socket: the connected reply advertises
    colsBackfill, the driver's columnar get_deltas returns exactly what
    the scalar door returns (including exclusive-bound trimming), and
    reaching below the retention base surfaces the driver-local
    LogTruncatedError with the base attached."""
    from fluidframework_tpu.driver import NetworkDocumentServiceFactory
    from fluidframework_tpu.driver.network import LogTruncatedError
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.service import LocalServer, NetworkFrontEnd

    log = DurableLog(str(tmp_path))
    server = LocalServer(log=log)
    fe = NetworkFrontEnd(server).start_background()
    try:
        factory = NetworkDocumentServiceFactory("127.0.0.1", fe.port)
        loader = Loader(factory)
        c1 = loader.resolve("t", "doc1")
        s1 = c1.runtime.create_data_store("default") \
            .create_channel("text", "shared-string")
        for i in range(50):
            s1.insert_text(len(s1.get_text()), f"{i % 10}")
        deadline = time.time() + 10
        while time.time() < deadline and len(s1.get_text()) < 50:
            time.sleep(0.05)
        assert len(s1.get_text()) == 50

        svc = factory.create_document_service("t", "doc1")
        conn = svc.connect_to_delta_stream()
        assert conn.cols_backfill is True
        storage = svc.connect_to_delta_storage()

        before = _storage_snap(log)
        msgs = storage.get_deltas(0, 1000)
        seqs = [m.sequence_number for m in msgs]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        assert seqs == [m.sequence_number
                        for m in server.get_deltas("t", "doc1", 0, 1000)]
        sub = storage.get_deltas(10, 20)
        assert [m.sequence_number for m in sub] == \
            [m.sequence_number
             for m in server.get_deltas("t", "doc1", 10, 20)]
        after = _storage_snap(log)
        assert _delta(before, after, "storage.backfill.byterange") > 0
        assert _delta(before, after, "storage.segment.decodes") == 0

        orderer = server._get_orderer("t", "doc1")
        orderer.scriptorium.truncate_below("t", "doc1", 10)
        assert storage.get_deltas(10, 20)  # at the base: fine
        with pytest.raises(LogTruncatedError) as ei:
            storage.get_deltas(9, 20)     # below it: explicit error
        assert ei.value.base == 10
        conn.close()
        c1.close()
    finally:
        fe.stop()


# ===================================================================
# the legacy JSON shim (_wrap/_unwrap) under tag collisions
# ===================================================================

def _rand_json_value(rng, depth=0):
    r = rng.random()
    if depth >= 4 or r < 0.35:
        return rng.choice([
            None, True, False, 17, -3, 2.5, "plain", "",
            _TAG_MSG, _TAG_ESC,  # tag names as VALUES must pass through
        ])
    if r < 0.55:
        return [_rand_json_value(rng, depth + 1)
                for _ in range(rng.randrange(3))]
    if r < 0.65:
        return SequencedDocumentMessage(
            client_id=f"c{rng.randrange(3)}",
            sequence_number=rng.randrange(100),
            minimum_sequence_number=0,
            client_sequence_number=rng.randrange(10),
            reference_sequence_number=rng.randrange(10),
            type=MessageType.OPERATION,
            contents={"p": rng.randrange(5)}, timestamp=1.5)
    keys = ["a", "b", _TAG_MSG, _TAG_ESC, "c_d"]
    return {rng.choice(keys): _rand_json_value(rng, depth + 1)
            for _ in range(rng.randrange(4))}


def test_wrap_unwrap_fuzz_roundtrip_with_tag_collisions():
    """decode(encode(v)) == v for arbitrarily nested JSON-able values
    whose dict keys COLLIDE with the shim's tag keys (including dicts
    that look exactly like the wrapped forms), with protocol messages
    embedded at any depth."""
    rng = random.Random(1234)
    for trial in range(300):
        v = _rand_json_value(rng)
        out = decode_json_value(encode_json_value(v))
        assert out == v, (trial, v, out)


def test_wrap_unwrap_adversarial_shapes():
    cases = [
        {_TAG_MSG: 5},
        {_TAG_ESC: {_TAG_MSG: 5}},
        {_TAG_ESC: {_TAG_ESC: {}}},
        {_TAG_MSG: {_TAG_MSG: {_TAG_MSG: None}}},
        {_TAG_MSG: 1, _TAG_ESC: 2, "x": 3},
        [{_TAG_MSG: [{_TAG_ESC: "y"}]}],
        {"outer": {_TAG_ESC: {"inner": {_TAG_MSG: [1, 2]}}}},
    ]
    for v in cases:
        assert decode_json_value(encode_json_value(v)) == v, v
