"""AgentScheduler: exclusive task ownership over register consensus,
reassignment on owner departure (ref: agent-scheduler scheduler.ts:34).
"""

import pytest

from fluidframework_tpu.driver import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.runtime.agent_scheduler import AgentScheduler
from fluidframework_tpu.service import LocalServer


@pytest.fixture
def server():
    return LocalServer()


@pytest.fixture
def loader(server):
    return Loader(LocalDocumentServiceFactory(server))


def boot_pair(loader):
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    c1.runtime.create_data_store("default")
    s1 = AgentScheduler(c1)
    s2 = AgentScheduler(c2)
    return c1, c2, s1, s2


def test_exactly_one_volunteer_wins(loader):
    c1, c2, s1, s2 = boot_pair(loader)
    s1.pick("intel")
    s2.pick("intel")
    assert s1.owner("intel") == s2.owner("intel")
    assert s1.owns("intel") != s2.owns("intel")  # exactly one


def test_ownership_transfers_on_leave(loader):
    c1, c2, s1, s2 = boot_pair(loader)
    events = []
    s1.pick("summarizer", lambda owned: events.append(("c1", owned)))
    s2.pick("summarizer", lambda owned: events.append(("c2", owned)))
    first_owner = s1.owner("summarizer")
    loser = s2 if s1.owns("summarizer") else s1
    winner_container = c1 if s1.owns("summarizer") else c2
    winner_container.close()  # sequenced leave reaches the survivor
    assert loser.owns("summarizer")
    assert loser.owner("summarizer") != first_owner
    assert ("c1", True) in events or ("c2", True) in events


def test_release_hands_off_to_volunteer(loader):
    c1, c2, s1, s2 = boot_pair(loader)
    s1.pick("task")
    assert s1.owns("task")
    s2.pick("task")
    assert not s2.owns("task")
    s1.release("task")
    assert s2.owns("task") and not s1.owns("task")


def test_owner_visible_from_non_volunteers(loader):
    c1, c2, s1, s2 = boot_pair(loader)
    s1.pick("solo")
    assert s2.owner("solo") == c1.client_id
    assert "solo" in s2.tasks
