"""Segment-sharded giant-doc APPLY (SURVEY §5.7 SP analog): the composed
sharded apply must match the single-chip kernel op-for-op on a fuzzed
stream — same content, same stamps, same zamboni result.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluidframework_tpu.ops.apply import (
    apply_ops_scan,
    compact,
    wave_min_seq,
)
from fluidframework_tpu.ops.doc_state import DocState
from fluidframework_tpu.ops.opgen import generate_batch_ops
from fluidframework_tpu.parallel.long_doc import sharded_apply_ops
from fluidframework_tpu.parallel.mesh import make_mesh, shard_map

N_SHARDS = 8
S_LOCAL = 64
S_GLOBAL = N_SHARDS * S_LOCAL

SLOT_FIELDS = ("length", "text_start", "flags", "ins_seq", "ins_client",
               "rem_seq", "rem_client_a", "rem_client_b")


def _live_rows(arrs, counts):
    """Ordered logical segment rows (shard-major, used slots only)."""
    rows = []
    if np.isscalar(counts) or counts.ndim == 0:
        counts = np.array([counts])
        arrs = {k: v[None, :] for k, v in arrs.items()}
    for s in range(len(counts)):
        for i in range(int(counts[s])):
            rows.append(tuple(int(arrs[f][s, i]) for f in SLOT_FIELDS))
    return rows


def _run_pair(seed, n_ops, remove_fraction=0.3, annotate_fraction=0.1):
    rng = np.random.default_rng(seed)
    ops = jnp.asarray(generate_batch_ops(
        rng, 1, n_ops, remove_fraction=remove_fraction,
        annotate_fraction=annotate_fraction, max_insert=6)[0])

    # --- single-chip reference
    ref = DocState.empty(S_GLOBAL)
    ref = jax.tree.map(jnp.asarray, ref)
    ref = apply_ops_scan(ref, ops)
    ref = compact(ref, wave_min_seq(ops))
    assert not bool(ref.overflow), "reference overflowed; enlarge slots"

    # --- sharded: per-shard [S_LOCAL] arrays + per-shard counts
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(N_SHARDS, seg_shards=N_SHARDS)
    empty = DocState.empty(S_LOCAL)
    sharded = DocState(
        **{f: jnp.tile(getattr(empty, f), (N_SHARDS,) +
                       (1,) * getattr(empty, f).ndim)
           for f in SLOT_FIELDS + ("prop_key", "prop_val")},
        count=jnp.zeros(N_SHARDS, jnp.int32),
        overflow=jnp.zeros(N_SHARDS, bool),
    )
    seg = P("seg")
    specs = DocState(
        **{f: seg for f in SLOT_FIELDS + ("prop_key", "prop_val")},
        count=seg, overflow=seg,
    )

    def body(st, ops):
        # strip the leading length-1 shard axis shard_map hands us
        local = jax.tree.map(lambda a: a[0], st)
        out = sharded_apply_ops(local, ops, axis="seg")
        return jax.tree.map(lambda a: a[None], out)

    fn = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(specs, P()), out_specs=specs,
        check_vma=False))
    out = fn(sharded, ops)
    counts = np.asarray(out.count)
    assert not np.asarray(out.overflow).any(), "sharded path overflowed"

    ref_rows = _live_rows(
        {f: np.asarray(getattr(ref, f)) for f in SLOT_FIELDS},
        np.asarray(ref.count))
    out_rows = _live_rows(
        {f: np.asarray(getattr(out, f)) for f in SLOT_FIELDS}, counts)
    return ref_rows, out_rows, counts


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_sharded_apply_matches_single_chip(seed):
    ref_rows, out_rows, _ = _run_pair(seed, n_ops=48)
    assert out_rows == ref_rows


def _run_chunked_with_rebalancing(ops_all, chunk=8):
    """Chunked sharded apply with watermark rebalancing between waves,
    against the single-chip reference. Returns (ref_rows, out_rows,
    counts, rebalances)."""
    from jax.sharding import PartitionSpec as P

    from fluidframework_tpu.parallel.long_doc import rebalance_shards

    n_ops = int(ops_all.shape[0])
    # an op can add up to 3 slots, so the rebalance watermark must leave
    # a full chunk's worst-case growth of headroom
    watermark = S_LOCAL - 3 * chunk

    ref = jax.tree.map(jnp.asarray, DocState.empty(S_GLOBAL))
    ref = apply_ops_scan(ref, ops_all)
    ref = compact(ref, wave_min_seq(ops_all))
    assert not bool(ref.overflow)

    mesh = make_mesh(N_SHARDS, seg_shards=N_SHARDS)
    empty = DocState.empty(S_LOCAL)
    all_fields = SLOT_FIELDS + ("prop_key", "prop_val")
    state = DocState(
        **{f: jnp.tile(getattr(empty, f), (N_SHARDS,) +
                       (1,) * getattr(empty, f).ndim) for f in all_fields},
        count=jnp.zeros(N_SHARDS, jnp.int32),
        overflow=jnp.zeros(N_SHARDS, bool),
    )
    seg = P("seg")
    specs = DocState(**{f: seg for f in all_fields}, count=seg, overflow=seg)

    def body(st, ops):
        local = jax.tree.map(lambda a: a[0], st)
        out = sharded_apply_ops(local, ops, axis="seg")
        return jax.tree.map(lambda a: a[None], out)

    fn = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(specs, P()), out_specs=specs,
        check_vma=False))

    rebalances = 0
    for i in range(0, n_ops, chunk):
        state = fn(state, ops_all[i:i + chunk])
        assert not np.asarray(state.overflow).any(), f"overflow at op {i}"
        counts = np.asarray(state.count)
        if counts.max() > watermark:
            arrays = {f: np.asarray(getattr(state, f)) for f in all_fields}
            arrays, new_counts = rebalance_shards(arrays, counts)
            state = DocState(
                **{f: jnp.asarray(a) for f, a in arrays.items()},
                count=jnp.asarray(new_counts),
                overflow=jnp.zeros(N_SHARDS, bool),
            )
            rebalances += 1

    counts = np.asarray(state.count)
    ref_rows = _live_rows(
        {f: np.asarray(getattr(ref, f)) for f in SLOT_FIELDS},
        np.asarray(ref.count))
    out_rows = _live_rows(
        {f: np.asarray(getattr(state, f)) for f in SLOT_FIELDS}, counts)
    return ref_rows, out_rows, counts, rebalances


def test_heavy_stream_with_watermark_rebalancing():
    """Mid-doc inserts pile onto the boundary-owning shard; a long
    insert-heavy stream therefore needs host rebalancing between waves
    (the bulk analog of B-tree node splits). Chunked apply with a 75%
    watermark must track the single-chip kernel exactly."""
    rng = np.random.default_rng(7)
    ops_all = jnp.asarray(generate_batch_ops(
        rng, 1, 96, remove_fraction=0.15, annotate_fraction=0.05,
        max_insert=6)[0])
    ref_rows, out_rows, counts, rebalances = \
        _run_chunked_with_rebalancing(ops_all)
    assert out_rows == ref_rows
    assert rebalances >= 1          # the stream really needed it
    assert (counts > 0).sum() > 1   # content spans shards afterwards


@pytest.mark.parametrize("seed", [0, 7, 42])
def test_giant_doc_exceeds_single_shard_budget(seed):
    """Adversarial giant doc (ISSUE 9): ONE doc whose live segment count
    exceeds a single shard's S_LOCAL budget — impossible to hold on one
    seg shard, so the stream only survives via cross-shard rebalancing —
    must still match the single-chip reference row-for-row."""
    rng = np.random.default_rng(seed)
    ops_all = jnp.asarray(generate_batch_ops(
        rng, 1, 128, remove_fraction=0.08, annotate_fraction=0.05,
        max_insert=6)[0])
    ref_rows, out_rows, counts, rebalances = \
        _run_chunked_with_rebalancing(ops_all)
    assert len(ref_rows) > S_LOCAL  # the doc genuinely outgrew one shard
    assert out_rows == ref_rows
    assert rebalances >= 1
    assert counts.max() <= S_LOCAL  # no shard holds more than its budget


def test_rebalance_refuses_when_doc_outgrows_whole_mesh():
    """Past total capacity an even spread no longer fits; silent
    out-of-bounds packing would corrupt shard-major order, so
    rebalance_shards must refuse loudly."""
    from fluidframework_tpu.parallel.long_doc import rebalance_shards

    arrays = {"length": np.ones((2, 4), np.int32)}
    with pytest.raises(ValueError, match="cannot fit"):
        rebalance_shards(arrays, np.array([5, 5], np.int32))
