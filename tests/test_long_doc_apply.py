"""Segment-sharded giant-doc APPLY (SURVEY §5.7 SP analog): the composed
sharded apply must match the single-chip kernel op-for-op on a fuzzed
stream — same content, same stamps, same zamboni result.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluidframework_tpu.ops.apply import (
    apply_ops_scan,
    compact,
    wave_min_seq,
)
from fluidframework_tpu.ops.doc_state import DocState
from fluidframework_tpu.ops.opgen import generate_batch_ops
from fluidframework_tpu.parallel.long_doc import sharded_apply_ops
from fluidframework_tpu.parallel.mesh import make_mesh, shard_map

N_SHARDS = 8
S_LOCAL = 64
S_GLOBAL = N_SHARDS * S_LOCAL

SLOT_FIELDS = ("length", "text_start", "flags", "ins_seq", "ins_client",
               "rem_seq", "rem_client_a", "rem_client_b")


def _live_rows(arrs, counts):
    """Ordered logical segment rows (shard-major, used slots only)."""
    rows = []
    if np.isscalar(counts) or counts.ndim == 0:
        counts = np.array([counts])
        arrs = {k: v[None, :] for k, v in arrs.items()}
    for s in range(len(counts)):
        for i in range(int(counts[s])):
            rows.append(tuple(int(arrs[f][s, i]) for f in SLOT_FIELDS))
    return rows


def _run_pair(seed, n_ops, remove_fraction=0.3, annotate_fraction=0.1):
    rng = np.random.default_rng(seed)
    ops = jnp.asarray(generate_batch_ops(
        rng, 1, n_ops, remove_fraction=remove_fraction,
        annotate_fraction=annotate_fraction, max_insert=6)[0])

    # --- single-chip reference
    ref = DocState.empty(S_GLOBAL)
    ref = jax.tree.map(jnp.asarray, ref)
    ref = apply_ops_scan(ref, ops)
    ref = compact(ref, wave_min_seq(ops))
    assert not bool(ref.overflow), "reference overflowed; enlarge slots"

    # --- sharded: per-shard [S_LOCAL] arrays + per-shard counts
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(N_SHARDS, seg_shards=N_SHARDS)
    empty = DocState.empty(S_LOCAL)
    sharded = DocState(
        **{f: jnp.tile(getattr(empty, f), (N_SHARDS,) +
                       (1,) * getattr(empty, f).ndim)
           for f in SLOT_FIELDS + ("prop_key", "prop_val")},
        count=jnp.zeros(N_SHARDS, jnp.int32),
        overflow=jnp.zeros(N_SHARDS, bool),
    )
    seg = P("seg")
    specs = DocState(
        **{f: seg for f in SLOT_FIELDS + ("prop_key", "prop_val")},
        count=seg, overflow=seg,
    )

    def body(st, ops):
        # strip the leading length-1 shard axis shard_map hands us
        local = jax.tree.map(lambda a: a[0], st)
        out = sharded_apply_ops(local, ops, axis="seg")
        return jax.tree.map(lambda a: a[None], out)

    fn = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(specs, P()), out_specs=specs,
        check_vma=False))
    out = fn(sharded, ops)
    counts = np.asarray(out.count)
    assert not np.asarray(out.overflow).any(), "sharded path overflowed"

    ref_rows = _live_rows(
        {f: np.asarray(getattr(ref, f)) for f in SLOT_FIELDS},
        np.asarray(ref.count))
    out_rows = _live_rows(
        {f: np.asarray(getattr(out, f)) for f in SLOT_FIELDS}, counts)
    return ref_rows, out_rows, counts


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_sharded_apply_matches_single_chip(seed):
    ref_rows, out_rows, _ = _run_pair(seed, n_ops=48)
    assert out_rows == ref_rows


def test_heavy_stream_with_watermark_rebalancing():
    """Mid-doc inserts pile onto the boundary-owning shard; a long
    insert-heavy stream therefore needs host rebalancing between waves
    (the bulk analog of B-tree node splits). Chunked apply with a 75%
    watermark must track the single-chip kernel exactly."""
    from jax.sharding import PartitionSpec as P

    from fluidframework_tpu.parallel.long_doc import rebalance_shards

    rng = np.random.default_rng(7)
    n_ops, chunk = 96, 8
    # an op can add up to 3 slots, so the rebalance watermark must leave
    # a full chunk's worst-case growth of headroom
    watermark = S_LOCAL - 3 * chunk
    ops_all = jnp.asarray(generate_batch_ops(
        rng, 1, n_ops, remove_fraction=0.15, annotate_fraction=0.05,
        max_insert=6)[0])

    ref = jax.tree.map(jnp.asarray, DocState.empty(S_GLOBAL))
    ref = apply_ops_scan(ref, ops_all)
    ref = compact(ref, wave_min_seq(ops_all))
    assert not bool(ref.overflow)

    mesh = make_mesh(N_SHARDS, seg_shards=N_SHARDS)
    empty = DocState.empty(S_LOCAL)
    all_fields = SLOT_FIELDS + ("prop_key", "prop_val")
    state = DocState(
        **{f: jnp.tile(getattr(empty, f), (N_SHARDS,) +
                       (1,) * getattr(empty, f).ndim) for f in all_fields},
        count=jnp.zeros(N_SHARDS, jnp.int32),
        overflow=jnp.zeros(N_SHARDS, bool),
    )
    seg = P("seg")
    specs = DocState(**{f: seg for f in all_fields}, count=seg, overflow=seg)

    def body(st, ops):
        local = jax.tree.map(lambda a: a[0], st)
        out = sharded_apply_ops(local, ops, axis="seg")
        return jax.tree.map(lambda a: a[None], out)

    fn = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(specs, P()), out_specs=specs,
        check_vma=False))

    rebalances = 0
    for i in range(0, n_ops, chunk):
        state = fn(state, ops_all[i:i + chunk])
        assert not np.asarray(state.overflow).any(), f"overflow at op {i}"
        counts = np.asarray(state.count)
        if counts.max() > watermark:
            arrays = {f: np.asarray(getattr(state, f)) for f in all_fields}
            arrays, new_counts = rebalance_shards(arrays, counts)
            state = DocState(
                **{f: jnp.asarray(a) for f, a in arrays.items()},
                count=jnp.asarray(new_counts),
                overflow=jnp.zeros(N_SHARDS, bool),
            )
            rebalances += 1

    counts = np.asarray(state.count)
    ref_rows = _live_rows(
        {f: np.asarray(getattr(ref, f)) for f in SLOT_FIELDS},
        np.asarray(ref.count))
    out_rows = _live_rows(
        {f: np.asarray(getattr(state, f)) for f in SLOT_FIELDS}, counts)
    assert out_rows == ref_rows
    assert rebalances >= 1          # the stream really needed it
    assert (counts > 0).sum() > 1   # content spans shards afterwards
