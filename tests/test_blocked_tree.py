"""BlockedMergeTree: differential fuzz vs the flat oracle + scaling.

The blocked tree (mergetree/blocked.py) is the production replica path;
the flat MergeTree stays the semantics oracle. Every test here drives
BOTH from identical op streams and demands identical observable state —
text, lengths, properties, canonical snapshots — across sequencing,
concurrency, removes, annotates, markers, and window advancement.
(The multi-client conflict/reconnect farms in test_mergetree_farm.py
also exercise the blocked tree, since it is the client default.)
"""

from __future__ import annotations

import random
import time

from fluidframework_tpu.mergetree.client import MergeTreeClient
from fluidframework_tpu.protocol.messages import (
    MessageType,
    SequencedDocumentMessage,
)


class Duo:
    """One logical client as two replicas: flat oracle + blocked."""

    def __init__(self, name: str):
        self.flat = MergeTreeClient(name, blocked=False)
        self.blk = MergeTreeClient(name, blocked=True)
        self.name = name

    def both(self):
        return (self.flat, self.blk)

    def check(self, where: str) -> None:
        assert self.flat.get_length() == self.blk.get_length(), where
        assert self.flat.get_text() == self.blk.get_text(), where


def _sequencer(duos):
    """Minimal deli: assigns seqs; delivers to every duo (both replicas)."""
    state = {"seq": 0}

    def sequence(author: "Duo", flat_op, blk_op, ref_seq: int):
        state["seq"] += 1
        seq = state["seq"]
        msn = max(0, seq - 6)
        for duo in duos:
            local = duo is author
            for client, op in ((duo.flat, flat_op), (duo.blk, blk_op)):
                msg = SequencedDocumentMessage(
                    client_id=author.name, sequence_number=seq,
                    minimum_sequence_number=msn,
                    client_sequence_number=seq,
                    reference_sequence_number=ref_seq,
                    type=MessageType.OPERATION, contents=op)
                client.apply_msg(msg, local=local)
    return sequence


def test_differential_fuzz_flat_vs_blocked():
    rng = random.Random(42)
    duos = [Duo("a"), Duo("b"), Duo("c")]
    sequence = _sequencer(duos)

    for step in range(600):
        duo = rng.choice(duos)
        ref_seq = duo.flat.tree.current_seq
        assert ref_seq == duo.blk.tree.current_seq
        n = duo.flat.get_length()
        r = rng.random()
        if n > 4 and r < 0.3:
            a = rng.randrange(n - 1)
            b = a + 1 + rng.randrange(min(n - a - 1, 9) + 1)
            flat_op = duo.flat.remove_range_local(a, b)
            blk_op = duo.blk.remove_range_local(a, b)
        elif n > 2 and r < 0.42:
            a = rng.randrange(n - 1)
            b = a + 1 + rng.randrange(min(n - a - 1, 6) + 1)
            props = {"k": rng.randrange(4)}
            flat_op = duo.flat.annotate_range_local(a, b, props)
            blk_op = duo.blk.annotate_range_local(a, b, props)
        elif r < 0.47:
            pos = rng.randrange(n + 1)
            marker = {"kind": "m", "v": step}
            flat_op = duo.flat.insert_marker_local(pos, marker)
            blk_op = duo.blk.insert_marker_local(pos, marker)
        else:
            pos = rng.randrange(n + 1)
            text = "abcdefgh"[: 1 + rng.randrange(6)]
            flat_op = duo.flat.insert_text_local(pos, text)
            blk_op = duo.blk.insert_text_local(pos, text)
        duo.check(f"step {step} local")
        sequence(duo, flat_op, blk_op, ref_seq)
        for d in duos:
            d.check(f"step {step} after seq")
        if rng.random() < 0.1:
            n2 = duo.flat.get_length()
            if n2:
                p = rng.randrange(n2)
                try:
                    pf = duo.flat.get_properties_at(p)
                    pb = duo.blk.get_properties_at(p)
                    assert pf == pb, f"step {step} props@{p}"
                except IndexError:
                    pass

    # fully acked: canonical snapshots must be byte-identical
    for d in duos:
        assert not d.flat.pending and not d.blk.pending
        assert d.flat.snapshot() == d.blk.snapshot()


def test_snapshot_canonical_across_representations():
    """Snapshot bytes must not depend on in-memory segmentation: load a
    snapshot into both representations, mutate identically, re-snapshot,
    compare."""
    rng = random.Random(7)
    duo = Duo("a")
    sequence = _sequencer([duo])
    for step in range(120):
        n = duo.flat.get_length()
        if n > 3 and rng.random() < 0.3:
            a = rng.randrange(n - 1)
            f = duo.flat.remove_range_local(a, a + 1)
            b = duo.blk.remove_range_local(a, a + 1)
        else:
            pos = rng.randrange(n + 1)
            f = duo.flat.insert_text_local(pos, "xy")
            b = duo.blk.insert_text_local(pos, "xy")
        sequence(duo, f, b, duo.flat.tree.current_seq)
    snap_f = duo.flat.snapshot()
    snap_b = duo.blk.snapshot()
    assert snap_f == snap_b
    # round trip through load on both classes
    rf = MergeTreeClient.load("a", snap_f, blocked=False)
    rb = MergeTreeClient.load("a", snap_f, blocked=True)
    assert rf.get_text() == rb.get_text() == duo.flat.get_text()
    assert rf.snapshot() == rb.snapshot() == snap_f


def test_long_doc_latency_near_flat():
    """VERDICT r3 item 4 'Done' criterion: client op latency on a
    1M-char doc must not scale like the flat oracle's O(n). Measured as
    per-op time growing < 4× from a 100k-char doc to a 1M-char doc
    (the flat list grows ~10×), with wide margins for the shared host."""

    def drive(client: MergeTreeClient, upto: int, chunk: int = 32):
        rng = random.Random(1)
        seq = client.tree.current_seq
        t0 = time.perf_counter()
        ops = 0
        while client.get_length() < upto:
            pos = rng.randrange(client.get_length() + 1)
            op = client.insert_text_local(pos, "x" * chunk)
            seq += 1
            client.apply_msg(SequencedDocumentMessage(
                client_id=client.client_id, sequence_number=seq,
                minimum_sequence_number=max(0, seq - 8),
                client_sequence_number=seq,
                reference_sequence_number=seq - 1,
                type=MessageType.OPERATION, contents=op), local=True)
            ops += 1
        return (time.perf_counter() - t0) / max(ops, 1)

    c = MergeTreeClient("perf", blocked=True)
    small = drive(c, 100_000)      # per-op cost building to 100k chars
    drive(c, 900_000)              # grow (untimed)
    big = drive(c, 1_000_000)      # per-op cost at ~1M chars
    assert big < small * 4, (
        f"per-op latency grew {big / small:.1f}x from 100k to 1M chars "
        f"({small * 1e6:.0f}us -> {big * 1e6:.0f}us)")


def test_escalated_replay_scales(tmp_path):
    """VERDICT r3 item 4: an applier HOST escalation replays the doc's
    whole op log through a MergeTreeClient — with the blocked tree that
    replay is near-linear in op count, not quadratic. Replay a
    ~200k-char synthetic log through the applier's escalation path and
    bound the wall time generously."""
    from fluidframework_tpu.service.tpu_applier import TpuDocumentApplier

    rng = random.Random(3)
    log = []
    length = 0
    for seq in range(1, 6001):
        if length > 40 and rng.random() < 0.2:
            a = rng.randrange(length - 8)
            op = {"type": 1, "start": a, "end": a + 1 + rng.randrange(8)}
            length -= op["end"] - op["start"]
        else:
            op = {"type": 0, "pos": rng.randrange(length + 1), "text": "y" * 40}
            length += 40
        log.append(SequencedDocumentMessage(
            client_id="gen", sequence_number=seq,
            minimum_sequence_number=max(0, seq - 8),
            client_sequence_number=seq, reference_sequence_number=seq - 1,
            type=MessageType.OPERATION, contents=op))

    # tiny slot budget forces the first ingest to overflow → escalate →
    # full-log replay on the host replica
    applier = TpuDocumentApplier(max_docs=2, max_slots=8, ops_per_dispatch=4)
    applier.set_replay_source(lambda t, d: log)
    t0 = time.perf_counter()
    for m in log[:40]:
        applier.ingest("t", "doc", m, m.contents)
    applier.flush()
    applier.finalize()
    took = time.perf_counter() - t0
    assert applier.host_escalations == 1
    assert len(applier.get_text("t", "doc")) == length
    # ~6k-op replay of a 200k-char doc: seconds with the blocked tree,
    # minutes with the old flat-list path (O(n) zamboni per op). The
    # bound is deliberately loose for the shared bench host.
    assert took < 30.0, f"escalation replay took {took:.1f}s"


def test_differential_fuzz_tiny_blocks(monkeypatch):
    """Block-split paths under stress: with TARGET_BLOCK shrunk to 3,
    every few ops split a block — the mid-walk split bug (splitting
    while iterating blocks corrupts range accounting) only manifests
    when splits fire during remove/annotate walks, which the default
    96-segment blocks never reached in the main fuzz."""
    from fluidframework_tpu.mergetree import blocked

    monkeypatch.setattr(blocked, "TARGET_BLOCK", 3)
    rng = random.Random(99)
    duos = [Duo("a"), Duo("b")]
    sequence = _sequencer(duos)
    for step in range(500):
        duo = rng.choice(duos)
        ref_seq = duo.flat.tree.current_seq
        n = duo.flat.get_length()
        r = rng.random()
        if n > 6 and r < 0.35:
            a = rng.randrange(n - 4)
            b = a + 1 + rng.randrange(min(n - a - 1, 20) + 1)
            f = duo.flat.remove_range_local(a, b)
            k = duo.blk.remove_range_local(a, b)
        elif n > 4 and r < 0.55:
            a = rng.randrange(n - 2)
            b = a + 1 + rng.randrange(min(n - a - 1, 16) + 1)
            props = {"s": rng.randrange(3)}
            f = duo.flat.annotate_range_local(a, b, props)
            k = duo.blk.annotate_range_local(a, b, props)
        else:
            pos = rng.randrange(n + 1)
            text = "qwerty"[: 1 + rng.randrange(5)]
            f = duo.flat.insert_text_local(pos, text)
            k = duo.blk.insert_text_local(pos, text)
        duo.check(f"tiny step {step} local")
        sequence(duo, f, k, ref_seq)
        for d in duos:
            d.check(f"tiny step {step} after seq")
        if rng.random() < 0.2 and duo.flat.get_length():
            p = rng.randrange(duo.flat.get_length())
            assert duo.flat.get_properties_at(p) \
                == duo.blk.get_properties_at(p), f"tiny step {step} props"
    for d in duos:
        assert d.flat.snapshot() == d.blk.snapshot()
