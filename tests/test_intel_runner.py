"""Intelligence runner agent: scheduler-elected analysis published back
into the document (ref: intelligence-runner-agent, headless-agent).
"""

import pytest

from fluidframework_tpu.driver import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.runtime.intel_runner import IntelRunner
from fluidframework_tpu.service import LocalServer


@pytest.fixture
def server():
    return LocalServer()


@pytest.fixture
def loader(server):
    return Loader(LocalDocumentServiceFactory(server))


def test_single_runner_analyzes_and_everyone_sees(loader):
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    ds = c1.runtime.create_data_store("default")
    text = ds.create_channel("text", "shared-string")
    text.insert_text(0, "hello collaborative world")
    r1 = IntelRunner(c1)
    r2 = IntelRunner(c2)
    assert r1.is_running != r2.is_running  # exactly one works

    # analysis converged to every replica through the total order
    res2 = c2.runtime.get_data_store("default").get_channel("intel-results")
    assert res2.get("words") == 3
    assert res2.get("longest_word") == "collaborative"

    # live re-analysis on edits from ANY client
    editor = (c2 if r1.is_running else c1).runtime \
        .get_data_store("default").get_channel("text")
    editor.insert_text(0, "extraordinarily ")
    assert res2.get("words") == 4
    assert res2.get("longest_word") == "extraordinarily"


def test_runner_fails_over_on_departure(loader):
    c1 = loader.resolve("t", "doc")
    c2 = loader.resolve("t", "doc")
    ds = c1.runtime.create_data_store("default")
    text = ds.create_channel("text", "shared-string")
    text.insert_text(0, "one two")
    r1 = IntelRunner(c1)
    r2 = IntelRunner(c2)
    worker, standby = (r1, r2) if r1.is_running else (r2, r1)
    worker.container.close()
    assert standby.is_running
    s2 = standby.container.runtime.get_data_store("default") \
        .get_channel("text")
    s2.insert_text(0, "zero ")
    assert standby.results.get("words") == 3
    assert standby.results.get("analyzed_by") == standby.container.client_id
