"""Sharded ordering core: N core processes over placement leases.

Ref: memory-orderer/src/reservationManager.ts:21 (lease-based doc
ownership), remoteNode.ts:92 (routing to the owner). The deployment under
test: two core processes each claiming one doc partition (per-partition
durable logs under a shared deployment dir), a routing gateway resolving
each doc's owner from the lease directory, and clients with
auto-reconnect riding through a core's death — the killed core's
partition goes stale, the survivor claims it, resumes the partition's
pipeline from ITS OWN durable log, and the clients' reconnect lands on
the survivor with their pending edits rebased.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import pytest

from fluidframework_tpu.driver.network import NetworkDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.service.stage_runner import doc_partition

TTL = "1.5"  # fast takeover so the failover test stays quick


def wait_for(cond, timeout=30.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.02)
    return False


def _spawn(args, tmp_path):
    errf = open(os.path.join(tmp_path, f"err-{len(os.listdir(tmp_path))}.log"),
                "w")
    proc = subprocess.Popen(
        [sys.executable, "-m"] + args,
        stdout=subprocess.PIPE, stderr=errf, text=True, cwd="/root/repo")
    proc._stderr_path = errf.name
    line = proc.stdout.readline().strip()
    assert line.startswith("LISTENING"), line
    return proc, int(line.rsplit(":", 1)[1])


def _core(tmp_path, shard_dir, prefer, *extra):
    return _spawn(["fluidframework_tpu.service.front_end", "--port", "0",
                   "--shard-dir", str(shard_dir), "--shards", "2",
                   "--prefer", prefer, "--lease-ttl", TTL, *extra],
                  tmp_path)


def _docs_for_both_partitions(n_each=2):
    """Doc names covering partition 0 and 1 of the 2-shard map."""
    by_part = {0: [], 1: []}
    i = 0
    while any(len(v) < n_each for v in by_part.values()):
        d = f"sdoc{i}"
        k = doc_partition("t", d, 2)
        if len(by_part[k]) < n_each:
            by_part[k].append(d)
        i += 1
    return by_part


def test_two_cores_serve_their_partitions_and_survive_takeover(tmp_path):
    shard_dir = tmp_path / "deploy"
    procs = []
    try:
        core0, p0 = _core(tmp_path, shard_dir, "0")
        procs.append(core0)
        core1, p1 = _core(tmp_path, shard_dir, "1")
        procs.append(core1)
        gw, gport = _spawn(
            ["fluidframework_tpu.service.gateway", "--shard-dir",
             str(shard_dir), "--shards", "2"], tmp_path)
        procs.append(gw)

        by_part = _docs_for_both_partitions(n_each=1)
        d0, d1 = by_part[0][0], by_part[1][0]

        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", gport),
                        auto_reconnect=True)
        c0 = loader.resolve("t", d0)
        c1 = loader.resolve("t", d1)
        s0 = c0.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s1 = c1.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s0.insert_text(0, "part zero ")
        s1.insert_text(0, "part one ")
        assert wait_for(lambda: c0.runtime.pending.count == 0
                        and c1.runtime.pending.count == 0)

        # both cores are live and each doc is served by its partition's
        # owner — a second client on another connection converges
        c0b = loader.resolve("t", d0)
        assert wait_for(
            lambda: "default" in c0b.runtime.data_stores
            and "text" in c0b.runtime.get_data_store("default").channels
            and c0b.runtime.get_data_store("default").get_channel(
                "text").get_text() == "part zero ")

        # ---- kill core0: its partition moves to core1 ----
        os.kill(core0.pid, signal.SIGKILL)
        core0.wait(timeout=10)

        # the survivor claims partition 0 after the lease goes stale and
        # resumes its durable log; c0 auto-reconnects through the
        # gateway and keeps editing the SAME doc
        def can_edit():
            if not c0.connected:
                return False
            try:
                s0.insert_text(0, "x")
                return True
            except RuntimeError:
                return False
        assert wait_for(can_edit, timeout=30)
        s0.insert_text(len(s0.get_text()), " moved")
        assert wait_for(lambda: c0.runtime.pending.count == 0, timeout=30)

        # a FRESH client boots the moved doc from the survivor: full
        # history (pre-kill text included) came from partition 0's
        # durable log, now owned by core1
        c0c = loader.resolve("t", d0)
        assert wait_for(
            lambda: "default" in c0c.runtime.data_stores
            and "text" in c0c.runtime.get_data_store("default").channels
            and c0c.runtime.get_data_store("default").get_channel(
                "text").get_text() == s0.get_text(), timeout=30)
        assert "part zero" in s0.get_text() and "moved" in s0.get_text()

        # the other partition was never disturbed
        s1.insert_text(0, "still here ")
        assert wait_for(lambda: c1.runtime.pending.count == 0)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


def test_stalled_core_is_revoked_and_clients_move(tmp_path):
    """The two-writer hazard: a core that STALLS past the lease TTL
    (SIGSTOP — the GC-pause/CPU-starvation model) is dispossessed while
    still alive. On resume its next heartbeat fails, it revokes the
    partition (order paths refuse; sessions are dropped), and the
    clients land on the takeover owner via auto-reconnect. The stalled
    incarnation must never sequence another op into the moved log."""
    shard_dir = tmp_path / "deploy"
    procs = []
    try:
        core0, p0 = _core(tmp_path, shard_dir, "0")
        procs.append(core0)
        core1, p1 = _core(tmp_path, shard_dir, "1")
        procs.append(core1)
        gw, gport = _spawn(
            ["fluidframework_tpu.service.gateway", "--shard-dir",
             str(shard_dir), "--shards", "2"], tmp_path)
        procs.append(gw)

        d0 = _docs_for_both_partitions(n_each=1)[0][0]
        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", gport),
                        auto_reconnect=True)
        c = loader.resolve("t", d0)
        s = c.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        s.insert_text(0, "before stall ")
        assert wait_for(lambda: c.runtime.pending.count == 0)

        os.kill(core0.pid, signal.SIGSTOP)
        time.sleep(float(TTL) + 1.0)  # lease goes stale; core1 claims
        os.kill(core0.pid, signal.SIGCONT)

        # the client's session (via core0) is dropped on revocation;
        # auto-reconnect resolves the new owner and edits flow again
        def can_edit():
            if not c.connected:
                return False
            try:
                s.insert_text(0, "y")
                return True
            except RuntimeError:
                return False
        assert wait_for(can_edit, timeout=30)
        s.insert_text(len(s.get_text()), " after")
        assert wait_for(lambda: c.runtime.pending.count == 0, timeout=30)

        # a fresh boot sees a single consistent history from the
        # takeover owner's log
        c2 = loader.resolve("t", d0)
        assert wait_for(
            lambda: "default" in c2.runtime.data_stores
            and "text" in c2.runtime.get_data_store("default").channels
            and c2.runtime.get_data_store("default").get_channel(
                "text").get_text() == s.get_text(), timeout=30)
    finally:
        for p in procs:
            if p.poll() is None:
                try:
                    os.kill(p.pid, signal.SIGCONT)  # in case still stopped
                except OSError:
                    pass
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


def test_lease_registry_claim_heartbeat_takeover(tmp_path):
    from fluidframework_tpu.service.placement import PlacementDir

    pd = PlacementDir(str(tmp_path / "pl"), 2, ttl_s=0.3)
    assert pd.try_claim(0, "a", "addr-a")
    assert pd.owner_of(0) == "addr-a"
    # live lease refuses another claimant
    assert not pd.try_claim(0, "b", "addr-b")
    # heartbeat keeps it alive across the ttl
    for _ in range(3):
        time.sleep(0.15)
        assert pd.heartbeat(0, "a")
    assert pd.owner_of(0) == "addr-a"
    # stop heartbeating: stale → takeover succeeds
    time.sleep(0.4)
    assert pd.owner_of(0) is None
    assert pd.try_claim(0, "b", "addr-b")
    assert pd.owner_of(0) == "addr-b"
    # the loser notices on its next heartbeat and must stop serving
    assert not pd.heartbeat(0, "a")
    # release clears the file
    pd.release(0, "b")
    assert pd.owner_of(0) is None


def test_admin_tenant_add_secures_partitions_claimed_later(tmp_path):
    """admin tenant-add on a sharded core must secure docs in partitions
    this core claims LATER by lease takeover too — a tenant-less
    late-claimed LocalServer would silently accept unsigned connects
    (the bypass _handle_admin's docstring promises can't happen)."""
    from fluidframework_tpu import admin
    from fluidframework_tpu.service.tenants import sign_token

    shard_dir = tmp_path / "deploy"
    procs = []
    try:
        # mutating admin calls require a secret (no open bootstrap)
        core0, p0 = _core(tmp_path, shard_dir, "0",
                          "--admin-secret", "adm1n")
        procs.append(core0)
        core1, p1 = _core(tmp_path, shard_dir, "1",
                          "--admin-secret", "adm1n")
        procs.append(core1)

        # register the tenant on core1 (which owns only partition 1 now)
        assert admin.main(["--port", str(p1), "--admin-secret", "adm1n",
                           "tenant-add", "acme", "shh"]) == 0

        by_part = _docs_for_both_partitions(n_each=1)
        d0 = by_part[0][0]  # partition core1 does NOT own yet

        # CROSS-PROCESS propagation: core0 (a different OS process that
        # never saw the admin call) reloads the deployment-wide registry
        # on its next lease poll and refuses unsigned connects too
        time.sleep(1.5)  # > ttl/3 poll cadence
        unsigned0 = Loader(NetworkDocumentServiceFactory("127.0.0.1", p0))
        with pytest.raises(RuntimeError):
            unsigned0.resolve("acme", d0)

        # kill core0; core1 claims partition 0 after the TTL
        os.kill(core0.pid, signal.SIGKILL)
        core0.wait(timeout=10)
        time.sleep(float(TTL) + 1.0)

        # an unsigned connect to the late-claimed partition is refused
        unsigned = Loader(NetworkDocumentServiceFactory("127.0.0.1", p1))
        with pytest.raises(RuntimeError):
            unsigned.resolve("acme", d0)

        # a signed one works
        signed = Loader(NetworkDocumentServiceFactory(
            "127.0.0.1", p1,
            token_provider=lambda t, d: sign_token(t, d, "shh")))
        c = signed.resolve("acme", d0)
        assert c.connected
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


def test_concurrent_connects_no_duplicate_broadcasts(tmp_path):
    """Two clients of one doc connecting CONCURRENTLY through a fresh
    gateway must not double every broadcast. Regression: the gateway's
    lazy per-core dial raced — both connects opened their own backbone
    connection to the owning core, the core fan-out subscribes per
    connection, and every batch reached each client twice (real clients
    masked it by seq dedupe; load tests saw acked == 2x ops)."""
    import threading

    shard_dir = tmp_path / "deploy"
    procs = []
    try:
        procs.append(_core(tmp_path, shard_dir, "0")[0])
        procs.append(_core(tmp_path, shard_dir, "1")[0])
        gw, gport = _spawn(
            ["fluidframework_tpu.service.gateway", "--shard-dir",
             str(shard_dir), "--shards", "2"], tmp_path)
        procs.append(gw)

        d0 = _docs_for_both_partitions(n_each=1)[0][0]
        loader = Loader(NetworkDocumentServiceFactory("127.0.0.1", gport))
        results = [None, None]

        def resolve(i):
            results[i] = loader.resolve("t", d0)

        threads = [threading.Thread(target=resolve, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        c1, c2 = results
        assert c1 is not None and c2 is not None

        s1 = c1.runtime.create_data_store("default").create_channel(
            "text", "shared-string")
        for i in range(10):
            s1.insert_text(0, f"x{i}")
        assert wait_for(lambda: c1.runtime.pending.count == 0)
        assert wait_for(
            lambda: c2.runtime.data_stores and "text" in
            c2.runtime.get_data_store("default").channels and
            c2.runtime.get_data_store("default").get_channel(
                "text").get_text() == s1.get_text())
        assert c1.delta_manager.duplicates_received == 0, \
            f"c1 saw {c1.delta_manager.duplicates_received} duplicates"
        assert c2.delta_manager.duplicates_received == 0, \
            f"c2 saw {c2.delta_manager.duplicates_received} duplicates"
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
